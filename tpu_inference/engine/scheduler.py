"""Continuous-batching scheduler: the host loop that feeds the TPU.

The reference's "Scheduler" (traffic_generator/main.py:53-84) only decides
when the *client* sends requests; this is the missing server-side scheduler
(SURVEY.md §1 "no scheduler-in-the-engine sense").

Design:
- One dedicated engine thread runs the device loop (JAX dispatch blocks the
  caller, so it must stay off the asyncio event loop). The aiohttp server
  submits requests from any thread; token/finish callbacks fire on the
  engine thread and the server trampolines them onto its event loop.
- FCFS admission with **worst-case page reservation**: a request is admitted
  only when a decode slot is free and the pool can hold its prompt plus its
  full generation budget (OOM-safe admission control, SURVEY.md §5).
- Join/leave at step boundaries: at most ``max_prefills_per_step`` prefills
  per iteration (prefill is the latency-heavy graph), then one batched
  decode step for every active slot.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Deque, Dict, List, Optional

from tpu_inference import telemetry
from tpu_inference.config import class_rank
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.engine.engine import InferenceEngine, Sequence

# on_token(seq, token_id); on_finish(seq)
TokenCallback = Callable[[Sequence, int], None]
FinishCallback = Callable[[Sequence], None]


@dataclasses.dataclass
class SchedulerStats:
    """Server-side observability counters (SURVEY.md §5)."""

    steps: int = 0
    prefills: int = 0
    # P/D disaggregation (README "P/D disaggregation"): settled prefills
    # handed off to a decode worker instead of decoded locally.
    pd_handoffs: int = 0
    tokens_generated: int = 0
    tokens_prefix_cached: int = 0      # prompt tokens served from KV reuse
    requests_finished: int = 0
    requests_rejected: int = 0
    step_failures: int = 0             # prefill/decode dispatch exceptions
    preemptions: int = 0               # sequences evicted for pool pressure
    batch_occupancy_sum: float = 0.0
    peak_pages_in_use: int = 0
    # Ring of recent decode-dispatch wall times (seconds): the host-side
    # number decode_steps_per_call / pipeline depth are tuned against.
    # A fixed list + index (not a deque): the engine thread writes while
    # /metrics reads, and list item assignment is GIL-atomic whereas
    # deque iteration raises if mutated mid-scan.
    decode_call_s: List[float] = dataclasses.field(
        default_factory=lambda: [0.0] * 512)
    decode_calls: int = 0

    def record_decode_call(self, seconds: float) -> None:
        self.decode_call_s[self.decode_calls % len(self.decode_call_s)] = \
            seconds
        self.decode_calls += 1

    def _decode_call_percentiles(self, pipelined: bool) -> Optional[Dict]:
        n = min(self.decode_calls, len(self.decode_call_s))
        if n == 0:
            return None
        xs = sorted(self.decode_call_s[:n])
        pick = lambda p: xs[min(n - 1, int(p * n))]  # noqa: E731
        return {"p50": round(pick(0.50), 6), "p99": round(pick(0.99), 6),
                # With pipeline depth > 1 decode_steps_pipelined returns
                # after a NON-blocking dispatch, so these percentiles
                # measure host dispatch overhead, not decode wall time —
                # label the semantics so operators don't compare across
                # modes (ADVICE r3).
                "measures": "dispatch" if pipelined else "call"}

    def snapshot(self, engine: InferenceEngine) -> Dict:
        occ = (self.batch_occupancy_sum / self.steps) if self.steps else 0.0
        total = engine.engine_cfg.num_pages - 1
        out = {
            "steps": self.steps,
            "prefills": self.prefills,
            "tokens_generated": self.tokens_generated,
            "tokens_prefix_cached": self.tokens_prefix_cached,
            "requests_finished": self.requests_finished,
            "requests_rejected": self.requests_rejected,
            "step_failures": self.step_failures,
            # Admission & preemption (README "Admission & preemption"):
            # mode, watermark evictions, resume prefills, and how much
            # of the pool is pinned right now.
            "admission": engine.admission,
            "preemptions": engine.preemptions_total,
            "recompute_resumes": engine.resumes_total,
            # Tiered KV cache: resumes whose published pages survived
            # (HBM or host tier) and swapped in instead of recomputing.
            "swap_in_resumes": engine.swap_in_resumes,
            # KV page migration (README "Process fleet"): pages exported
            # at drain / imported from a sibling replica's drain.
            "migrate_out_pages": engine.migrate_out_pages,
            "migrate_in_pages": engine.migrate_in_pages,
            # P/D disaggregation (README "P/D disaggregation"): this
            # worker's phase role, prefills handed off to decode
            # workers, and handed-off sequences adopted here (KV
            # restored + decode resumed, zero recompute).
            "role": engine.role,
            "pd_handoffs": self.pd_handoffs,
            "pd_adoptions": engine.adoptions_in,
            "pd_adopt_fallbacks": engine.adopt_fallbacks,
            # Hybrid prefill-decode stepping (README "Scheduling"):
            # whether chunks fuse into decode dispatches, and how many
            # fused dispatches have run.
            "hybrid_prefill": engine.engine_cfg.hybrid_prefill,
            "hybrid_steps": engine.hybrid_steps_total,
            "pool_pressure": round(engine.pool_pressure, 4),
            "mean_batch_occupancy": occ,
            # Batch ladder (README "Batch ladder"): compiled rungs, the
            # rung the latest dispatch ran, the highest rung reached,
            # graph switches, current lane occupancy over the top rung,
            # and the scrape-window MFU estimate.
            "decode_ladder": list(engine.ladder),
            "decode_rung": engine.decode_rung,
            "rung_peak": engine.rung_peak,
            "rung_switches": engine.rung_switches_total,
            "lane_occupancy": round(
                sum(s is not None for s in engine.slots)
                / max(engine.ladder[-1], 1), 4),
            "mfu_estimate": engine.telemetry.mfu_estimate(),
            "kv_pages_total": total,
            "kv_pages_in_use": total - engine.allocator.num_free,
            "peak_pages_in_use": self.peak_pages_in_use,
            "model_params": engine.n_params,
            # ~2 FLOPs per param per decoded token; divide tokens/s by
            # chip peak to get MFU.
            "approx_flops_per_token": 2 * engine.n_params,
            "attn_backend": engine.attn_backend,
            "quant": engine.engine_cfg.quant,
            "kv_quant": engine.engine_cfg.kv_quant,
            "decode_pipeline_depth": engine.engine_cfg.decode_pipeline_depth,
            "decode_call_s": self._decode_call_percentiles(
                engine.engine_cfg.decode_pipeline_depth > 1),
        }
        if engine.prefix_cache is not None:
            out["prefix_cache"] = engine.prefix_cache.stats()
        if engine.spec_enabled:
            d, a = engine.spec_drafted, engine.spec_accepted
            out["speculative"] = {
                # Proposal source + configured γ (README "Speculative
                # decoding"): "ngram" = draft-free self-drafting with
                # adaptive per-sequence γ; "draft" = draft-model rounds.
                "mode": engine.spec_mode,
                "gamma": engine.engine_cfg.num_speculative_tokens,
                "drafted": d, "accepted": a,
                "acceptance_rate": (a / d) if d else 0.0,
                # ngram-mode round mix: verify rounds vs plain-decode
                # fallbacks (no lane proposed), and γ=0 throttle events.
                "rounds": engine.spec_rounds_total,
                "fallback_rounds": engine.spec_fallback_rounds,
                "throttles": engine.spec_throttles_total,
            }
        # Rolling SLO view (README "Observability": SLO gauges): exact
        # windowed TTFT/TPOT quantiles + breach counts, with the raw
        # ring values so fleet aggregation can pool EXACT quantiles
        # across replicas. Absent when TPU_INF_TELEMETRY=0.
        if engine.telemetry.slo is not None:
            out["slo"] = engine.telemetry.slo.snapshot()
        # Step-phase histograms (telemetry.py): dispatch wall, bubble,
        # queue-wait, per-request phases — cumulative buckets + estimated
        # percentiles, diffable across scrapes (benchmarks commit the
        # diff as phase_breakdown). Empty dict when TPU_INF_TELEMETRY=0.
        out["phases"] = engine.telemetry.phase_snapshot()
        return out


@dataclasses.dataclass
class _Pending:
    seq: Sequence
    on_token: TokenCallback
    on_finish: FinishCallback


class EngineScheduler:
    """Threaded continuous-batching loop around an InferenceEngine."""

    def __init__(self, engine: InferenceEngine,
                 max_prefills_per_step: Optional[int] = None,
                 idle_sleep_s: float = 0.001):
        self.engine = engine
        if max_prefills_per_step is None:
            # Default to the engine's batched-prefill width: a burst of
            # arrivals shares one [P, S] dispatch instead of queueing
            # behind P serial prefills.
            max_prefills_per_step = engine.engine_cfg.max_prefill_batch
        self.max_prefills_per_step = max_prefills_per_step
        self.idle_sleep_s = idle_sleep_s
        self.stats = SchedulerStats()
        # Read-through Prometheus counters over this scheduler's stats
        # (steps/prefills/tokens/queue depth) join the engine's registry.
        engine.telemetry.bind_scheduler(self)
        # Per-request event timeline ring (SURVEY.md §5 observability:
        # "per-request event timeline: enqueue -> schedule -> prefill ->
        # decode -> stream"). Read by /debug/requests.
        self.recent: Deque[dict] = collections.deque(maxlen=256)
        self._waiting: Deque[_Pending] = collections.deque()
        self._callbacks: Dict[int, _Pending] = {}
        # At most one multi-chunk prompt prefills incrementally (one
        # chunk per loop iteration) so decode keeps running in between.
        self._prefilling: Optional[_Pending] = None
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Supervision hooks (set by EngineGroup): fire on the engine
        # thread after every dispatch. step_inflight_since is the
        # monotonic start of the dispatch currently on device, or None —
        # the watchdog reads it from the monitor thread (GIL-atomic
        # float/None store, no lock on the hot path).
        self.step_inflight_since: Optional[float] = None
        self.on_step_ok: Optional[Callable[[], None]] = None
        self.on_step_error: Optional[Callable[[BaseException], None]] = None
        # P/D disaggregation hook (set by a prefill-role worker): called
        # on the engine thread when a sequence flagged
        # handoff_after_prefill settles its prefill (first token already
        # delivered). Returns True when the handoff was emitted — the
        # sequence then finishes locally with reason "handoff" and the
        # router resumes it on a decode worker; False keeps it decoding
        # here (mixed fallback, e.g. nothing exportable).
        self.on_prefill_handoff: Optional[Callable[[Sequence], bool]] = None

    # ---------------------------------------------- supervision plumbing

    def _note_ok(self) -> None:
        if self.on_step_ok is not None:
            self.on_step_ok()

    def _note_error(self, exc: BaseException) -> None:
        self.stats.step_failures += 1
        flight = self.engine.telemetry.flight
        if flight is not None:
            # Evidence first: dump the ledger/spans/config while the
            # failed step's records are still the newest in the ring.
            flight.capture("step_error")
        if self.on_step_error is not None:
            self.on_step_error(exc)

    @staticmethod
    def _log_step_error(phase: str, exc: BaseException,
                        seqs: List[Sequence]) -> None:
        """One structured, greppable error record per step failure
        (replaces bare traceback.print_exc): phase, exception, the
        request ids affected, and a trimmed traceback — all through the
        TPU_INF_LOG stream so operators can join failures to requests."""
        import traceback
        telemetry.log_event(
            "step_error", level="error", phase=phase, error=repr(exc),
            request_ids=[s.trace_id or str(s.request_id) for s in seqs],
            traceback="".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__, limit=8)))

    # -------------------------------------------------- submission API

    @property
    def load(self) -> int:
        """Queued + admitted (not yet finished) requests — the number the
        least-loaded router and the admission-control queue cap compare.
        _callbacks (not active_sequences) so mid-incremental-prefill
        requests still count."""
        return len(self._waiting) + len(self._callbacks)

    def submit(self, seq: Sequence, on_token: TokenCallback,
               on_finish: FinishCallback) -> None:
        """Queue a request; callbacks fire on the engine thread."""
        if len(self._waiting) >= self.engine.engine_cfg.max_queue_len:
            self.stats.requests_rejected += 1
            seq.done, seq.finish_reason = True, "queue_full"
            on_finish(seq)
            return
        if not self.engine.can_ever_admit(seq):
            # Would block the FCFS queue forever — reject immediately.
            self.stats.requests_rejected += 1
            seq.done, seq.finish_reason = True, "too_large"
            on_finish(seq)
            return
        seq.enqueue_time = time.perf_counter()
        with self._lock:
            # Class-aware queue (README "Elastic fleet"): insert before
            # any strictly-lower class so an interactive arrival jumps a
            # batch backlog; FCFS within a class. O(n) from the tail is
            # fine — the queue is bounded by max_queue_len, and the
            # common single-class workload degenerates to append().
            rank = class_rank(seq.priority_class)
            idx = len(self._waiting)
            while idx > 0 and class_rank(
                    self._waiting[idx - 1].seq.priority_class) > rank:
                idx -= 1
            self._waiting.insert(idx, _Pending(seq, on_token, on_finish))
        self._work.set()

    def kick(self) -> None:
        """Wake the engine loop from its idle wait (e.g. after queueing
        a cross-thread engine request like a migration import) so it is
        applied promptly instead of at the next 100 ms poll."""
        self._work.set()

    def cancel(self, request_id: int) -> None:
        """Cancel a queued or running request (client disconnect)."""
        with self._lock:
            for p in list(self._waiting):
                if p.seq.request_id == request_id:
                    self._waiting.remove(p)
                    p.seq.done, p.seq.finish_reason = True, "cancelled"
                    return
            p = self._callbacks.get(request_id)
            if p is not None and not p.seq.done:
                p.seq.done = True
                p.seq.finish_reason = "cancelled"

    # -------------------------------------------------- engine loop

    def start(self) -> "EngineScheduler":
        self._stop.clear()   # restartable (server app cycles in tests)
        self._thread = threading.Thread(target=self.run, name="engine-loop",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful shutdown; with drain=True, finish in-flight work
        first. Requests still unfinished at the drain deadline are
        CANCELLED with ``finish_reason="shutdown"`` — every submitted
        request gets its terminal callback, so client streams end
        cleanly instead of hanging until their own timeout."""
        if drain:
            deadline = time.monotonic() + timeout
            while (time.monotonic() < deadline
                   and (self._waiting or self._prefilling is not None
                        or self._callbacks
                        or self.engine.active_sequences())):
                time.sleep(0.01)
            self._cancel_stragglers()
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def _cancel_stragglers(self) -> None:
        """Drain deadline passed: terminate whatever is still queued or
        running with finish_reason="shutdown". Queued requests finish
        directly; engine-bound ones are marked done for the run loop to
        reap (callbacks fire on the engine thread as usual), with a
        short grace period — if the engine thread is wedged and never
        reaps them, their terminal callbacks fire from here so no
        client hangs."""
        with self._lock:
            waiting = list(self._waiting)
            self._waiting.clear()
            running = list(self._callbacks.values())
            for p in waiting:
                # Register so _finish finds (and pops) the callback.
                self._callbacks[p.seq.request_id] = p
        stragglers = waiting + running
        if not stragglers:
            return
        for p in stragglers:
            if not p.seq.done:
                p.seq.done = True
                p.seq.finish_reason = "shutdown"
                p.seq.finish_time = time.perf_counter()
        telemetry.log_event(
            "shutdown_cancel", level="warning",
            request_ids=[p.seq.trace_id or str(p.seq.request_id)
                         for p in stragglers])
        for p in waiting:
            self._finish(p.seq)
        grace = time.monotonic() + 2.0
        while self._callbacks and time.monotonic() < grace:
            self._work.set()                 # wake the idle wait
            time.sleep(0.01)
        for p in list(self._callbacks.values()):
            self._finish(p.seq)              # engine thread wedged

    def _hybrid_active(self) -> bool:
        """True when the in-progress incremental prefill should advance
        through HYBRID steps (fused into the decode dispatch) instead of
        the serial one-chunk-per-iteration path: hybrid_prefill is on,
        speculative decoding is off (the spec round has its own fused
        graph), and there are decode lanes to fuse with — with an empty
        batch the serial chunk IS the whole step, so fusing buys
        nothing and the serial path keeps its simpler bookkeeping."""
        return (self.engine.engine_cfg.hybrid_prefill
                and not self.engine.spec_enabled
                and self._prefilling is not None
                and bool(self.engine.active_sequences()))

    def _needs_chunking(self, seq: Sequence) -> bool:
        """True when the prompt spans several prefill chunks (so it goes
        through the incremental path instead of stalling the batch).
        Conservative: a prefix-cache hit could still shrink it to one.
        Resume prefills measure prompt + already-generated tokens."""
        ecfg = self.engine.engine_cfg
        cap = ecfg.chunk_tokens_cap
        base = len(self.engine._prefill_tokens(seq))
        return min(base, ecfg.max_context - 1) > cap

    def _prefill_done(self, pending: _Pending) -> None:
        """Post-prefill bookkeeping shared by the batched and incremental
        paths: counters, first-token delivery, immediate finish."""
        seq = pending.seq
        self.stats.prefills += 1
        self.stats.tokens_generated += 1
        if not seq.resume_base:
            # Resume prefills reuse pages THIS request published at its
            # own preemption — counting them would inflate the cross-
            # request prefix-cache hit rate the replay artifact reports.
            self.stats.tokens_prefix_cached += seq.cached_tokens
        tel = self.engine.telemetry
        if tel.enabled and seq.enqueue_time and not seq.resume_base:
            # Resume prefills skip the queue-wait histogram: their
            # enqueue->prefill gap spans the whole first attempt.
            tel.queue_wait_s.observe(
                max(0.0, seq.prefill_start - seq.enqueue_time))
        pending.on_token(seq, seq.generated[-1])
        if (not seq.done and seq.handoff_after_prefill
                and self.on_prefill_handoff is not None):
            # P/D disaggregation: the prefill settled — emit the live
            # handoff (KV pages + stream state) instead of decoding on
            # this worker. The first token above already streamed; the
            # router replays it in the decode worker's resume record.
            if self.on_prefill_handoff(seq):
                self.stats.pd_handoffs += 1
                seq.done = True
                seq.finish_reason = "handoff"
                seq.finish_time = time.perf_counter()
        if seq.done:
            self._finish(seq)

    def _step_incremental_prefill(self) -> None:
        """Advance the in-progress multi-chunk prefill by ONE chunk."""
        pending = self._prefilling
        seq = pending.seq
        if seq.done:                          # cancelled mid-prefill
            self._prefilling = None
            self._finish(seq)
            return
        self.step_inflight_since = time.monotonic()
        try:
            finished = self.engine.prefill_step(seq)
        except Exception as exc:  # noqa: BLE001 — keep the engine loop alive
            self._log_step_error("incremental_prefill", exc, [seq])
            self._note_error(exc)
            self._prefilling = None
            seq.done, seq.finish_reason = True, "error"
            self._finish(seq)
            return
        finally:
            self.step_inflight_since = None
        self._note_ok()
        if finished:
            self._prefilling = None
            self._prefill_done(pending)

    def _admit(self) -> int:
        """Admit up to max_prefills_per_step waiting requests in one
        batched prefill dispatch (engine.prefill_many): same-bucket
        arrivals share a [P, S] forward instead of queueing behind P
        serial prefills. Multi-chunk prompts instead start an incremental
        prefill advanced one chunk per loop, so decode interleaves —
        and short requests can still batch-admit in the same iteration
        (no head-of-line blocking behind the long prompt)."""
        admitted = 0
        if self._prefilling is not None and not self._hybrid_active():
            # Advancing an ALREADY-admitted prefill by one chunk is not a
            # new admission; only fresh requests count below. With hybrid
            # stepping active, the chunk instead rides the decode
            # dispatch later this iteration (run()'s hybrid branch).
            seq = self._prefilling.seq
            if seq.done and self.engine.pipeline_pending:
                # Cancelled with chained hybrid chunks still in flight:
                # settle their writes before the terminal path below
                # releases the pages they target.
                self._deliver(self._drain_safely())
            self._poll_hybrid_prefill()   # completed at an earlier sync?
            if self._prefilling is not None:
                if (not seq.done and seq.prefill_prompt is not None
                        and seq.prefill_offset >= len(seq.prefill_prompt)):
                    # Every chunk is already staged into in-flight hybrid
                    # calls; the final chunk's token folds at its sync —
                    # nothing to advance serially (and re-dispatching
                    # would run an empty chunk).
                    pass
                else:
                    self._step_incremental_prefill()
        batch: List[_Pending] = []
        start_chunked: Optional[_Pending] = None
        start_adopt: Optional[_Pending] = None
        reserved = 0
        with self._lock:
            engine = self.engine
            free_slots = len(engine.free_slots())
            bound = sum(s is not None for s in engine.slots)
            base_rung = engine.ladder[0]
            headroom = engine.engine_cfg.ladder_admit_headroom_pages
            while (len(batch) < self.max_prefills_per_step
                   and len(batch) < free_slots and self._waiting):
                pending = self._waiting[0]
                if pending.seq.done:          # cancelled while queued
                    self._waiting.popleft()
                    continue
                # Admission page accounting across the whole batch —
                # allocation happens later inside prefill_many, so each
                # candidate must fit on top of those already selected.
                # reserve mode charges the worst case; optimistic the
                # prompt footprint + headroom (engine._pages_for_admission).
                need = self.engine._pages_for_admission(pending.seq)
                if self.engine._free_plus_evictable() < reserved + need:
                    break
                # Batch-ladder pool-vs-lanes guard: growing the batch
                # past the BASE rung must leave at least
                # ``ladder_admit_headroom_pages`` of reclaimable slack
                # behind — extra lanes must not drain the pool to the
                # preemption watermark or force decode grants to evict
                # the whole hot set (with a host tier the evictions
                # demote and survive; the headroom keeps either tier's
                # churn off the steady-state path). Below the base
                # rung, admission keeps the legacy gate.
                if (headroom > 0
                        and bound + len(batch) + 1 > base_rung
                        and engine._free_plus_evictable()
                        < reserved + need + headroom):
                    break
                if pending.seq.adopt_kv is not None:
                    # P/D handoff adoption: no prefill dispatch — the KV
                    # restore runs solo below (before _needs_chunking,
                    # whose prompt+generated stream length would
                    # misroute an adoptable sequence into chunking).
                    if batch:
                        break     # admit the plain batch first
                    self._waiting.popleft()
                    self._callbacks[pending.seq.request_id] = pending
                    start_adopt = pending
                    reserved += need
                    break
                if self._needs_chunking(pending.seq):
                    if self._prefilling is not None:
                        break     # one incremental prefill at a time
                    if batch:
                        break     # admit the batch first; chunked head next
                    self._waiting.popleft()
                    self._callbacks[pending.seq.request_id] = pending
                    start_chunked = pending
                    reserved += need
                    break
                self._waiting.popleft()
                # Register before releasing the lock so cancel() always
                # finds the request in _waiting or _callbacks.
                self._callbacks[pending.seq.request_id] = pending
                reserved += need
                batch.append(pending)
        # Queue-wait swap-in (README "Tiered KV cache"): the head-of-
        # queue request's host-tier pages start restoring into cache-
        # owned device pages WHILE it waits, so its eventual prefill
        # begins warm instead of paying the swap inside TTFT. Engine
        # thread, bounded to the head request; no-ops without a host
        # tier (host_prefetched short-circuits repeats).
        if self.engine.host_pool is not None:
            with self._lock:
                head = self._waiting[0] if self._waiting else None
            if (head is not None and not head.seq.done
                    and head.seq.adopt_kv is None):
                # (Adoptable heads skip the prefetch: their KV arrives
                # with the handoff blob, not from the host tier.)
                try:
                    self.engine.prefetch_host_hits(head.seq)
                except Exception as exc:  # noqa: BLE001 — keep loop alive
                    self._log_step_error("host_prefetch", exc, [head.seq])
        if start_adopt is not None:
            seq = start_adopt.seq
            t_adopt = time.perf_counter()
            try:
                self.step_inflight_since = time.monotonic()
                self.engine.adopt_sequence(seq)
            except Exception as exc:  # noqa: BLE001 — keep the loop alive
                # Malformed blob / pool shortfall: fall back to an
                # ordinary recompute-resume (prompt + replayed tokens
                # re-prefill; byte-identical under greedy) by clearing
                # the adoption state and requeueing at the head.
                self._log_step_error("handoff_adopt", exc, [seq])
                self.engine.adopt_fallbacks += 1
                seq.adopt_kv = None
                with self._lock:
                    self._callbacks.pop(seq.request_id, None)
                    self._waiting.appendleft(start_adopt)
                return admitted
            finally:
                self.step_inflight_since = None
            self._note_ok()
            # Trace span: the adoption (KV restore, no prefill) stands
            # in for the prefill span on this worker — adjacent to the
            # prefill worker's handoff_export on the assembled
            # timeline. Ends exactly at first_token_time (set by
            # adopt_sequence), which is where the decode span begins,
            # so the two spans abut without overlapping.
            self.engine.telemetry.recorder.add(
                "handoff_adopt", seq.trace_id or str(seq.request_id),
                t_adopt, seq.first_token_time or time.perf_counter(),
                ctx_len=seq.ctx_len, pages=len(seq.pages))
            # No token delivery and no prefill counters: every token in
            # seq.generated was already streamed (the handoff's replay
            # record), and no prefill dispatch ran.
            if seq.done:              # cancelled while queued, raced
                self._finish(seq)
            return admitted + 1
        if start_chunked is not None:
            seq = start_chunked.seq
            try:
                self.engine.prefill_begin(seq)
            except Exception as exc:  # noqa: BLE001
                self._log_step_error("prefill_begin", exc, [seq])
                self._note_error(exc)
                seq.done, seq.finish_reason = True, "error"
                self._finish(seq)
                return admitted
            self._prefilling = start_chunked
            if self._hybrid_active():
                # Decode lanes are running: even the FIRST chunk rides
                # the fused hybrid dispatch this iteration instead of
                # stalling them here.
                return admitted + 1
            self._step_incremental_prefill()
            return admitted + 1
        if not batch:
            return admitted
        self.step_inflight_since = time.monotonic()
        try:
            self.engine.prefill_many([p.seq for p in batch])
        except Exception as exc:  # noqa: BLE001 — keep the engine loop alive
            self._log_step_error("batched_prefill", exc,
                                 [p.seq for p in batch])
            self._note_error(exc)
            # Coarse failure domain: the whole batch errors (admission
            # control makes device OOM here exceptional, not routine).
            for pending in batch:
                pending.seq.done, pending.seq.finish_reason = True, "error"
                self._finish(pending.seq)   # releases pages/slot
            return admitted
        finally:
            self.step_inflight_since = None
        self._note_ok()
        for pending in batch:
            self._prefill_done(pending)
        return admitted + len(batch)

    def _drain_safely(self) -> Dict[int, List[int]]:
        """drain_pipeline under the engine loop's keep-alive contract:
        a device error that surfaces only at sync time (async dispatch
        on real TPU) fails the affected requests with
        finish_reason="error" instead of propagating out of run() and
        killing the engine thread with work still queued."""
        engine = self.engine
        try:
            return engine.drain_pipeline()
        except Exception as exc:  # noqa: BLE001 — keep the loop alive
            victims = engine.active_sequences()
            pending = self._prefilling
            if pending is not None:
                self._prefilling = None
                if pending.seq not in victims:
                    victims = victims + [pending.seq]
            self._log_step_error("drain", exc, victims)
            self._note_error(exc)
            engine.abort_pipeline()
            engine.take_preempted()
            for s in victims:
                if not s.done:     # a cancelled seq keeps its reason
                    s.done, s.finish_reason = True, "error"
                    s.finish_time = time.perf_counter()
                self._finish(s)
            return {}

    def _poll_hybrid_prefill(self) -> None:
        """Hybrid prefills complete at SYNC time (possibly inside a
        drain): the final chunk's sampled token folds in the engine's
        _sync_oldest and ``prefill_prompt`` clears. Detect that here and
        run the shared post-prefill bookkeeping (counters, first-token
        delivery, immediate finish). A cancel that landed mid-chunks
        keeps ``prefill_prompt`` set and is handled by the run loop's
        cancel branch instead."""
        pending = self._prefilling
        if pending is None or pending.seq.prefill_prompt is not None:
            return
        self._prefilling = None
        self._prefill_done(pending)

    def _requeue_preempted(self) -> None:
        """Move sequences the engine preempted this step back to the
        HEAD of the wait queue (they were admitted before anything still
        waiting) for recompute-resume. The pending entry leaves
        _callbacks while it waits — _admit re-registers it — so ``load``
        counts the request exactly once and cancel() finds it in
        _waiting. Runs after _deliver: tokens folded before the
        preemption must reach the client first."""
        preempted = self.engine.take_preempted()
        if not preempted:
            return
        self.stats.preemptions += len(preempted)
        cancelled: List[Sequence] = []
        with self._lock:
            for seq in reversed(preempted):
                pending = self._callbacks.get(seq.request_id)
                if pending is None:
                    continue
                if seq.done:          # cancelled while being preempted
                    cancelled.append(seq)
                    continue
                del self._callbacks[seq.request_id]
                self._waiting.appendleft(pending)
        for seq in cancelled:
            self._finish(seq)

    def _finish(self, seq: Sequence) -> None:
        with self._lock:
            if seq.reaped:
                # Already finished — the shutdown force-finish path and
                # a slow (but alive) engine thread's own reap can both
                # reach here; counters/timelines must move once.
                return
            seq.reaped = True
            pending = self._callbacks.pop(seq.request_id, None)
        self.engine.release(seq)
        self.stats.requests_finished += 1
        self._observe_finish(seq)
        with self._lock:
            self.recent.append(self._timeline(seq))
        if pending is not None:
            pending.on_finish(seq)

    def _observe_finish(self, seq: Sequence) -> None:
        """Fold one finished request into the phase histograms + the
        structured log stream (telemetry.py). Phases come from the same
        timestamps as the /debug/requests timeline, so queue + prefill +
        decode sums to e2e by construction — the invariant the bench
        artifact sum-checks."""
        tel = self.engine.telemetry
        tel.request_finished(seq.finish_reason)
        fin = seq.finish_time or time.perf_counter()
        first = seq.first_token_time or fin
        start = seq.prefill_start or fin
        enq = seq.enqueue_time or start
        if tel.enabled and seq.enqueue_time:
            tel.prefill_phase_s.observe(max(0.0, first - start))
            tel.decode_phase_s.observe(max(0.0, fin - first))
            tel.ttft_s.observe(max(0.0, first - enq))
            tel.e2e_s.observe(max(0.0, fin - enq))
        self._observe_trace(seq, enq, start, first, fin)
        telemetry.log_event(
            "request_finish", level="info",
            request_id=seq.trace_id or str(seq.request_id),
            reason=seq.finish_reason, attempt=seq.attempt,
            routed_replica=seq.routed_replica,
            route_hit_pages=seq.route_hit_pages,
            route_host_hit_pages=seq.route_host_hit_pages,
            route_fabric_hit_pages=seq.route_fabric_hit_pages,
            host_restored_pages=seq.host_restored_pages,
            preemptions=seq.preemptions,
            prompt_tokens=len(seq.prompt_tokens),
            output_tokens=len(seq.generated),
            queue_wait_s=round(max(0.0, start - enq), 6),
            prefill_s=round(max(0.0, first - start), 6),
            decode_s=round(max(0.0, fin - first), 6),
            e2e_s=round(max(0.0, fin - enq), 6))

    def _observe_trace(self, seq: Sequence, enq: float, start: float,
                       first: float, fin: float) -> None:
        """Emit the request's phase spans (README "Observability" span
        schema) and fold its TTFT/TPOT into the rolling SLO window.

        Span rules: queue_wait covers enqueue -> prefill start
        (admission included); prefill covers prefill start -> first
        token (per-chunk children were recorded by the engine; an
        ADOPTED sequence's handoff_adopt span, recorded at admission,
        stands in instead); decode covers first token -> finish and is
        skipped on a "handoff" finish (no decode ran on the prefill
        worker — the handoff_export span follows instead, recorded by
        the worker's handoff hook). Sealing moves the trace into the
        recorder's recent ring, where the worker's finish event, the
        trace RPC verb, and /debug/trace read it."""
        tel = self.engine.telemetry
        rec = tel.recorder
        tid = seq.trace_id or str(seq.request_id)
        if rec.enabled and seq.enqueue_time:
            rec.add("queue_wait", tid, enq, max(enq, start),
                    admission=self.engine.admission)
            if not seq.adopted:
                rec.add("prefill", tid, start, max(start, first),
                        cached_tokens=seq.cached_tokens,
                        host_restored_pages=seq.host_restored_pages,
                        attempt=seq.attempt)
            if seq.finish_reason != "handoff":
                attrs = {"output_tokens": len(seq.generated),
                         "reason": seq.finish_reason,
                         "preemptions": seq.preemptions}
                if seq.spec_rounds:
                    attrs["spec_rounds"] = seq.spec_rounds
                    attrs["spec_accepted_tokens"] = seq.spec_accepted_toks
                rec.add("decode", tid, first, max(first, fin), **attrs)
        rec.seal(tid)
        # Rolling SLO window: TTFT only for a FRESH first attempt —
        # attempt 0 and no resume (a resume/adoption's or a failover
        # resubmission's local first-token gap is not what the client
        # waited: the first attempt's latency precedes it, and
        # understating TTFT exactly while the fleet is failing is what
        # an SLO autoscaler must not do); TPOT only where real decode
        # steps ran here.
        slo = tel.slo
        if slo is None or not seq.enqueue_time:
            return
        ttft = (max(0.0, first - enq)
                if not seq.resume_base and seq.attempt == 0
                and seq.first_token_time
                and seq.finish_reason != "error" else None)
        decoded = len(seq.generated) - seq.resume_base
        # Inter-token gaps in (first, fin]: on an ADOPTED sequence
        # `first` is the adoption instant, so all `decoded` local
        # tokens were produced after it; elsewhere the first token IS
        # `first` and only decoded-1 gaps follow.
        gaps = decoded if seq.adopted else decoded - 1
        tpot = (max(0.0, fin - first) / gaps
                if gaps > 0 and seq.finish_reason != "handoff"
                else None)
        slo.observe(ttft, tpot)

    def recent_snapshot(self, n: int) -> List[dict]:
        """Thread-safe copy of the last ``n`` request timelines (the deque
        is appended from the engine thread; iterating it unlocked from an
        HTTP handler would race a concurrent append)."""
        with self._lock:
            items = list(self.recent)
        return items[-n:]

    @staticmethod
    def _timeline(seq: Sequence) -> dict:
        """Flatten one request's lifecycle into durations (seconds)."""
        fin = seq.finish_time or time.perf_counter()
        first = seq.first_token_time or fin
        n_out = len(seq.generated)
        return {
            "request_id": seq.request_id,
            # Client-visible trace id (X-Request-Id) and failover attempt
            # count: a resubmitted span carries attempt >= 1 so operators
            # can tell a replayed request from a first try.
            "trace_id": seq.trace_id,
            "attempt": seq.attempt,
            # Routing span: the dp replica this attempt ran on and the
            # cached prefix pages the router counted on (-1/0 when the
            # request was submitted scheduler-direct, e.g. tests/bench).
            "routed_replica": seq.routed_replica,
            "route_hit_pages": seq.route_hit_pages,
            # Of route_hit_pages, the pages that were HOST-tier-warm at
            # decision time (the router's third temperature).
            "route_host_hit_pages": seq.route_host_hit_pages,
            # Pages pulled from the fleet KV fabric into this replica's
            # host tier before dispatch (the fourth temperature: warmth
            # another replica prefilled; README "KV fabric").
            "route_fabric_hit_pages": seq.route_fabric_hit_pages,
            "finished_unix": round(time.time(), 3),
            "prompt_tokens": len(seq.prompt_tokens),
            "cached_tokens": seq.cached_tokens,
            # Tiered KV cache: device pages this request's prefills
            # swapped in from the host-RAM tier (0 = every cached page
            # was already HBM-warm).
            "host_restored_pages": seq.host_restored_pages,
            "output_tokens": n_out,
            # Watermark evictions this request survived (0 = never
            # preempted); recompute-resume makes them invisible in the
            # token stream, so the span must say they happened.
            "preemptions": seq.preemptions,
            "finish_reason": seq.finish_reason,
            "queue_wait_s": round(max(0.0, (seq.prefill_start or fin)
                                      - seq.enqueue_time), 6),
            "prefill_s": round(max(0.0, first - (seq.prefill_start or first)),
                               6),
            "decode_s": round(max(0.0, fin - first), 6),
            "e2e_s": round(max(0.0, fin - (seq.enqueue_time
                                           or seq.prefill_start or fin)), 6),
            "ttft_s": round(max(0.0, first - (seq.enqueue_time or first)), 6),
            # Engine-accrued phase exposure: wall time of device
            # dispatches this request participated in, and its share of
            # host-side bubbles between decode calls.
            "dispatch_wall_s": round(seq.dispatch_wall_s, 6),
            "bubble_s": round(seq.bubble_s, 6),
            "tpot_s": round((fin - first) / (n_out - 1), 6)
            if n_out > 1 else None,
        }

    def _deliver(self, new_tokens: Dict[int, List[int]]) -> None:
        for rid, toks in new_tokens.items():
            pending = self._callbacks.get(rid)
            if pending is not None:
                for tok in toks:
                    pending.on_token(pending.seq, tok)

    def _reapable(self) -> List[Sequence]:
        """Finished sequences the run loop may finish NOW. A sequence
        still owned by the incremental prefill (cancelled mid-chunks) is
        excluded — _step_incremental_prefill finishes it, and finishing
        twice would double-count stats and duplicate /debug timelines
        (mid-prefill sequences sit in engine.slots since prefill_begin
        binds the slot)."""
        own = self._prefilling.seq if self._prefilling is not None else None
        return [s for s in self.engine.slots
                if s is not None and s.done and s is not own]

    def run(self) -> None:
        engine = self.engine
        while not self._stop.is_set():
            # Re-read each tick: the recorder may be attached after the
            # engine thread starts (worker boot binds it post-start).
            flight = engine.telemetry.flight
            if flight is not None:
                # Rolling periodic.json refresh — the capture a kill -9
                # leaves behind (no signal handler runs for SIGKILL).
                flight.maybe_periodic()
            # Cross-thread chaos page-pressure requests (/debug/chaos)
            # and migration imports (the worker's import-kv RPC) apply
            # HERE — the allocator and host tier are engine-thread only,
            # and imports must land before admission so a migrated
            # request's prefill sees them.
            engine.apply_pending_page_pressure()
            engine.apply_pending_imports()
            self._admit()
            active = engine.active_sequences()
            if not active:
                # Flush any dispatch-ahead calls, then reap
                # cancelled-in-flight sequences even when idle.
                if engine.pipeline_pending:
                    self._deliver(self._drain_safely())
                    # The drain may have synced a hybrid prefill's final
                    # chunk (e.g. every decode lane finished mid-chunks).
                    self._poll_hybrid_prefill()
                for s in self._reapable():
                    self._finish(s)
                if self._prefilling is not None:
                    continue          # next iteration runs the next chunk
                if not self._waiting:
                    self._work.clear()
                    self._work.wait(timeout=0.1)
                else:
                    time.sleep(self.idle_sleep_s)
                continue

            hybrid_pf = self._prefilling if self._hybrid_active() else None
            if hybrid_pf is not None and hybrid_pf.seq.done:
                # Cancelled mid-hybrid-prefill: settle in-flight chunk
                # writes BEFORE release frees its pages (a chained chunk
                # may still be writing them), deliver whatever the drain
                # surfaced, then run the terminal path.
                self._deliver(self._drain_safely())
                self._prefilling = None
                self._finish(hybrid_pf.seq)
                hybrid_pf = None
            try:
                # Latency mode: with a near-empty batch and nothing queued
                # or in flight, run the single-step graph so each token
                # streams out as sampled (no K-token flush bursts). Spec
                # decode has its own emission cadence; leave it alone.
                thresh = engine.engine_cfg.latency_decode_threshold
                t_call = time.perf_counter()
                self.step_inflight_since = time.monotonic()
                if hybrid_pf is not None:
                    # Hybrid step: the in-progress prefill's next chunk
                    # rides the decode dispatch instead of stalling it.
                    new_tokens = engine.hybrid_step_pipelined(hybrid_pf.seq)
                elif (0 < len(active) <= thresh and not self._waiting
                        and self._prefilling is None
                        and not engine.pipeline_pending
                        and not engine.spec_enabled):
                    new_tokens = engine.decode_steps(max_steps=1)
                else:
                    new_tokens = engine.decode_steps_pipelined()
                self.stats.record_decode_call(time.perf_counter() - t_call)
            except Exception as exc:  # noqa: BLE001 — keep the engine loop alive
                victims = list(active)
                if hybrid_pf is not None:
                    # The failed dispatch may have carried a prefill
                    # chunk whose writes are now suspect — the prefilling
                    # request fails with the batch.
                    self._prefilling = None
                    victims.append(hybrid_pf.seq)
                self._log_step_error(
                    "hybrid" if hybrid_pf is not None else "decode",
                    exc, victims)
                self._note_error(exc)
                engine.abort_pipeline()   # stale in-flight state would
                engine.take_preempted()   # poison reused slots; drop any
                for s in victims:         # mid-call preemptions too —
                    s.done, s.finish_reason = True, "error"  # they fail
                    s.finish_time = time.perf_counter()      # with the
                    self._finish(s)                          # batch
                continue
            finally:
                self.step_inflight_since = None
            self._note_ok()
            self.stats.steps += 1
            self.stats.batch_occupancy_sum += len(active)
            done_seqs = self._reapable()
            if done_seqs and engine.pipeline_pending:
                # A finish releases pages a newer in-flight call may still
                # write: drain first so release happens against settled
                # device state, and deliver the drained tokens too.
                extra = self._drain_safely()
                for rid, toks in extra.items():
                    new_tokens.setdefault(rid, []).extend(toks)
            self.stats.tokens_generated += sum(
                len(toks) for toks in new_tokens.values())
            in_use = (engine.engine_cfg.num_pages - 1) - engine.allocator.num_free
            self.stats.peak_pages_in_use = max(self.stats.peak_pages_in_use,
                                               in_use)

            self._deliver(new_tokens)
            # A hybrid prefill completes at sync time (inside the hybrid
            # step or one of the drains above) — run its post-prefill
            # bookkeeping before reaping.
            self._poll_hybrid_prefill()
            self._requeue_preempted()
            for s in self._reapable():
                self._finish(s)
