"""Speculative decoding: propose, target-model verify, exact acceptance.

Two proposal sources share the verify/accept machinery:

- **Draft model** (``spec_mode="draft"``, ``spec_round``): a small model
  scans γ sequential steps, then the target verifies all γ+1 positions
  in one forward — classic Leviathan et al. 2023.
- **N-gram self-drafting** (``spec_mode="ngram"``, ``ngram_propose`` +
  ``verify_round``): prompt-lookup decoding (Saxena 2023) — the host
  matches the sequence's last N tokens against its own prompt+generated
  history and proposes the continuation of the most recent match. No
  draft model, no draft KV pool, no extra HBM; proposals are one-hot
  distributions, so greedy acceptance degenerates to exact argmax match
  and sampled acceptance stays distribution-exact (with p one-hot at
  d_i: accept iff u < q_i(d_i); the rejection residual norm(max(q-p,0))
  is q with d_i zeroed, renormalized).

One spec round per device dispatch (BASELINE.json config 4), all static
shapes (SURVEY.md §7 hard part 6 — "variable acceptance lengths vs
static shapes"):

1. **Draft phase** — the small draft model runs ``gamma`` sequential
   decode steps under ``lax.scan``, proposing d_1..d_gamma per slot and
   recording its full probability rows (needed for exact rejection
   sampling).
2. **Verify phase** — the target model scores all gamma+1 positions in
   ONE forward: inputs [last, d_1..d_gamma] at positions ctx..ctx+gamma.
   This turns gamma sequential target steps into one MXU-friendly
   batched-matmul pass — the entire speedup.
3. **Accept phase** — standard rejection sampling (greedy degenerates to
   exact argmax match): accept d_i with prob min(1, q_i(d_i)/p_i(d_i));
   on first rejection emit a correction drawn from norm(max(q_i - p_i,
   0)); if all accepted, emit a bonus token from q_{gamma+1}.

Variable acceptance needs NO KV rollback in this engine: attention masks
the cache by per-sequence ``kv_len`` (= host ctx_len), so KV rows written
for rejected drafts are simply never attended to and get overwritten when
real tokens reach those positions. Draft and target share block tables
(the draft pool has identical page geometry), so the host tracks one
ctx per sequence for both models.

Sampling filters (temperature, top-k, top-p) are applied to BOTH the
draft and target distributions before the q/p acceptance ratio, so spec
mode samples from exactly the same filtered distribution as the plain
decode path.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class SpecRoundOut(NamedTuple):
    kv: object               # target KVPages
    draft_kv: object         # draft KVPages
    emitted: jax.Array       # [B, gamma+1] int32, -1 padded
    n_accepted: jax.Array    # [B] int32 (drafts accepted, excl. bonus)


class VerifyRoundOut(NamedTuple):
    kv: object               # target KVPages
    emitted: jax.Array       # [B, gamma+1] int32, -1 padded
    n_accepted: jax.Array    # [B] int32 (proposals accepted, excl. final)


# The n-gram proposer scans at most this many trailing history tokens —
# matching is O(scan * n) numpy per sequence per round, and a match far
# behind a multi-thousand-token context rarely predicts the present.
NGRAM_SCAN_CAP = 8192


def ngram_propose(history, gamma: int, max_n: int,
                  min_n: int = 1) -> np.ndarray:
    """Prompt-lookup proposal (Saxena 2023): match the last n tokens of
    ``history`` (n from ``max_n`` down to ``min_n``) against the rest of
    the history and return up to ``gamma`` continuation tokens of the
    MOST RECENT match (recency wins: multi-turn echo repeats what was
    just said, not what opened the conversation).

    Pure numpy on the host — this runs inside the host bubble between
    device dispatches, proposing for every running slot per round.
    Returns an int32 array of length 0..gamma (empty = no match).
    """
    hist = np.asarray(history[-NGRAM_SCAN_CAP:], dtype=np.int32)
    length = len(hist)
    if gamma <= 0 or length < min_n + 1:
        return np.empty((0,), np.int32)
    for n in range(min(max_n, length - 1), min_n - 1, -1):
        pattern = hist[-n:]
        # Candidate starts 0..length-n-1: the match must end before the
        # final position so at least one continuation token exists (the
        # suffix matching itself proposes nothing).
        windows = np.lib.stride_tricks.sliding_window_view(
            hist[:-1], n)                         # [length-n, n]
        hits = np.nonzero((windows == pattern).all(axis=1))[0]
        if hits.size:
            start = int(hits[-1]) + n             # most recent match
            # The match hypothesis is "the stream repeats with period
            # length - start"; read the full γ proposal under it, tiling
            # past the end of history (a match one period from the end —
            # the repetition-loop steady state — would otherwise truncate
            # proposals to one period). For matches deep in the history
            # this indexes the plain continuation untiled.
            period = length - start
            idx = start + np.arange(gamma) % period
            return hist[idx].astype(np.int32, copy=True)
    return np.empty((0,), np.int32)


def _probs(logits: jax.Array, temperature: jax.Array, top_p: jax.Array,
           top_k: jax.Array) -> jax.Array:
    """The engine's actual sampling distribution per row (temperature +
    top-k + top-p filtered, renormalized); temperature<=0 = one-hot
    argmax. Using the *filtered* distributions for both p and q keeps
    rejection sampling exact w.r.t. what the non-spec path samples.
    logits [B, V] f32; temperature/top_p [B]; top_k [B] int32."""
    from tpu_inference.engine.sampling import apply_filters

    greedy = jax.nn.one_hot(jnp.argmax(logits, -1), logits.shape[-1],
                            dtype=jnp.float32)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = apply_filters(logits / temp, top_k, top_p)
    soft = jax.nn.softmax(scaled, axis=-1)
    return jnp.where((temperature <= 0.0)[:, None], greedy, soft)


def _sample_from(probs: jax.Array, key: jax.Array) -> jax.Array:
    """Categorical over probability rows (works for one-hot too)."""
    return jax.random.categorical(key, jnp.log(probs + 1e-30), axis=-1
                                  ).astype(jnp.int32)


def spec_round(engine, params, draft_params, kv, draft_kv, tokens, ctx_lens,
               block_tables, cap, active, key, temperature, top_p, top_k):
    """One propose/verify/accept round. Pure function of arrays; jitted by
    the engine with both KV pools donated.

    tokens [B] last sampled (unwritten) token; ctx_lens [B]; cap [B] =
    provisioned token capacity per slot (writes at positions >= cap go to
    the trash page); active [B] bool. Returns SpecRoundOut.
    """
    from tpu_inference.engine.engine import make_paged_attn

    ecfg = engine.engine_cfg
    gamma = ecfg.num_speculative_tokens
    b = tokens.shape[0]
    vocab = engine.model_cfg.vocab_size

    # ---------------------------------------------------------- draft
    def draft_step(carry, s):
        dkv, tok, ctx = carry
        positions = jnp.minimum(ctx, ecfg.max_context - 1)[:, None]
        valid = active[:, None] & (positions < cap[:, None])
        attn = make_paged_attn(engine.draft_cfg, ecfg.page_size,
                               block_tables, positions, valid,
                               q_offset=ctx, kv_len=ctx + 1)
        hidden, dkv = engine.draft_mod.forward_hidden(
            draft_params, engine.draft_cfg, tok[:, None], positions, dkv,
            attn)
        logits = engine.draft_mod.unembed(draft_params, engine.draft_cfg,
                                          hidden[:, 0])
        p_row = _probs(logits, temperature, top_p, top_k)       # [B, V]
        d = _sample_from(p_row, jax.random.fold_in(key, s))
        return (dkv, d, ctx + 1), (d, p_row)

    # gamma+1 steps: the extra step's *write* (input d_gamma at position
    # ctx+gamma) is what matters — on a full accept that row becomes part
    # of the permanent context and no later step revisits it; skipping it
    # would leave a stale draft-KV row degrading acceptance forever after.
    # Its sampled token/probs are discarded.
    (draft_kv, _, _), (drafts, p_rows) = jax.lax.scan(
        draft_step, (draft_kv, tokens, ctx_lens),
        jnp.arange(gamma + 1, dtype=jnp.int32))
    drafts = drafts.T[:, :gamma]                              # [B, gamma]
    p_rows = p_rows.transpose(1, 0, 2)[:, :gamma]             # [B, gamma, V]

    # ---------------------------------------------------------- verify
    s_len = gamma + 1
    tokens_in = jnp.concatenate([tokens[:, None], drafts], axis=1)
    ar = jnp.arange(s_len, dtype=jnp.int32)[None, :]
    positions = jnp.minimum(ctx_lens[:, None] + ar, ecfg.max_context - 1)
    valid = active[:, None] & (positions < cap[:, None])
    attn = make_paged_attn(engine.model_cfg, ecfg.page_size, block_tables,
                           positions, valid, q_offset=ctx_lens,
                           kv_len=ctx_lens + s_len)
    hidden, kv = engine.mod.forward_hidden(params, engine.model_cfg,
                                           tokens_in, positions, kv, attn)
    logits_all = engine.mod.unembed(params, engine.model_cfg, hidden)
    q_rows = jax.vmap(_probs, in_axes=(1, None, None, None), out_axes=1)(
        logits_all, temperature, top_p, top_k)                # [B, g+1, V]

    # ---------------------------------------------------------- accept
    d_idx = drafts[..., None]                                 # [B, g, 1]
    q_d = jnp.take_along_axis(q_rows[:, :gamma], d_idx, -1)[..., 0]
    p_d = jnp.take_along_axis(p_rows, d_idx, -1)[..., 0]      # [B, g]
    u = jax.random.uniform(jax.random.fold_in(key, 7919), (b, gamma))
    accept = u < q_d / jnp.maximum(p_d, 1e-30)                # [B, g]
    acc_prefix = jnp.cumprod(accept.astype(jnp.int32), axis=1)
    n_acc = jnp.sum(acc_prefix, axis=1)                       # [B] 0..g

    # Correction dist at the first rejected row; bonus row when n_acc==g.
    row = jax.vmap(lambda q, i: q[i])(q_rows, n_acc)          # [B, V]
    p_row_at = jax.vmap(lambda p, i: p[jnp.minimum(i, gamma - 1)])(
        p_rows, n_acc)
    resid = jnp.maximum(row - p_row_at, 0.0)
    resid_sum = jnp.sum(resid, axis=-1, keepdims=True)
    # Degenerate residual (q==p, e.g. both greedy-one-hot on the same
    # token can't be rejected, but guard anyway) falls back to q.
    corr_dist = jnp.where(resid_sum > 1e-12, resid / (resid_sum + 1e-30),
                          row)
    final_dist = jnp.where((n_acc == gamma)[:, None], row, corr_dist)
    final_tok = _sample_from(final_dist, jax.random.fold_in(key, 104729))

    # emitted[b] = accepted drafts ++ [final_tok] ++ -1 padding.
    slot_idx = jnp.arange(s_len, dtype=jnp.int32)[None, :]    # [1, g+1]
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)
    emitted = jnp.where(slot_idx < n_acc[:, None], drafts_pad, -1)
    emitted = jnp.where(slot_idx == n_acc[:, None], final_tok[:, None],
                        emitted)
    emitted = jnp.where(active[:, None], emitted, -1)
    return SpecRoundOut(kv=kv, draft_kv=draft_kv, emitted=emitted,
                        n_accepted=jnp.where(active, n_acc, 0))


def verify_round(engine, params, kv, tokens, ctx_lens, block_tables, cap,
                 active, drafts, n_prop, key, temperature, top_p, top_k,
                 rpen, rlast, window):
    """Verify-only spec round for host-proposed (one-hot) drafts — the
    ``spec_mode="ngram"`` device graph. Pure function of arrays; jitted
    by the engine with the KV pool donated, compiled once per ladder
    rung (the batch dim B is the rung; γ+1 is static).

    ``drafts`` [B, gamma] int32 host proposals, of which only the first
    ``n_prop[b]`` (0..gamma) are real — the rest are padding and forced
    rejections, so per-sequence adaptive γ lives INSIDE one compiled
    shape instead of multiplying graphs. Proposal probs are one-hot, so:
    greedy acceptance is exact argmax match (q one-hot at argmax: accept
    iff d_i == argmax); sampled acceptance is exact rejection sampling
    (accept with prob q_i(d_i); the correction draws from
    norm(max(q_i - onehot(d_i), 0)) = q_i with d_i zeroed).

    Unlike the draft-model round, the repetition penalty COMPOSES here:
    position i's target distribution is penalized against the window
    rolled forward with d_1..d_i — exactly the window the sequential
    plain-decode path would hold if those drafts were its samples, and
    position i's row is only ever consumed when they were all accepted.

    Same no-rollback contract as ``spec_round``: rejected/padded rows
    are dead KV (kv_len masking) and get overwritten by real tokens.
    Returns VerifyRoundOut; with n_prop==0 a round degenerates to one
    plain decode step (one forward, one emitted token).
    """
    from tpu_inference.engine.engine import make_paged_attn
    from tpu_inference.engine.sampling import (apply_repeat_penalty,
                                               roll_window)

    ecfg = engine.engine_cfg
    # Active γ comes from the PROPOSAL width, not the config: the engine
    # compiles this graph at (every ladder rung) x (probe width 1, full
    # γ), so throttled lanes re-probe on a near-plain-cost narrow round
    # instead of paying the full verify width to learn they still don't
    # echo.
    gamma = drafts.shape[1]
    s_len = gamma + 1
    b = tokens.shape[0]
    vocab = engine.model_cfg.vocab_size

    # ------------------------------------------------------- verify
    tokens_in = jnp.concatenate([tokens[:, None], drafts], axis=1)
    ar = jnp.arange(s_len, dtype=jnp.int32)[None, :]
    positions = jnp.minimum(ctx_lens[:, None] + ar, ecfg.max_context - 1)
    valid = active[:, None] & (positions < cap[:, None])
    attn = make_paged_attn(engine.model_cfg, ecfg.page_size, block_tables,
                           positions, valid, q_offset=ctx_lens,
                           kv_len=ctx_lens + s_len,
                           attn_backend=engine.attn_backend,
                           mesh=engine.mesh)
    hidden, kv = engine.mod.forward_hidden(params, engine.model_cfg,
                                           tokens_in, positions, kv, attn)
    logits_all = engine.mod.unembed(params, engine.model_cfg, hidden)

    # Per-position penalty windows: window_i = base window rolled with
    # d_1..d_i (the state sequential decode would hold if those drafts
    # were its own samples — position i's row only matters when they
    # were all accepted, so this is exact, not approximate).
    def _roll(win, d):
        win = roll_window(win, d, active)
        return win, win
    _, rolled = jax.lax.scan(_roll, window, drafts.T)     # [g, B, W]
    win_seq = jnp.concatenate([window[None], rolled], axis=0)

    def _pen(logits_i, win_i):
        return apply_repeat_penalty(logits_i, win_i, rpen, rlast)
    logits_all = jax.vmap(_pen, in_axes=(1, 0), out_axes=1)(
        logits_all, win_seq)

    # All-greedy rounds (the byte-identity serving hot path) skip the
    # per-position [B, V] sort+softmax of the filtered branch entirely —
    # same lax.cond fast path sampling.sample takes. jnp.where alone
    # would still compute both branches.
    def _greedy_rows(_):
        return jax.nn.one_hot(jnp.argmax(logits_all, -1), vocab,
                              dtype=jnp.float32)

    def _filtered_rows(_):
        return jax.vmap(_probs, in_axes=(1, None, None, None),
                        out_axes=1)(logits_all, temperature, top_p,
                                    top_k)
    q_rows = jax.lax.cond(jnp.all(temperature <= 0.0), _greedy_rows,
                          _filtered_rows, None)           # [B, g+1, V]

    # ------------------------------------------------------- accept
    d_idx = drafts[..., None]                             # [B, g, 1]
    q_d = jnp.take_along_axis(q_rows[:, :gamma], d_idx, -1)[..., 0]
    u = jax.random.uniform(jax.random.fold_in(key, 7919), (b, gamma))
    slot_idx = jnp.arange(gamma, dtype=jnp.int32)[None, :]
    proposed = slot_idx < n_prop[:, None]
    # One-hot proposal: p_i(d_i) == 1, so the ratio test is u < q_i(d_i)
    # (greedy: q one-hot -> deterministic argmax match). Padded slots
    # force-reject so n_acc <= n_prop.
    accept = proposed & (u < q_d)                         # [B, g]
    acc_prefix = jnp.cumprod(accept.astype(jnp.int32), axis=1)
    n_acc = jnp.sum(acc_prefix, axis=1)                   # [B] 0..n_prop

    # Final token: at the first rejected PROPOSED position, draw from
    # the residual q with the rejected draft zeroed; with every proposal
    # accepted (n_acc == n_prop, padding included), the row at n_prop is
    # the model's genuine next-token distribution — the bonus draw.
    row = jax.vmap(lambda q, i: q[i])(q_rows, n_acc)      # [B, V]
    d_at = jax.vmap(lambda d, i: d[jnp.minimum(i, gamma - 1)])(
        drafts, n_acc)
    resid = jnp.maximum(row - jax.nn.one_hot(d_at, vocab,
                                             dtype=row.dtype), 0.0)
    resid_sum = jnp.sum(resid, axis=-1, keepdims=True)
    # Degenerate residual (q(d) ~ 1: the proposal is essentially surely
    # accepted, so this branch is unreachable in exact arithmetic —
    # guard anyway) falls back to q.
    corr_dist = jnp.where(resid_sum > 1e-12, resid / (resid_sum + 1e-30),
                          row)
    rejected_mid = n_acc < n_prop
    final_dist = jnp.where(rejected_mid[:, None], corr_dist, row)
    final_tok = _sample_from(final_dist, jax.random.fold_in(key, 104729))

    slot_all = jnp.arange(s_len, dtype=jnp.int32)[None, :]
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)
    emitted = jnp.where(slot_all < n_acc[:, None], drafts_pad, -1)
    emitted = jnp.where(slot_all == n_acc[:, None], final_tok[:, None],
                        emitted)
    emitted = jnp.where(active[:, None], emitted, -1)
    return VerifyRoundOut(kv=kv, emitted=emitted,
                          n_accepted=jnp.where(active, n_acc, 0))
