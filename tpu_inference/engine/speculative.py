"""Speculative decoding: draft-model propose, target-model verify.

One spec round per device dispatch (BASELINE.json config 4), all static
shapes (SURVEY.md §7 hard part 6 — "variable acceptance lengths vs
static shapes"):

1. **Draft phase** — the small draft model runs ``gamma`` sequential
   decode steps under ``lax.scan``, proposing d_1..d_gamma per slot and
   recording its full probability rows (needed for exact rejection
   sampling).
2. **Verify phase** — the target model scores all gamma+1 positions in
   ONE forward: inputs [last, d_1..d_gamma] at positions ctx..ctx+gamma.
   This turns gamma sequential target steps into one MXU-friendly
   batched-matmul pass — the entire speedup.
3. **Accept phase** — standard rejection sampling (greedy degenerates to
   exact argmax match): accept d_i with prob min(1, q_i(d_i)/p_i(d_i));
   on first rejection emit a correction drawn from norm(max(q_i - p_i,
   0)); if all accepted, emit a bonus token from q_{gamma+1}.

Variable acceptance needs NO KV rollback in this engine: attention masks
the cache by per-sequence ``kv_len`` (= host ctx_len), so KV rows written
for rejected drafts are simply never attended to and get overwritten when
real tokens reach those positions. Draft and target share block tables
(the draft pool has identical page geometry), so the host tracks one
ctx per sequence for both models.

Sampling filters (temperature, top-k, top-p) are applied to BOTH the
draft and target distributions before the q/p acceptance ratio, so spec
mode samples from exactly the same filtered distribution as the plain
decode path.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SpecRoundOut(NamedTuple):
    kv: object               # target KVPages
    draft_kv: object         # draft KVPages
    emitted: jax.Array       # [B, gamma+1] int32, -1 padded
    n_accepted: jax.Array    # [B] int32 (drafts accepted, excl. bonus)


def _probs(logits: jax.Array, temperature: jax.Array, top_p: jax.Array,
           top_k: jax.Array) -> jax.Array:
    """The engine's actual sampling distribution per row (temperature +
    top-k + top-p filtered, renormalized); temperature<=0 = one-hot
    argmax. Using the *filtered* distributions for both p and q keeps
    rejection sampling exact w.r.t. what the non-spec path samples.
    logits [B, V] f32; temperature/top_p [B]; top_k [B] int32."""
    from tpu_inference.engine.sampling import apply_filters

    greedy = jax.nn.one_hot(jnp.argmax(logits, -1), logits.shape[-1],
                            dtype=jnp.float32)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = apply_filters(logits / temp, top_k, top_p)
    soft = jax.nn.softmax(scaled, axis=-1)
    return jnp.where((temperature <= 0.0)[:, None], greedy, soft)


def _sample_from(probs: jax.Array, key: jax.Array) -> jax.Array:
    """Categorical over probability rows (works for one-hot too)."""
    return jax.random.categorical(key, jnp.log(probs + 1e-30), axis=-1
                                  ).astype(jnp.int32)


def spec_round(engine, params, draft_params, kv, draft_kv, tokens, ctx_lens,
               block_tables, cap, active, key, temperature, top_p, top_k):
    """One propose/verify/accept round. Pure function of arrays; jitted by
    the engine with both KV pools donated.

    tokens [B] last sampled (unwritten) token; ctx_lens [B]; cap [B] =
    provisioned token capacity per slot (writes at positions >= cap go to
    the trash page); active [B] bool. Returns SpecRoundOut.
    """
    from tpu_inference.engine.engine import make_paged_attn

    ecfg = engine.engine_cfg
    gamma = ecfg.num_speculative_tokens
    b = tokens.shape[0]
    vocab = engine.model_cfg.vocab_size

    # ---------------------------------------------------------- draft
    def draft_step(carry, s):
        dkv, tok, ctx = carry
        positions = jnp.minimum(ctx, ecfg.max_context - 1)[:, None]
        valid = active[:, None] & (positions < cap[:, None])
        attn = make_paged_attn(engine.draft_cfg, ecfg.page_size,
                               block_tables, positions, valid,
                               q_offset=ctx, kv_len=ctx + 1)
        hidden, dkv = engine.draft_mod.forward_hidden(
            draft_params, engine.draft_cfg, tok[:, None], positions, dkv,
            attn)
        logits = engine.draft_mod.unembed(draft_params, engine.draft_cfg,
                                          hidden[:, 0])
        p_row = _probs(logits, temperature, top_p, top_k)       # [B, V]
        d = _sample_from(p_row, jax.random.fold_in(key, s))
        return (dkv, d, ctx + 1), (d, p_row)

    # gamma+1 steps: the extra step's *write* (input d_gamma at position
    # ctx+gamma) is what matters — on a full accept that row becomes part
    # of the permanent context and no later step revisits it; skipping it
    # would leave a stale draft-KV row degrading acceptance forever after.
    # Its sampled token/probs are discarded.
    (draft_kv, _, _), (drafts, p_rows) = jax.lax.scan(
        draft_step, (draft_kv, tokens, ctx_lens),
        jnp.arange(gamma + 1, dtype=jnp.int32))
    drafts = drafts.T[:, :gamma]                              # [B, gamma]
    p_rows = p_rows.transpose(1, 0, 2)[:, :gamma]             # [B, gamma, V]

    # ---------------------------------------------------------- verify
    s_len = gamma + 1
    tokens_in = jnp.concatenate([tokens[:, None], drafts], axis=1)
    ar = jnp.arange(s_len, dtype=jnp.int32)[None, :]
    positions = jnp.minimum(ctx_lens[:, None] + ar, ecfg.max_context - 1)
    valid = active[:, None] & (positions < cap[:, None])
    attn = make_paged_attn(engine.model_cfg, ecfg.page_size, block_tables,
                           positions, valid, q_offset=ctx_lens,
                           kv_len=ctx_lens + s_len)
    hidden, kv = engine.mod.forward_hidden(params, engine.model_cfg,
                                           tokens_in, positions, kv, attn)
    logits_all = engine.mod.unembed(params, engine.model_cfg, hidden)
    q_rows = jax.vmap(_probs, in_axes=(1, None, None, None), out_axes=1)(
        logits_all, temperature, top_p, top_k)                # [B, g+1, V]

    # ---------------------------------------------------------- accept
    d_idx = drafts[..., None]                                 # [B, g, 1]
    q_d = jnp.take_along_axis(q_rows[:, :gamma], d_idx, -1)[..., 0]
    p_d = jnp.take_along_axis(p_rows, d_idx, -1)[..., 0]      # [B, g]
    u = jax.random.uniform(jax.random.fold_in(key, 7919), (b, gamma))
    accept = u < q_d / jnp.maximum(p_d, 1e-30)                # [B, g]
    acc_prefix = jnp.cumprod(accept.astype(jnp.int32), axis=1)
    n_acc = jnp.sum(acc_prefix, axis=1)                       # [B] 0..g

    # Correction dist at the first rejected row; bonus row when n_acc==g.
    row = jax.vmap(lambda q, i: q[i])(q_rows, n_acc)          # [B, V]
    p_row_at = jax.vmap(lambda p, i: p[jnp.minimum(i, gamma - 1)])(
        p_rows, n_acc)
    resid = jnp.maximum(row - p_row_at, 0.0)
    resid_sum = jnp.sum(resid, axis=-1, keepdims=True)
    # Degenerate residual (q==p, e.g. both greedy-one-hot on the same
    # token can't be rejected, but guard anyway) falls back to q.
    corr_dist = jnp.where(resid_sum > 1e-12, resid / (resid_sum + 1e-30),
                          row)
    final_dist = jnp.where((n_acc == gamma)[:, None], row, corr_dist)
    final_tok = _sample_from(final_dist, jax.random.fold_in(key, 104729))

    # emitted[b] = accepted drafts ++ [final_tok] ++ -1 padding.
    slot_idx = jnp.arange(s_len, dtype=jnp.int32)[None, :]    # [1, g+1]
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)
    emitted = jnp.where(slot_idx < n_acc[:, None], drafts_pad, -1)
    emitted = jnp.where(slot_idx == n_acc[:, None], final_tok[:, None],
                        emitted)
    emitted = jnp.where(active[:, None], emitted, -1)
    return SpecRoundOut(kv=kv, draft_kv=draft_kv, emitted=emitted,
                        n_accepted=jnp.where(active, n_acc, 0))
