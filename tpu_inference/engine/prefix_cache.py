"""Prefix cache: shared-prefix KV page reuse across requests.

Multi-turn conversations resend the whole history each turn (the Ollama
protocol the reference harness speaks is stateless — SURVEY.md §2c), so
consecutive requests share long token prefixes. Pages holding those
prefixes are immutable once full (decode appends only ever write the
*current* page), which makes page-granular sharing safe with plain
refcounts — no copy-on-write needed for inference (engine/kv_cache.py).

Design:
- Key = rolling blake2b chain hash over page-sized token blocks, so a hit
  guarantees the *entire* prefix up to that page matches, not just that
  one block.
- The cache holds its own allocator reference on every inserted page
  (PageAllocator.share); a sequence releasing its pages never invalidates
  a cached copy, and eviction is just dropping the cache's reference.
- **Two tiers** (README "Tiered KV cache"): LRU eviction of the HBM
  table, triggered by the engine when the free list runs dry, DEMOTES a
  page to a host-RAM tier (device->host copy, then the device page is
  freed) when a ``HostPagePool`` is attached — the KV survives pool
  churn and promotes back into a freshly allocated device page when a
  returning prompt (or a preempted sequence's swap-in-resume) needs it.
  The host tier has its own LRU; entries are dropped for good only when
  host capacity runs dry (second-tier evict) or on ``clear()``. With no
  host pool attached, eviction degrades to the classic free-on-evict.
- Victim selection is O(evicted): the cache keeps an evictable-ordered
  table (digests whose page it alone references, in became-evictable
  order — maintained via the allocator's ``on_evictable`` hook) instead
  of scanning the whole, mostly share-pinned, LRU table per evict call.
- Tier invariant: a digest lives in the HBM table OR the host table,
  never both (promote and publish both drop the host copy).
- KV content depends only on absolute positions + token ids (RoPE is
  absolute), so equal prefixes produce bit-identical pages; sharing is
  exact, not approximate — and a demoted page's bytes round-trip the
  host tier untouched (quantized layouts copy as stored).

Hit/miss/peek accounting goes through telemetry ``Counter`` objects
(per-tier labels once an engine binds its registry) — the same objects
/metrics scrapes, so there is ONE set of numbers instead of ad-hoc ints
shadowing the exported ones. The concurrency stance is telemetry.py's:
``inc`` is a GIL-serialized read-modify-write whose rare torn update
under thread races is tolerated, not prevented.

The reference has no KV reuse of any kind (its server is external);
BASELINE.json config 3 ("multi-turn conversations.json") is the
acceptance target for this component.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_inference import telemetry
from tpu_inference.engine.kv_cache import (
    HostKVPage,
    HostPagePool,
    PageAllocator,
)


def _chain_hashes(tokens: Sequence[int], page_size: int) -> List[bytes]:
    """One digest per *full* page, each folding in all prior pages.

    Runs on every admit AND every router peek (dp replicas score each
    incoming prompt), so the block encoding is fixed-width packed int32
    via numpy — one bulk tobytes() per page instead of a per-token
    str/encode/join. Fixed width keeps the encoding injective (token
    ids are non-negative and < 2**31 for any real vocab), so distinct
    token blocks can never serialize to the same bytes.
    """
    return extend_chain_hashes(tokens, page_size, [])


def extend_chain_hashes(tokens: Sequence[int], page_size: int,
                        prefix_digests: Sequence[bytes]) -> List[bytes]:
    """Chain digests for every full page of ``tokens``, reusing
    ``prefix_digests`` (digests of the leading pages, e.g. the ones the
    router already computed for this request) and hashing only the
    remainder — the plumb that keeps a routed request at ONE hash pass
    over its prompt instead of three (route, admit, publish)."""
    n_pages = len(tokens) // page_size
    if n_pages == 0:
        return []
    start = min(len(prefix_digests), n_pages)
    out: List[bytes] = list(prefix_digests[:start])
    if start == n_pages:
        return out
    blocks = np.asarray(tokens[start * page_size:n_pages * page_size],
                        dtype=np.int32).reshape(n_pages - start, page_size)
    h = out[-1] if out else b""
    for i in range(n_pages - start):
        d = hashlib.blake2b(digest_size=16)
        d.update(h)
        d.update(blocks[i].tobytes())
        h = d.digest()
        out.append(h)
    return out


class PrefixCache:
    """Maps prefix chain-hashes to physical KV pages (HBM tier) and
    host-RAM page copies (host tier)."""

    def __init__(self, allocator: PageAllocator, page_size: int,
                 host_pool: Optional[HostPagePool] = None,
                 offload_fn=None):
        self.allocator = allocator
        self.page_size = page_size
        # digest -> page id, LRU order (oldest first).
        self._table: "OrderedDict[bytes, int]" = OrderedDict()
        # Host tier: digest -> HostKVPage, LRU order (oldest first).
        # ``host_pool`` does the capacity accounting; ``offload_fn``
        # (engine-provided: pages -> List[HostKVPage]) performs the
        # device->host copy at demote time.
        self._host: "OrderedDict[bytes, HostKVPage]" = OrderedDict()
        self.host_pool = host_pool
        self._offload_fn = offload_fn
        # Evictable-ordered view of _table: digests whose page the cache
        # alone references, oldest-released first. Maintained through
        # the allocator's evictability hook so evict() is O(evicted).
        self._evict_order: "OrderedDict[bytes, None]" = OrderedDict()
        self._page_digest: Dict[int, bytes] = {}
        allocator.on_evictable = self._note_evictable
        # Accounting via telemetry counters (standalone objects until an
        # engine binds its registry — see bind_telemetry): hit/miss per
        # lookup, split by the tier that served it; peeks from router
        # threads. These are exactly what /metrics scrapes, so there is
        # no second set of ad-hoc ints to race with.
        self.hits_hbm = telemetry.Counter("tpu_inf_prefix_cache_hits_total")
        self.hits_host = telemetry.Counter("tpu_inf_prefix_cache_hits_total")
        self.misses = telemetry.Counter("tpu_inf_prefix_cache_misses_total")
        self.peeks = telemetry.Counter("tpu_inf_prefix_cache_peeks_total")

    def bind_telemetry(self, tel) -> None:
        """Swap the standalone counters for registry-backed ones (tier
        labels included) so /metrics exposes them per replica."""
        if not getattr(tel, "enabled", False):
            return
        r = tel.registry
        self.hits_hbm = r.counter(
            "tpu_inf_prefix_cache_hits_total",
            "Prefix-cache lookups served (by tier that contributed pages)",
            tier="hbm")
        self.hits_host = r.counter(
            "tpu_inf_prefix_cache_hits_total",
            "Prefix-cache lookups served (by tier that contributed pages)",
            tier="host")
        self.misses = r.counter(
            "tpu_inf_prefix_cache_misses_total",
            "Prefix-cache lookups with no cached prefix in either tier")
        self.peeks = r.counter(
            "tpu_inf_prefix_cache_peeks_total",
            "Side-effect-free prefix probes (router scoring)")

    def __len__(self) -> int:
        return len(self._table)

    @property
    def evictable(self) -> int:
        """Pages reclaimable right now (cache holds the only reference).
        O(1): the allocator maintains the counter on the engine thread,
        so metrics scrapes from other threads read a plain int."""
        return self.allocator.evictable_count

    def _note_evictable(self, page: int, up: bool) -> None:
        """Allocator evictability hook (engine thread): mirror the flip
        into the evictable-ordered digest table."""
        digest = self._page_digest.get(page)
        if digest is None:
            return
        if up:
            self._evict_order[digest] = None
            self._evict_order.move_to_end(digest)
        else:
            self._evict_order.pop(digest, None)

    # ------------------------------------------------------------- peek

    def peek(self, tokens: Sequence[int],
             max_tokens: Optional[int] = None) -> int:
        """Length (in full pages, across BOTH tiers) of the longest
        cached prefix of ``tokens`` — **side-effect-free**: no LRU
        promotion, no refcount share, no hit/miss accounting. The dp
        router calls this from HTTP threads to score replicas, so it
        must neither perturb the engine-thread-owned eviction order nor
        pin pages a routing decision merely *considered*. Plain dict
        gets are GIL-atomic, so no lock is needed; a concurrent
        insert/evict can make the answer stale by a page or two, which
        the router tolerates (the prefill re-checks with ``lookup`` and
        simply recomputes the difference).
        """
        limit = len(tokens) if max_tokens is None else max_tokens
        digests = _chain_hashes(tokens, self.page_size)
        return self.peek_digests(digests[:limit // self.page_size])

    def peek_digests(self, digests: Sequence[bytes]) -> int:
        """peek() over pre-computed chain digests (both tiers summed).
        The dp router hashes each prompt ONCE and probes every replica's
        table with the same digest list (all replicas share page_size),
        so scoring costs one hash pass per request, not one per replica.
        Same side-effect-free contract as peek()."""
        hbm, host = self.peek_digests_tiered(digests)
        return hbm + host

    def peek_digests_tiered(self, digests: Sequence[bytes]
                            ) -> Tuple[int, int]:
        """Tier-aware peek: (hbm_hit_pages, host_hit_pages) over the
        longest contiguous cached prefix — the router's three-
        temperature signal (HBM-warm > host-warm > cold). Side-effect-
        free; safe from any thread."""
        hbm = host = 0
        for digest in digests:
            if digest in self._table:
                hbm += 1
            elif digest in self._host:
                host += 1
            else:
                break
        self.peeks.inc()
        return hbm, host

    # ------------------------------------------------------------- lookup

    def lookup(self, tokens: Sequence[int],
               max_tokens: Optional[int] = None,
               digests: Optional[Sequence[bytes]] = None
               ) -> Tuple[List[Optional[int]],
                          List[Tuple[int, bytes, HostKVPage]], int]:
        """Longest cached prefix of ``tokens`` across both tiers.

        Returns ``(hbm_pages, host_entries, n_cached_tokens)``:
        ``hbm_pages[i]`` is the device page holding matched page ``i``
        (fresh allocator reference — the caller owns it and must free
        it) or ``None`` where the match was served by the host tier;
        ``host_entries`` lists ``(i, digest, HostKVPage)`` for those
        ``None`` slots. Host entries leave the host tier here — the
        caller restores them into freshly allocated device pages and
        publishes them back via :meth:`promote` (or returns them via
        :meth:`readmit_host` if the restore cannot allocate).

        ``max_tokens`` caps the match (the engine always re-computes at
        least the prompt's final token to get logits). ``digests``
        supplies precomputed chain hashes (router plumb) so the prompt
        is hashed once per request, not once per call.
        """
        limit = len(tokens) if max_tokens is None else max_tokens
        if digests is None:
            digests = _chain_hashes(tokens, self.page_size)
        pages: List[Optional[int]] = []
        host_entries: List[Tuple[int, bytes, HostKVPage]] = []
        for i, digest in enumerate(digests):
            if (i + 1) * self.page_size > limit:
                break
            page = self._table.get(digest)
            if page is not None:
                self._table.move_to_end(digest)
                pages.append(page)
                continue
            entry = self._host.pop(digest, None)
            if entry is None:
                break
            self.host_pool.note_restore(entry.nbytes)
            host_entries.append((i, digest, entry))
            pages.append(None)
        for p in pages:
            if p is not None:
                self.allocator.share(p)
        if pages:
            if any(p is not None for p in pages):
                self.hits_hbm.inc()
            if host_entries:
                self.hits_host.inc()
        else:
            self.misses.inc()
        return pages, host_entries, len(pages) * self.page_size

    def promote(self, digest: bytes, page: int) -> None:
        """Publish a just-restored host-tier page into the HBM table
        (the caller owns ``page``; the cache takes its own reference).
        The host copy was already removed by lookup, preserving the
        one-tier-per-digest invariant."""
        if digest in self._table:
            return
        self._table[digest] = self.allocator.share(page)
        self._page_digest[page] = digest
        self.allocator.mark_cached(page)

    def adopt(self, digest: bytes, page: int) -> None:
        """Queue-wait prefetch: take ownership of a freshly allocated
        ``page`` (refcount 1, transferred from the caller) holding a
        just-restored host entry's bytes, and publish it in the HBM
        tier — the upcoming admission then sees a plain HBM hit."""
        assert digest not in self._table
        self._table[digest] = page
        self._page_digest[page] = digest
        self.allocator.mark_cached(page)   # refs==1 -> evictable

    def take_host_matches(self, digests: Sequence[bytes], max_pages: int
                          ) -> List[Tuple[bytes, HostKVPage]]:
        """Pop the host-tier entries inside the longest contiguous
        cached prefix of ``digests`` (HBM hits are skipped over, not
        touched). Used by the queue-wait swap-in: the caller restores
        the entries and hands the pages back via :meth:`adopt` (or
        :meth:`readmit_host` on allocation failure)."""
        out: List[Tuple[bytes, HostKVPage]] = []
        for i, digest in enumerate(digests):
            if i >= max_pages:
                break
            if digest in self._table:
                continue
            entry = self._host.pop(digest, None)
            if entry is None:
                break
            self.host_pool.note_restore(entry.nbytes)
            out.append((digest, entry))
        return out

    def readmit_host(self, taken: Sequence[Tuple[bytes, HostKVPage]]
                     ) -> None:
        """Return host entries a failed restore could not place (device
        pool exhausted) to the host tier, newest-first preserved. An
        intervening demote may have refilled the slots the take freed
        (evict() runs inside the very allocation that failed) — entries
        that no longer fit are dropped (they are cache copies; losing
        them costs recompute, never correctness) so ``used`` can never
        exceed the configured RAM cap."""
        for digest, entry in taken:
            if digest in self._table or digest in self._host:
                continue
            if self.host_pool.readmit(entry.nbytes):
                self._host[digest] = entry

    # ------------------------------------------------------------- insert

    def insert(self, tokens: Sequence[int], pages: Sequence[int],
               digests: Optional[Sequence[bytes]] = None) -> int:
        """Publish a sequence's full pages. ``pages[i]`` must hold tokens
        ``[i*page, (i+1)*page)`` of ``tokens``. Call while the caller still
        owns the pages (the cache takes its own reference). ``digests``
        may supply precomputed chain hashes for the leading pages (the
        suffix is hashed here). Returns the number of newly published
        pages."""
        digests = extend_chain_hashes(tokens, self.page_size, digests or [])
        added = 0
        for i, digest in enumerate(digests):
            if i >= len(pages):
                break
            if digest in self._table:
                self._table.move_to_end(digest)
                continue
            # Tier invariant: publishing a digest in HBM supersedes any
            # host copy (a sibling sequence may have recomputed pages
            # the host tier still holds from an earlier demotion).
            self._drop_host(digest)
            self._table[digest] = self.allocator.share(pages[i])
            self._page_digest[pages[i]] = digest
            self.allocator.mark_cached(pages[i])
            added += 1
        return added

    def _drop_host(self, digest: bytes) -> None:
        entry = self._host.pop(digest, None)
        if entry is not None:
            self.host_pool.note_evict(entry.nbytes)

    def import_host(self, entries: Sequence[Tuple[bytes, HostKVPage]]
                    ) -> int:
        """Adopt MIGRATED host page copies (another replica's drain
        export — README "Process fleet") into the host tier, newest-LRU.
        Digests already resident in either tier are skipped (the local
        copy is at least as fresh); capacity is made by dropping the
        host tier's own oldest entries — migrated pages are about to be
        used by a resubmitted request, so they outrank idle warmth.
        Stops (dropping the remainder) when the tier cannot hold more:
        losing a migrated page costs recompute, never correctness.
        Engine thread only (same stance as evict/insert). Returns the
        pages adopted."""
        if self.host_pool is None or self.host_pool.capacity <= 0:
            return 0
        added = 0
        for digest, entry in entries:
            if digest in self._table or digest in self._host:
                continue
            while not self.host_pool.can_hold(1) and self._host:
                _, old = self._host.popitem(last=False)
                self.host_pool.note_evict(old.nbytes)
            if not self.host_pool.can_hold(1):
                break
            self._host[digest] = entry
            self.host_pool.note_import(entry.nbytes)
            added += 1
        return added

    # ------------------------------------------------------------- evict

    def _forget(self, digest: bytes) -> int:
        """Remove one HBM entry (digest must be evictable) and free its
        device page. Returns the page id."""
        page = self._table.pop(digest)
        self._evict_order.pop(digest, None)
        del self._page_digest[page]
        self.allocator.unmark_cached(page)
        self.allocator.free([page])
        return page

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` device pages from the HBM tier, oldest
        evictable entries first (entries whose page is share-pinned by a
        running sequence are never touched — the evictable-ordered table
        contains only sole-referenced entries, so this is O(evicted)).

        With a host tier attached, victims DEMOTE: their bytes copy to
        host memory (one bundled device->host transfer for the whole
        batch) before the device page is freed, making room in the host
        tier by dropping ITS oldest entries when capacity runs dry.
        With no host tier (or zero capacity), this degrades to the
        classic free-on-evict. Either way the device pages are freed.
        """
        victims: List[bytes] = []
        for digest in self._evict_order:
            if len(victims) >= n_pages:
                break
            victims.append(digest)
        if not victims:
            return 0
        demote = (self.host_pool is not None
                  and self._offload_fn is not None
                  and self.host_pool.capacity > 0)
        copies: List[Optional[HostKVPage]] = [None] * len(victims)
        if demote:
            # Second-tier eviction first: make host room for the batch
            # (never more — a victim batch larger than the whole host
            # capacity must not flush unrelated entries it can't use).
            target = min(len(victims), self.host_pool.capacity)
            while self.host_pool.free < target and self._host:
                _, old = self._host.popitem(last=False)
                self.host_pool.note_evict(old.nbytes)
            fit = min(self.host_pool.free, len(victims))
            if fit > 0:
                # Demote the NEWEST victims when not all fit — they are
                # the most likely to return.
                pages = [self._table[d] for d in victims[-fit:]]
                offloaded = self._offload_fn(pages)
                for j, hp in enumerate(offloaded):
                    copies[len(victims) - fit + j] = hp
        freed = 0
        for digest, hp in zip(victims, copies):
            self._forget(digest)
            freed += 1
            if hp is not None:
                self._drop_host(digest)     # stale host copy, if any
                self._host[digest] = hp
                self.host_pool.note_offload(hp.nbytes)
        return freed

    def clear(self) -> None:
        for digest, page in list(self._table.items()):
            self.allocator.unmark_cached(page)
            self.allocator.free([page])
        self._table.clear()
        self._evict_order.clear()
        self._page_digest.clear()
        for entry in self._host.values():
            self.host_pool.note_evict(entry.nbytes)
        self._host.clear()

    def stats(self) -> Dict[str, int]:
        out = {"entries": len(self._table), "evictable": self.evictable,
               "host_entries": len(self._host)}
        if self.host_pool is not None:
            hp = self.host_pool
            out.update({
                "host_capacity_pages": hp.capacity,
                "host_pages_used": hp.used,
                "host_bytes_resident": hp.bytes_resident,
                "offloaded_pages": hp.offloaded_total,
                "restored_pages": hp.restored_total,
                "imported_pages": hp.imported_total,
                "host_evictions": hp.evicted_total,
            })
        return out
