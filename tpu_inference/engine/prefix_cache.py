"""Prefix cache: shared-prefix KV page reuse across requests.

Multi-turn conversations resend the whole history each turn (the Ollama
protocol the reference harness speaks is stateless — SURVEY.md §2c), so
consecutive requests share long token prefixes. Pages holding those
prefixes are immutable once full (decode appends only ever write the
*current* page), which makes page-granular sharing safe with plain
refcounts — no copy-on-write needed for inference (engine/kv_cache.py).

Design:
- Key = rolling blake2b chain hash over page-sized token blocks, so a hit
  guarantees the *entire* prefix up to that page matches, not just that
  one block.
- The cache holds its own allocator reference on every inserted page
  (PageAllocator.share); a sequence releasing its pages never invalidates
  a cached copy, and eviction is just dropping the cache's reference.
- LRU eviction, triggered by the engine when the free list runs dry —
  cached-but-unused pages are reclaimable capacity, not reserved memory.
- KV content depends only on absolute positions + token ids (RoPE is
  absolute), so equal prefixes produce bit-identical pages; sharing is
  exact, not approximate.

The reference has no KV reuse of any kind (its server is external);
BASELINE.json config 3 ("multi-turn conversations.json") is the
acceptance target for this component.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_inference.engine.kv_cache import PageAllocator


def _chain_hashes(tokens: Sequence[int], page_size: int) -> List[bytes]:
    """One digest per *full* page, each folding in all prior pages.

    Runs on every admit AND every router peek (dp replicas score each
    incoming prompt), so the block encoding is fixed-width packed int32
    via numpy — one bulk tobytes() per page instead of a per-token
    str/encode/join. Fixed width keeps the encoding injective (token
    ids are non-negative and < 2**31 for any real vocab), so distinct
    token blocks can never serialize to the same bytes.
    """
    n_pages = len(tokens) // page_size
    if n_pages == 0:
        return []
    blocks = np.asarray(tokens[:n_pages * page_size],
                        dtype=np.int32).reshape(n_pages, page_size)
    out: List[bytes] = []
    h = b""
    for i in range(n_pages):
        d = hashlib.blake2b(digest_size=16)
        d.update(h)
        d.update(blocks[i].tobytes())
        h = d.digest()
        out.append(h)
    return out


class PrefixCache:
    """Maps prefix chain-hashes to physical KV pages."""

    def __init__(self, allocator: PageAllocator, page_size: int):
        self.allocator = allocator
        self.page_size = page_size
        # digest -> page id, LRU order (oldest first).
        self._table: "OrderedDict[bytes, int]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.peeks = 0

    def __len__(self) -> int:
        return len(self._table)

    @property
    def evictable(self) -> int:
        """Pages reclaimable right now (cache holds the only reference).
        O(1): the allocator maintains the counter on the engine thread,
        so metrics scrapes from other threads read a plain int."""
        return self.allocator.evictable_count

    # ------------------------------------------------------------- peek

    def peek(self, tokens: Sequence[int],
             max_tokens: Optional[int] = None) -> int:
        """Length (in full pages) of the longest cached prefix of
        ``tokens`` — **side-effect-free**: no LRU promotion, no refcount
        share, no hit/miss accounting. The dp router calls this from
        HTTP threads to score replicas, so it must neither perturb the
        engine-thread-owned eviction order nor pin pages a routing
        decision merely *considered*. Plain dict gets are GIL-atomic, so
        no lock is needed; a concurrent insert/evict can make the answer
        stale by a page or two, which the router tolerates (the prefill
        re-checks with ``lookup`` and simply recomputes the difference).
        """
        limit = len(tokens) if max_tokens is None else max_tokens
        digests = _chain_hashes(tokens, self.page_size)
        return self.peek_digests(digests[:limit // self.page_size])

    def peek_digests(self, digests: Sequence[bytes]) -> int:
        """peek() over pre-computed chain digests. The dp router hashes
        each prompt ONCE and probes every replica's table with the same
        digest list (all replicas share page_size), so scoring costs one
        hash pass per request, not one per replica. Same side-effect-free
        contract as peek()."""
        n = 0
        for digest in digests:
            if digest not in self._table:
                break
            n += 1
        self.peeks += 1
        return n

    # ------------------------------------------------------------- lookup

    def lookup(self, tokens: Sequence[int],
               max_tokens: Optional[int] = None) -> Tuple[List[int], int]:
        """Longest cached prefix of ``tokens``.

        Returns (shared_pages, n_cached_tokens); every returned page got a
        fresh allocator reference (caller owns it and must free it).
        ``max_tokens`` caps the match (the engine always re-computes at
        least the prompt's final token to get logits).
        """
        limit = len(tokens) if max_tokens is None else max_tokens
        pages: List[int] = []
        for i, digest in enumerate(_chain_hashes(tokens, self.page_size)):
            if (i + 1) * self.page_size > limit:
                break
            page = self._table.get(digest)
            if page is None:
                break
            self._table.move_to_end(digest)
            pages.append(page)
        for p in pages:
            self.allocator.share(p)
        if pages:
            self.hits += 1
        else:
            self.misses += 1
        return pages, len(pages) * self.page_size

    # ------------------------------------------------------------- insert

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Publish a sequence's full pages. ``pages[i]`` must hold tokens
        ``[i*page, (i+1)*page)`` of ``tokens``. Call while the caller still
        owns the pages (the cache takes its own reference). Returns the
        number of newly published pages."""
        added = 0
        for i, digest in enumerate(_chain_hashes(tokens, self.page_size)):
            if i >= len(pages):
                break
            if digest in self._table:
                self._table.move_to_end(digest)
                continue
            self._table[digest] = self.allocator.share(pages[i])
            self.allocator.mark_cached(pages[i])
            added += 1
        return added

    # ------------------------------------------------------------- evict

    def evict(self, n_pages: int) -> int:
        """Drop up to ``n_pages`` LRU entries whose page the cache alone
        still references (releasing shared entries frees no memory, so
        they are skipped). Returns pages actually freed."""
        freed = 0
        for digest in list(self._table):
            if freed >= n_pages:
                break
            page = self._table[digest]
            if self.allocator.refcount(page) == 1:
                del self._table[digest]
                self.allocator.unmark_cached(page)
                self.allocator.free([page])
                freed += 1
        return freed

    def clear(self) -> None:
        for digest, page in list(self._table.items()):
            self.allocator.unmark_cached(page)
            self.allocator.free([page])
        self._table.clear()

    def stats(self) -> Dict[str, int]:
        return {"entries": len(self._table), "evictable": self.evictable,
                "hits": self.hits, "misses": self.misses,
                "peeks": self.peeks}
