"""Token sampling, jit-safe and batched.

All control flow is data-parallel (`jnp.where` over the batch), so one
compiled graph serves any mix of greedy / temperature / top-k / top-p
requests in the same decode batch — no per-request recompiles (XLA static
shapes, SURVEY.md §7 hard part 2). top_k is a static graph parameter
(lax.top_k needs a static k); the server buckets requests by it.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    """Per-slot device arrays, shape [B]."""

    temperature: jax.Array   # f32; <= 0 means greedy
    top_p: jax.Array         # f32 in (0, 1]; 1 disables

    @staticmethod
    def greedy(batch: int) -> "SamplingParams":
        return SamplingParams(temperature=jnp.zeros((batch,), jnp.float32),
                              top_p=jnp.ones((batch,), jnp.float32))


def _apply_top_k(logits: jax.Array, top_k: int) -> jax.Array:
    """Keep the top_k logits per row, -inf the rest. Static k."""
    if top_k <= 0:
        return logits
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]          # [B, 1]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _apply_top_p(logits: jax.Array, top_p: jax.Array) -> jax.Array:
    """Nucleus filtering. top_p: [B]. Keeps the smallest prefix of the
    probability-sorted vocab whose mass reaches top_p (always >= 1 token)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]      # desc
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # Token i is kept if the cumulative mass *before* it is < top_p.
    keep_sorted = (cum - sorted_probs) < top_p[:, None]
    # Per-row logit threshold = smallest kept logit.
    thresh = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where(logits < thresh, -jnp.inf, logits)


def sample(logits: jax.Array, key: jax.Array, params: SamplingParams,
           top_k: int = 0) -> jax.Array:
    """logits: [B, V] f32 -> token ids [B] int32.

    Greedy rows (temperature <= 0) and sampled rows coexist in one batch.
    """
    b = logits.shape[0]
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = logits / temp
    scaled = _apply_top_k(scaled, top_k)
    scaled = _apply_top_p(scaled, params.top_p)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)

    return jnp.where(params.temperature <= 0.0, greedy_tok, sampled)


def logprobs_of(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Log-prob of given tokens under logits. [B, V], [B] -> [B]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
