"""Token sampling, jit-safe and batched.

All control flow is data-parallel (`jnp.where` over the batch), so one
compiled graph serves any mix of greedy / temperature / top-k / top-p /
seeded requests in the same decode batch — no per-request recompiles (XLA
static shapes, SURVEY.md §7 hard part 2). top_k is a per-row *dynamic*
value: instead of `lax.top_k` (which needs a static k), the row is sorted
once and thresholded at its k-th largest logit, which also serves the
top-p filter — one sort, both filters, any per-request mix.

Per-request determinism: a row with ``seed >= 0`` draws from a key stream
derived only from (seed, absolute token position), so regeneration with
the same seed reproduces the same tokens regardless of batch placement
or scheduling; rows with ``seed < 0`` use the engine-global key stream.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    """Per-slot device arrays, shape [B]."""

    temperature: jax.Array   # f32; <= 0 means greedy
    top_p: jax.Array         # f32 in (0, 1]; 1 disables
    top_k: jax.Array         # int32; <= 0 disables
    seed: jax.Array          # int32; < 0 = engine-global key stream

    @staticmethod
    def greedy(batch: int) -> "SamplingParams":
        return SamplingParams(temperature=jnp.zeros((batch,), jnp.float32),
                              top_p=jnp.ones((batch,), jnp.float32),
                              top_k=jnp.zeros((batch,), jnp.int32),
                              seed=jnp.full((batch,), -1, jnp.int32))


# Static ring-buffer width for repetition-penalty windows. Ollama's
# repeat_last_n defaults to 64; per-request values clamp to this (XLA
# static shapes — one buffer size serves every request mix).
PENALTY_WINDOW = 64


def apply_repeat_penalty(logits: jax.Array, window: jax.Array,
                         penalty: jax.Array,
                         last_n: jax.Array) -> jax.Array:
    """Ollama/llama.cpp repetition penalty, batched and jit-safe.

    logits: [B, V]; window: [B, W] chronological recent token ids (-1 =
    empty slot); penalty: [B] f32 (1.0 disables); last_n: [B] int32 —
    only the newest ``last_n`` window entries count (0 disables).
    Positive logits divide by the penalty, negative multiply — the
    llama.cpp convention that always reduces a repeated token's score.
    """
    b, v = logits.shape
    w = window.shape[1]
    rank = jnp.arange(w)[None, :]
    # Window is chronological, so the newest last_n entries live at the
    # high end of the buffer.
    in_n = rank >= (w - jnp.minimum(last_n, w))[:, None]
    valid = (window >= 0) & in_n
    idx = jnp.where(valid, window, 0)
    presence = jnp.zeros((b, v), bool).at[
        jnp.arange(b)[:, None], idx].max(valid)
    p = penalty[:, None]
    penalized = jnp.where(logits > 0, logits / p, logits * p)
    return jnp.where(presence & (p != 1.0), penalized, logits)


def roll_window(window: jax.Array, tokens: jax.Array,
                active: jax.Array) -> jax.Array:
    """Append this step's sampled tokens to active rows' windows
    (device-side, so fused multi-step decode keeps windows current)."""
    rolled = jnp.roll(window, -1, axis=1).at[:, -1].set(tokens)
    return jnp.where(active[:, None], rolled, window)


def apply_filters(logits: jax.Array, top_k, top_p: jax.Array) -> jax.Array:
    """Sequential top-k then top-p (nucleus) filtering, ONE [B, V] sort.

    ``top_k``: static int or [B] int32; <= 0 disables that row's k filter.
    ``top_p``: [B] f32; mass is measured over the top-k *survivors*
    (renormalized), matching the sequential HF processor semantics.
    Always keeps >= 1 token per row.
    """
    b, v = logits.shape
    k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    rank = jnp.arange(v)[None, :]
    keep_k = (k[:, None] <= 0) | (rank < k[:, None])
    sorted_f = jnp.where(keep_k, sorted_desc, -jnp.inf)
    probs = jax.nn.softmax(sorted_f, axis=-1)     # renormalized post-top-k
    cum = jnp.cumsum(probs, axis=-1)
    # Sorted token i is kept if the cumulative mass *before* it is < top_p.
    keep = keep_k & ((cum - probs) < top_p[:, None])
    # Per-row logit threshold = smallest kept logit.
    thresh = jnp.min(jnp.where(keep, sorted_f, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where(logits < thresh, -jnp.inf, logits)


def _row_keys(key: jax.Array, seed: jax.Array, ctx: jax.Array) -> jax.Array:
    """One PRNG key per batch row.

    seed >= 0: key = fold(fold(PRNGKey(0), seed), ctx) — a function of the
    request seed and the absolute position only (reproducible across
    batches/restarts). seed < 0: fold the engine-global step key by row.
    """
    b = seed.shape[0]
    glob = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(b, dtype=jnp.int32))
    base = jax.random.PRNGKey(0)
    seeded = jax.vmap(lambda s, c: jax.random.fold_in(
        jax.random.fold_in(base, jnp.maximum(s, 0)), c))(seed, ctx)
    return jnp.where((seed >= 0)[:, None], seeded, glob)


def sample(logits: jax.Array, key: jax.Array, params: SamplingParams,
           ctx: Optional[jax.Array] = None,
           penalty_window: Optional[jax.Array] = None,
           repeat_penalty: Optional[jax.Array] = None,
           repeat_last_n: Optional[jax.Array] = None) -> jax.Array:
    """logits: [B, V] f32 -> token ids [B] int32.

    Greedy rows (temperature <= 0) and sampled rows coexist in one batch.
    ``ctx``: [B] int32 absolute position of the token being sampled
    (keys per-request seeded streams; defaults to 0s).
    ``penalty_window``/``repeat_penalty``/``repeat_last_n``: recent-token
    repetition penalty (Ollama options); applied before temperature and
    before the greedy argmax, so greedy rows are penalized too (matching
    Ollama, where penalties act even at temperature 0).
    """
    b = logits.shape[0]
    if penalty_window is not None:
        logits = jax.lax.cond(
            jnp.any(repeat_penalty != 1.0),
            lambda l: apply_repeat_penalty(l, penalty_window,
                                           repeat_penalty, repeat_last_n),
            lambda l: l, logits)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if ctx is None:
        ctx = jnp.zeros((b,), jnp.int32)

    def sampled_path(_):
        temp = jnp.maximum(params.temperature, 1e-6)[:, None]
        scaled = apply_filters(logits / temp, params.top_k, params.top_p)
        keys = _row_keys(key, params.seed, ctx)
        sampled = jax.vmap(
            lambda k_, l: jax.random.categorical(k_, l))(keys, scaled)
        return jnp.where(params.temperature <= 0.0, greedy_tok,
                         sampled.astype(jnp.int32))

    # All-greedy batches (the benchmark/replay hot path) skip the full
    # [B, V] sort + categorical entirely — lax.cond executes one branch.
    return jax.lax.cond(jnp.all(params.temperature <= 0.0),
                        lambda _: greedy_tok, sampled_path, None)


def logprobs_of(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Log-prob of given tokens under logits. [B, V], [B] -> [B]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
