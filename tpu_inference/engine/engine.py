"""The inference engine: bucketed prefill + batched decode as two XLA graphs.

TPU-first structure (SURVEY.md §7, hard parts 2-3):
- **Two compiled graphs**, not one: ``prefill`` (one sequence, prompt padded
  to a static bucket) and ``decode`` (fixed max-batch, one token per active
  slot). Every shape is static; prompt-length variation is handled by a small
  set of buckets, batch variation by validity masks — zero recompiles in
  steady state.
- **KV buffers are donated** (``donate_argnums``) so the pool is updated in
  place in HBM instead of being double-buffered.
- Attention inside the graphs goes through the injected AttentionFn: the
  dense gather-based reference here, or the Pallas paged kernel
  (kernels/paged_attention.py) on TPU.
- The host never blocks per token on device_get of logits: decode returns
  sampled token ids ([B] int32), the only per-step host transfer.

The reference repo has no engine (it load-tests an external server,
SURVEY.md §0); capability parity is defined by BASELINE.json configs 1-4.
"""

from __future__ import annotations

import collections
import dataclasses
import random as _chaos_random
import threading
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_inference import telemetry
from tpu_inference.compat import shard_map
from tpu_inference.config import EngineConfig, ModelConfig
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.engine.kv_cache import KVPages, PageAllocator
from tpu_inference.engine.sampling import (
    PENALTY_WINDOW,
    SamplingParams,
    roll_window,
    sample,
)
from tpu_inference.engine.speculative import NGRAM_SCAN_CAP, ngram_propose
from tpu_inference.models.registry import build_model, get_model_fns


def make_paged_attn(cfg: ModelConfig, page_size: int, block_tables: jax.Array,
                    positions: jax.Array, valid: jax.Array,
                    q_offset: jax.Array, kv_len: jax.Array,
                    attn_backend: str = "dense", mesh: Optional[Any] = None,
                    sp_mode: Optional[str] = None):
    """AttentionFn that writes new K/V into the paged pool then attends.

    block_tables [B, MP]; positions/valid [B, S]; q_offset/kv_len [B].

    With a mesh, the Pallas decode kernel is shard_map-wrapped over the
    ``tp`` axis: q shards on the query-head dim and the KV pool on the
    kv-head dim (parallel/shardings.py keeps them aligned), so each chip
    streams only its own head shard's pages — attention output is
    head-local and needs no collective; the following wo matmul's
    all-reduce (placed by GSPMD) combines chips as usual.

    ``sp_mode``: sequence-parallel prefill — the chunk's self-attention
    runs sequence-sharded over the mesh's ``sp`` axis, composed with tp
    head sharding. "ring" rotates K/V shards by ppermute over ICI
    (kernels/ring_attention.py, O((S/n)²) memory); "ulysses" re-shards
    via two all-to-alls and attends full-sequence per head group
    (kernels/ulysses_attention.py, fewer collective hops, needs head
    counts divisible by sp). Valid only for a fresh full-prompt chunk
    (no cached prefix); the engine routes eligible prefills here. Both
    kernels apply ``cfg.sliding_window`` when set, so SWA models (Mistral)
    compose with sequence parallelism.
    """
    from tpu_inference.models.common import dense_causal_attention

    def _sp_prefill(q, k, v):
        from functools import partial as _partial

        from jax.sharding import PartitionSpec as P

        if sp_mode == "ulysses":
            from tpu_inference.kernels.ulysses_attention import (
                ulysses_attention_local as sp_local)
        else:
            from tpu_inference.kernels.ring_attention import (
                ring_attention_local as sp_local)

        spec = P(None, "sp", "tp", None)       # [B, S, H, D]: seq × heads
        return shard_map(
            _partial(sp_local, axis_name="sp",
                     sliding_window=cfg.sliding_window),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)(q, k, v)

    def _scales(kv: KVPages, layer_idx):
        if not kv.quantized:
            return None, None
        return kv.k_scale[layer_idx], kv.v_scale[layer_idx]

    def _sharded_paged_call(kernel, kv: KVPages, layer_idx, lead_args,
                            lead_specs, out_spec):
        """shard_map a paged kernel over tp: pool (+ scale pool when the
        KV is int8-quantized) shards on the kv-head dim; scale operands
        append conditionally so the quantized/unquantized paths share
        one spec assembly (same pattern as the kernels' own operand
        lists)."""
        from jax.sharding import PartitionSpec as P
        pool_p = P(None, None, "tp", None)             # [P, pg, Hkv, D]
        args = list(lead_args) + [kv.k[layer_idx], kv.v[layer_idx]]
        specs = list(lead_specs) + [pool_p, pool_p]
        if kv.quantized:
            scale_p = P(None, None, "tp")              # [P, pg, Hkv]
            args += [kv.k_scale[layer_idx], kv.v_scale[layer_idx]]
            specs += [scale_p, scale_p]
        return shard_map(
            kernel, mesh=mesh, in_specs=tuple(specs), out_specs=out_spec,
            check_vma=False)(*args)

    def _pallas_decode(q1, kv: KVPages, layer_idx):
        from tpu_inference.kernels.paged_attention import paged_attention
        win = cfg.sliding_window
        if mesh is None:
            ks, vs = _scales(kv, layer_idx)
            return paged_attention(q1, kv.k[layer_idx], kv.v[layer_idx],
                                   block_tables, kv_len, ks, vs,
                                   sliding_window=win)
        from jax.sharding import PartitionSpec as P
        head_p = P(None, "tp", None)                   # q/out [B, H*, D]

        def kernel(q_, bt_, kl_, k_, v_, *scales):
            ks_, vs_ = scales if scales else (None, None)
            return paged_attention(q_, k_, v_, bt_, kl_, ks_, vs_,
                                   sliding_window=win)

        return _sharded_paged_call(
            kernel, kv, layer_idx,
            lead_args=(q1, block_tables, kv_len),
            lead_specs=(head_p, P(), P()), out_spec=head_p)

    def _pallas_prefill(q, kv: KVPages, layer_idx):
        from tpu_inference.kernels.prefill_attention import (
            paged_prefill_attention)
        win = cfg.sliding_window
        if mesh is None:
            ks, vs = _scales(kv, layer_idx)
            return paged_prefill_attention(q, kv.k[layer_idx],
                                           kv.v[layer_idx], block_tables,
                                           kv_len, q_offset, ks, vs,
                                           sliding_window=win)
        from jax.sharding import PartitionSpec as P
        head_p = P(None, None, "tp", None)             # q/out [B, S, H*, D]

        def kernel(q_, bt_, kl_, qo_, k_, v_, *scales):
            ks_, vs_ = scales if scales else (None, None)
            return paged_prefill_attention(q_, k_, v_, bt_, kl_, qo_,
                                           ks_, vs_, sliding_window=win)

        return _sharded_paged_call(
            kernel, kv, layer_idx,
            lead_args=(q, block_tables, kv_len, q_offset),
            lead_specs=(head_p, P(), P(), P()), out_spec=head_p)

    def attn(layer_idx, q, k, v, kv: KVPages):
        slots = kvc.slot_mapping(block_tables, positions, valid, page_size)
        kv = kvc.write_kv(kv, layer_idx, k, v, slots)
        if attn_backend == "pallas" and q.shape[1] == 1:
            return _pallas_decode(q[:, 0], kv, layer_idx)[:, None], kv
        if sp_mode and q.shape[1] > 1:
            # Fresh full-prompt chunk: attention is pure self-attention
            # over (q, k, v) — no need to read back through the pool.
            return _sp_prefill(q, k, v), kv
        if attn_backend == "pallas" and q.shape[1] > 1:
            # Flash prefill over pool pages: O(S·page) memory, no gather
            # (window-aware when cfg.sliding_window is set: each query
            # block touches O(block+window) pages).
            return _pallas_prefill(q, kv, layer_idx), kv
        k_all, v_all = kvc.gather_kv(kv, layer_idx, block_tables)
        out = dense_causal_attention(q, k_all, v_all, q_offset=q_offset,
                                     kv_len=kv_len,
                                     sliding_window=cfg.sliding_window)
        return out, kv

    return attn


def int4_mosaic_validated() -> bool:
    """True when an on-chip Mosaic validation artifact covers the int4
    KV path (ADVICE r5: the nibble-packed kernels have only ever been
    proven under interpret-mode Pallas unless a benchmarks/results
    mosaic_*.json from a real-TPU run says otherwise).

    ``TPU_INF_INT4_VALIDATED=1`` is the operator override for
    deployments that validated out-of-repo.
    """
    import glob
    import json as _json
    import os

    if os.environ.get("TPU_INF_INT4_VALIDATED"):
        return True
    results = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "..", "..", "benchmarks", "results")
    for path in glob.glob(os.path.join(results, "mosaic_*.json")):
        try:
            with open(path) as f:
                rec = _json.load(f)
        except (OSError, ValueError):
            continue
        if (rec.get("platform") == "tpu" and rec.get("ok")
                and any("int4" in k for k in rec.get("checks", {}))):
            return True
    return False


class ChaosStepError(RuntimeError):
    """Injected engine-step failure (EngineConfig.chaos_step_failure_rate).

    A distinct type so supervision tests can tell injected faults from
    real engine bugs; the scheduler treats both identically (any step
    exception feeds the replica health machine)."""


@dataclasses.dataclass
class Sequence:
    """Host-side state for one running sequence (one decode slot)."""

    request_id: int
    prompt_tokens: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: Optional[int] = None            # None = engine default
    seed: Optional[int] = None             # None = engine-global key stream
    # Ollama repetition penalty (1.0 = off; window clamps to
    # sampling.PENALTY_WINDOW). Ignored under speculative decoding
    # (rejection sampling needs the unmodified target distribution).
    repeat_penalty: float = 1.0
    repeat_last_n: int = 64
    eos_token_id: Optional[int] = None
    # Filled by the engine:
    slot: int = -1
    pages: List[int] = dataclasses.field(default_factory=list)
    # Bumped whenever ``pages`` is wholesale-replaced (each prefill
    # setup): part of the staging-buffer block-table key, so a preempted
    # sequence resumed into the same slot with a same-length page list
    # can never alias a stale cached row (engine._stage_batch).
    pages_version: int = 0
    ctx_len: int = 0                       # tokens currently in KV
    # SWA eviction cursor: pages[:evicted_pages] are behind the window,
    # freed, and zeroed (engine._evict_behind_window).
    evicted_pages: int = 0
    cached_tokens: int = 0                 # prefix-cache hit length
    # Tiered KV cache (README "Tiered KV cache"): device pages restored
    # from the host-RAM tier for this request's prefill (swap-in), and
    # whether the queue-wait prefetch already ran for it. prefix_digests
    # carries the prompt's chain hashes computed ONCE (by the router's
    # scoring pass, or lazily at first engine use) so route -> admit ->
    # publish costs one hash pass per request, not three.
    host_restored_pages: int = 0
    host_prefetched: bool = False
    prefix_digests: Optional[List[bytes]] = None
    # Resume-stream digests (prompt + pre-preemption generated tokens),
    # kept SEPARATE from prefix_digests so failover clones and router
    # reuse never see a resume-polluted list; cleared at each preemption
    # (the stream and truncation window change there and only there).
    resume_digests: Optional[List[bytes]] = None
    # Preemption / recompute-resume state (admission="optimistic"):
    # preemptions counts evictions so far (the starvation guard compares
    # it against preempt_max_per_request); resume_base is the number of
    # generated tokens present at the last (re)prefill, so the resume
    # prefill computes prompt + generated[:resume_base] and decode
    # continues from there. admit_idx orders running sequences by
    # admission recency (victim selection preempts the newest first).
    preemptions: int = 0
    resume_base: int = 0
    admit_idx: int = -1
    # Incremental multi-chunk prefill state (prefill_begin/prefill_step).
    prefill_prompt: Optional[List[int]] = None
    prefill_offset: int = 0
    generated: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    finish_reason: str = ""
    # Set (under the scheduler lock) by EngineScheduler._finish so the
    # terminal path runs exactly once even when the shutdown force-
    # finish races a slow engine thread's own reap.
    reaped: bool = False
    # Timing (server metrics; SURVEY.md §5 observability).
    enqueue_time: float = 0.0
    prefill_start: float = 0.0
    first_token_time: float = 0.0
    finish_time: float = 0.0
    # End-to-end tracing (telemetry.py): trace_id is the client-visible
    # request id propagated from HTTP ingress (X-Request-Id) into
    # structured logs and response metadata; attempt counts failover
    # resubmissions (server/replicas.py) so a resubmitted span is marked.
    trace_id: str = ""
    attempt: int = 0
    # Priority class (README "Elastic fleet"): interactive requests
    # outrank batch/background at admission AND in the waiting queue
    # (config.class_rank); lower classes absorb overload via deferral
    # and watermark preemption instead of a fleet-wide 429.
    priority_class: str = "interactive"
    # Routing span (server/replicas.py): which dp replica this attempt
    # was dispatched to and how many cached prefix pages the router
    # counted on at decision time (-1/0 when submitted scheduler-direct).
    routed_replica: int = -1
    route_hit_pages: int = 0
    # Of route_hit_pages, how many were host-tier (warm but needing a
    # swap-in) at decision time — the router's third temperature.
    route_host_hit_pages: int = 0
    # Pages the router pulled from the fleet KV fabric into this
    # replica's host tier before dispatch (README "KV fabric") — the
    # fourth temperature: warmth another replica prefilled.
    route_fabric_hit_pages: int = 0
    # Phase accounting accrued by the engine: wall time of device
    # dispatches this request participated in, and its share of the
    # host-side bubble between decode calls. Shared dispatches accrue
    # fully to every participant (they wait on the same call), so these
    # are per-request *exposure*, not an additive fleet total.
    dispatch_wall_s: float = 0.0
    bubble_s: float = 0.0
    # Adaptive-γ state for draft-free n-gram speculation (README
    # "Speculative decoding"): current per-sequence γ (-1 = engine
    # default, 0 = throttled), EWMA acceptance rate, and the countdown
    # until a throttled sequence re-probes. Survives preemption /
    # recompute-resume — the stream's echo statistics don't change when
    # its KV pages do.
    # The EWMA starts mildly optimistic (not 1.0): a fresh echo-free
    # stream throttles after ~3 rejected rounds instead of ~5, and an
    # echoic one pulls toward 1 just as fast.
    spec_gamma: int = -1
    spec_accept_ewma: float = 0.5
    spec_probe_countdown: int = 0
    # Consecutive failed probes back the probe interval off (doubling,
    # capped at 8x spec_probe_every), so a stream that never echoes
    # pays a vanishing fraction of its rounds re-checking.
    spec_probe_interval: int = 0
    # P/D disaggregation (README "P/D disaggregation"). Outbound: a
    # prefill-role worker sets handoff_after_prefill so the scheduler
    # emits the settled prefill (KV pages incl. the partial final page
    # + stream state) as a live handoff instead of decoding it locally.
    # Inbound: adopt_kv = (host_pages, ctx_len) carries a received
    # handoff; admission restores the pages straight into fresh device
    # pages and resumes DECODE — no prefill dispatch, zero recomputed
    # tokens (engine.adopt_sequence).
    handoff_after_prefill: bool = False
    adopt_kv: Optional[tuple] = None
    # Set by adopt_sequence: this attempt resumed from a live KV
    # handoff (no prefill dispatch ran) — the tracing layer emits a
    # handoff_adopt span in place of the prefill span, and the SLO
    # tracker skips its TTFT (the client's first token streamed from
    # the prefill worker, not here).
    adopted: bool = False
    # Per-request speculative-round exposure (ngram/draft modes):
    # rounds this sequence proposed in and positions accepted —
    # surfaced as attrs on the request's decode span so a trace shows
    # where speculation paid off without a span per round.
    spec_rounds: int = 0
    spec_accepted_toks: int = 0

    @property
    def last_token(self) -> int:
        return self.generated[-1] if self.generated else self.prompt_tokens[-1]


class InferenceEngine:
    """Owns device state (params, KV pool) and the compiled step functions."""

    def __init__(self, model_cfg: ModelConfig, engine_cfg: EngineConfig,
                 params: Optional[dict] = None, seed: int = 0,
                 attn_backend: Optional[str] = None,
                 shard_fn: Optional[Callable[[dict], dict]] = None,
                 mesh: Optional[Any] = None,
                 draft_cfg: Optional[ModelConfig] = None,
                 draft_params: Optional[dict] = None):
        model_cfg.validate()
        self.model_cfg = model_cfg
        self.engine_cfg = engine_cfg
        self.mod = get_model_fns(model_cfg)
        # Resolve the decode-attention backend: constructor arg wins, then
        # EngineConfig; "auto" = the Pallas paged kernel on real TPU, the
        # dense gather path elsewhere (interpret-mode Pallas on CPU is far
        # slower than XLA's fused gather+attention, so tests opt in
        # explicitly).
        backend = attn_backend or engine_cfg.attn_backend
        if backend == "auto":
            backend = ("pallas" if jax.default_backend() == "tpu"
                       else "dense")
        if backend not in ("dense", "pallas"):
            raise ValueError(f"unknown attn_backend {backend!r}; "
                             "expected 'auto', 'dense' or 'pallas'")
        # Validate mesh compatibility BEFORE materializing params —
        # at 70B scale a post-init failure wastes minutes (or OOMs).
        if mesh is not None:
            from tpu_inference.parallel import shardings as _shd
            _shd.validate_tp(model_cfg, mesh.shape.get("tp", 1))
            if draft_cfg is not None:
                _shd.validate_tp(draft_cfg, mesh.shape.get("tp", 1))
        def maybe_quantize(p):
            # Weight-only int8: halves the per-step HBM weight read that
            # bounds decode throughput (BASELINE.md roofline). Runs on
            # device; shard_params below re-canonicalizes placements.
            if engine_cfg.quant == "none":
                return p
            from tpu_inference.models.quant import quantize_params
            return quantize_params(p, engine_cfg.quant)

        if params is None:
            if engine_cfg.quant != "none":
                # Leaf-by-leaf init+quantize: peak device memory stays
                # ~quantized-model-sized (8B random-init int8 fits one
                # 16 GB chip; init-everything-then-quantize would OOM
                # at the full-precision peak).
                from tpu_inference.models.quant import init_quantized_params
                params = init_quantized_params(model_cfg, seed,
                                               engine_cfg.quant)
            else:
                params, _ = build_model(model_cfg, seed=seed)
        if shard_fn is not None:
            params = shard_fn(params)
        params = maybe_quantize(params)  # no-op on already-quantized leaves
        self.mesh = mesh
        kv_sh = kv_scale_sh = None
        if mesh is not None:
            # Declarative TP/EP: annotate weights + KV pool, let GSPMD place
            # the ICI collectives. The jitted graphs pick the shardings up
            # from their inputs; donated KV keeps its sharding step to step.
            from tpu_inference.parallel import shardings as shd
            params = shd.shard_params(params, model_cfg, mesh)
            kv_sh = shd.kv_sharding(mesh)
            kv_scale_sh = shd.kv_scale_sharding(mesh)
        self.params = params
        self.n_params = int(sum(x.size for x in jax.tree.leaves(params)))
        # Resident bytes of the (possibly quantized) weights — global
        # logical size, independent of sharding. Reported by /api/ps and
        # used by bench.py's hbm_util roofline math.
        self.weight_bytes = int(sum(x.nbytes
                                    for x in jax.tree.leaves(params)))
        self.attn_backend = backend
        self.kv = kvc.alloc_kv_pages(model_cfg, engine_cfg, sharding=kv_sh,
                                     scale_sharding=kv_scale_sh)
        self.allocator = PageAllocator(engine_cfg.num_pages)
        # Step-phase telemetry (telemetry.py): dispatch/bubble histograms
        # + read-through page/param gauges. TPU_INF_TELEMETRY=0 swaps in
        # no-op metrics (the overhead-comparison arm).
        self.telemetry = telemetry.EngineTelemetry(self)
        # Host-side bubble tracking: perf_counter at the end of the last
        # decode dispatch, None when the decode streak broke (idle batch
        # or an interleaved prefill) so cross-idle gaps never count.
        self._last_decode_end: Optional[float] = None
        self._check_degraded_modes()
        # Fault injection, copied out of the frozen config so tests and
        # the /debug/chaos endpoint can arm/disarm per replica at runtime.
        self.chaos_step_failure_rate = engine_cfg.chaos_step_failure_rate
        self.chaos_step_wedge_s = engine_cfg.chaos_step_wedge_s
        # Admission mode (README "Admission & preemption"): "reserve"
        # charges worst case at admission; "optimistic" charges prompt +
        # headroom and relies on watermark-driven preemption +
        # recompute-resume as the exhaustion safety net.
        if engine_cfg.admission not in ("reserve", "optimistic"):
            raise ValueError(f"unknown admission mode "
                             f"{engine_cfg.admission!r}; "
                             "one of ('reserve', 'optimistic')")
        self.admission = engine_cfg.admission
        self.preemptions_total = 0        # sequences evicted for pressure
        self.resumes_total = 0            # recompute-resume prefills
        self.swap_in_resumes = 0          # resumes that restored KV pages
        self.hybrid_steps_total = 0       # fused prefill+decode dispatches
        # KV page migration (README "Process fleet"): pages/bytes this
        # engine exported at drain time and imported from a sibling
        # replica's drain. Plain ints (GIL-atomic reads from scrape
        # threads), exported read-through by bind_engine.
        self.migrate_out_pages = 0
        self.migrate_out_bytes = 0
        self.migrate_in_pages = 0
        self.migrate_in_bytes = 0
        # P/D disaggregation (README "P/D disaggregation"): the worker
        # phase role this engine serves (specializes warmup below), and
        # the live-handoff churn — settled prefills exported to a decode
        # worker, and handed-off sequences adopted here (KV restored,
        # decode resumed, nothing recomputed).
        from tpu_inference.config import WORKER_ROLES
        if engine_cfg.role not in WORKER_ROLES:
            raise ValueError(f"unknown engine role {engine_cfg.role!r}; "
                             f"one of {WORKER_ROLES}")
        self.role = engine_cfg.role
        self.handoffs_out = 0
        self.handoff_out_pages = 0
        self.adoptions_in = 0
        # Handoffs this worker RECEIVED but could not adopt (malformed/
        # truncated blob, pool shortfall at admission) — they fell back
        # to recompute-resume. Folded into the fleet's
        # tpu_inf_pd_handoff_recomputes_total so the metric's contract
        # ("every non-clean handoff") holds for worker-side failures
        # too, not just the router-side stale-blob/no-adopter paths.
        self.adopt_fallbacks = 0
        # Byzantine transport (README "Failure model"): KV blobs whose
        # embedded CRC-32C digest failed verification on an adopt or
        # import path — rejected and counted here, never adopted. The
        # worker folds this into healthz and the fleet sums it into
        # tpu_inf_kv_integrity_rejections_total.
        self.kv_integrity_rejections = 0
        # Fleet KV fabric publish (README "KV fabric"): when armed (the
        # worker's boot() or the in-process group sets fabric_publish to
        # a callable taking [(digest, HostKVPage)]), _publish_to_cache
        # also offloads the settled prefix run and ships it to the
        # router's fabric pool, so a prefix prefilled here warms every
        # replica. _fabric_published is a bounded dedup set so steady
        # traffic over the same system prompt doesn't re-serialize the
        # same pages every release.
        self.fabric_publish = None
        self.fabric_publish_min_pages = 1
        self._fabric_published: "collections.OrderedDict[bytes, None]" = \
            collections.OrderedDict()
        self.fabric_published_pages = 0
        # Cross-thread migration imports (the worker's import-kv RPC
        # lands on an RPC thread; the host tier is engine-thread only):
        # queued here, applied by the scheduler loop before admission so
        # an import acked before its request's submit is visible to that
        # request's prefill. Each entry is (entries, done_event).
        self._pending_imports: List[tuple] = []
        self._pending_imports_lock = threading.Lock()
        self._admit_counter = 0           # admission recency for victims
        # Sequences preempted since the caller last collected them; the
        # scheduler requeues these at the head of its wait queue.
        self._preempted_out: List[Sequence] = []
        # chaos_page_pressure holds REAL pages out of the pool so the
        # exhaustion/preemption paths run deterministically on CPU.
        self._pressure_pages: List[int] = []
        self.chaos_page_pressure = 0
        # Cross-thread arm/disarm requests (the /debug/chaos handler
        # runs on an aiohttp thread; the allocator is engine-thread
        # only): a plain GIL-atomic store, applied by the engine loop.
        self._pressure_target: Optional[int] = None
        if engine_cfg.chaos_page_pressure > 0:
            self.set_page_pressure(engine_cfg.chaos_page_pressure)
        # Speculative decoding modes (README "Speculative decoding"):
        # "draft" = a separate draft model proposes (needs its own KV
        # pool, so several compositions below are gated off); "ngram" =
        # draft-free host-side self-drafting (prompt lookup) — no draft
        # pool, no extra HBM, so the ladder, host tier, SWA eviction and
        # the repetition penalty all stay active.
        if engine_cfg.spec_mode not in ("draft", "ngram"):
            raise ValueError(f"unknown spec_mode {engine_cfg.spec_mode!r}; "
                             "one of ('draft', 'ngram')")
        if engine_cfg.spec_mode == "ngram":
            from tpu_inference.config import validate_spec_config
            validate_spec_config("ngram", engine_cfg.num_speculative_tokens,
                                 engine_cfg.ngram_window,
                                 draft_cfg is not None)
        spec_draft = (engine_cfg.spec_mode == "draft"
                      and draft_cfg is not None
                      and engine_cfg.num_speculative_tokens > 0)
        spec_ngram = engine_cfg.spec_mode == "ngram"
        spec_on = spec_draft or spec_ngram
        self.spec_draft = spec_draft
        self.spec_ngram = spec_ngram
        self.spec_mode = "ngram" if spec_ngram else "draft"
        self.prefix_cache = None
        # Prefix caching composes with speculative decoding because the
        # draft pool is a strict positional twin of the target pool: both
        # write the SAME input-token stream at the same block-table slots
        # (prompt chunks via _draft_prefill_fn; decode rounds via
        # spec_round, whose draft scan and target verify consume
        # identical [last, d_0..d_{gamma-1}] inputs), and cache hits are
        # full pages below ctx_len, where every row in BOTH pools is
        # settled. Reusing a cached page therefore reuses a valid draft
        # twin for free.
        # The window only binds when the serving context can exceed it
        # (ADVICE r4): with max_context <= window no query ever looks
        # back past the window, eviction would never free a page, and
        # behavior is identical to full attention — so the prefix cache
        # stays safe and the SWA exclusions don't apply.
        swa_binds = bool(model_cfg.sliding_window) and (
            engine_cfg.max_context > model_cfg.sliding_window)
        self.host_pool = None
        if engine_cfg.enable_prefix_cache and not swa_binds:
            # SWA models run WITHOUT the prefix cache (vLLM makes the
            # same exclusion): behind-window pages are evicted while a
            # sequence runs (_evict_behind_window), and a cached prefix
            # with holes would hand garbage KV to a shorter follow-up
            # request whose own window lands inside the evicted region.
            from tpu_inference.engine.prefix_cache import PrefixCache
            if engine_cfg.host_cache_pages > 0 and not spec_draft:
                # Host-RAM second tier: evicted pages demote instead of
                # being dropped (README "Tiered KV cache"). Off under
                # DRAFT-model speculative decoding: only the TARGET pool
                # offloads, and a restored page with a stale draft twin
                # would silently tank acceptance — the draft pool's
                # positional twin invariant (below) only holds for pages
                # both models wrote in lockstep. Draft-free ngram spec
                # has no draft pool, so the tier stays live.
                self.host_pool = kvc.HostPagePool(
                    engine_cfg.host_cache_pages)
                self.telemetry.bind_host_pool(self.host_pool)
            elif engine_cfg.host_cache_pages > 0:
                print(f"[engine] {model_cfg.name}: host KV tier disabled "
                      "— speculative decoding's draft pool has no host "
                      "twin to restore")
            self.prefix_cache = PrefixCache(self.allocator,
                                            engine_cfg.page_size,
                                            host_pool=self.host_pool,
                                            offload_fn=self._offload_pages)
            self.prefix_cache.bind_telemetry(self.telemetry)
        elif engine_cfg.enable_prefix_cache:
            print(f"[engine] {model_cfg.name}: prefix cache disabled — "
                  f"sliding_window={model_cfg.sliding_window} evicts "
                  "behind-window pages, which doesn't compose with "
                  "cached prefixes (multi-turn requests re-prefill)")
        self.max_pages = engine_cfg.max_pages_per_seq
        self._base_key = jax.random.PRNGKey(seed)
        self._step_count = 0
        # Batch ladder (README "Batch ladder"): the decode graphs are
        # compiled at every rung; dispatch uses the smallest rung that
        # covers the occupied slots. The slot array is always top-rung
        # sized — rung moves never relocate KV (block tables are host
        # state shipped per dispatch), only which compiled graph runs.
        from tpu_inference.engine.autosize import validate_ladder
        ladder = validate_ladder(engine_cfg.ladder_rungs,
                                 engine_cfg.max_batch_size)
        if spec_draft and len(ladder) > 1:
            # The draft-model spec round compiles one fused draft+verify
            # graph at the full batch; rung-switching it would multiply
            # compiles for a path the roadmap still calls a slowdown.
            # Single rung. (ngram spec keeps the full ladder: its
            # verify-only graph compiles per rung in warmup, like the
            # plain decode graphs.)
            print(f"[engine] {model_cfg.name}: draft-model speculative "
                  "decoding — decode ladder collapsed to the top rung")
            ladder = (engine_cfg.max_batch_size,)
        self.ladder = ladder
        self.decode_rung = ladder[0]      # rung of the latest dispatch
        self.rung_peak = ladder[0]        # highest rung reached
        self.rung_switches_total = 0      # dispatches at a changed rung
        # Step-ledger scratch (telemetry.py StepLedger; README
        # "Performance attribution"): compile-event detection per rung /
        # prefill bucket, the staged bubble/staging micros the next
        # ledger push consumes, and the KV-swap byte-counter watermark
        # that turns cumulative swap counters into per-record deltas.
        self._rungs_seen: set = set()
        self._prefill_buckets_seen: set = set()
        self._pending_bubble = 0.0
        self._last_staging_s = 0.0
        self._last_swap_bytes_total = 0.0
        self._last_compile_event = False
        # Host staging reuse (the per-dispatch bubble shrinker): per-rung
        # persistent arrays, refreshed incrementally. Device hand-off
        # always copies — jnp.asarray aliases numpy memory on CPU, and
        # these buffers mutate next step while a dispatch may still read.
        self._stage_reuse = engine_cfg.stage_host_reuse
        self._stage_bufs: Dict[int, dict] = {}
        self.slots: List[Optional[Sequence]] = [None] * engine_cfg.max_batch_size
        # Dispatch-ahead decode pipeline (decode_steps_pipelined).
        self._inflight: List[dict] = []
        # Embeddings graph (built on first /api/embeddings use).
        self._embed_jit = None
        self._embed_lock = threading.Lock()

        self._prefill_jit = jax.jit(
            partial(self._prefill_fn), donate_argnums=(1,))
        self._decode_multi_jit = jax.jit(
            partial(self._decode_multi_fn), donate_argnums=(1,))
        # Hybrid prefill-decode steps (EngineConfig.hybrid_prefill): one
        # fused dispatch advances a [1, S] prefill chunk AND the [B]
        # K-step decode scan on the shared (page-disjoint) pool. One
        # graph per prefill bucket; the decode half keeps the fused-K
        # shape, so compile count matches the serial path's.
        self._hybrid_jit = jax.jit(
            partial(self._hybrid_step_fn), donate_argnums=(1,))
        # Single-step decode graph: a 1-iteration scan, so a token leaves
        # the device every step instead of every K — the scheduler's
        # latency mode uses it when the batch is nearly empty (streaming
        # smoothness; fused K-step calls would still run K forwards for
        # one visible token). With K == 1 the fused graph IS the 1-step
        # graph; aliasing keeps one compile cache so warmup covers both
        # routes.
        if engine_cfg.decode_steps_per_call <= 1:
            self._decode_one_jit = self._decode_multi_jit
        else:
            self._decode_one_jit = jax.jit(
                partial(self._decode_multi_fn, k_steps=1),
                donate_argnums=(1,))
        # Sequence-parallel prefill (ring attention over the sp axis) for
        # fresh full-prompt chunks on an sp>1 mesh.
        self.sp = 1 if mesh is None else int(mesh.shape.get("sp", 1))
        # Compiled prefill lane counts (pad-to-size keeps XLA graph count
        # bounded at 2 per bucket).
        self._prefill_batch_sizes = sorted(
            {1, max(1, engine_cfg.max_prefill_batch)})
        if self.sp > 1:
            if engine_cfg.sp_attn not in ("ring", "ulysses"):
                raise ValueError(f"sp_attn={engine_cfg.sp_attn!r}: "
                                 "one of ('ring', 'ulysses')")
            if engine_cfg.sp_attn == "ulysses":
                tp = int(mesh.shape.get("tp", 1))
                if (model_cfg.n_heads % (tp * self.sp)
                        or model_cfg.n_kv_heads % (tp * self.sp)):
                    raise ValueError(
                        f"sp_attn='ulysses' needs n_heads "
                        f"({model_cfg.n_heads}) and n_kv_heads "
                        f"({model_cfg.n_kv_heads}) divisible by tp*sp "
                        f"({tp}*{self.sp}); use sp_attn='ring'")
            self._prefill_sp_jit = jax.jit(
                partial(self._prefill_fn, sp_mode=engine_cfg.sp_attn),
                donate_argnums=(1,))

        # Speculative decoding (BASELINE.json config 4): a draft model with
        # its own KV pool but the SAME page geometry + block tables, so one
        # host-side ctx/page state serves both models.
        self.spec_enabled = spec_on
        self.spec_drafted = 0
        self.spec_accepted = 0
        # ngram-mode round accounting: verify rounds dispatched, rounds
        # that degraded to the plain fused-K graph (no slot proposed),
        # and per-sequence γ=0 throttle events (the adaptive-γ "spec
        # never loses" lever).
        self.spec_rounds_total = 0
        self.spec_fallback_rounds = 0
        self.spec_throttles_total = 0
        if spec_on:
            self.telemetry.bind_spec(self)
        # Behind-window page eviction (SWA): a running sequence holds
        # O(window) KV pages instead of O(context). Off under DRAFT-model
        # spec decode — a window-less DRAFT model still attends to the
        # full context, so the target's behind-window pages stay live
        # (ngram spec has no draft; its verify queries sit at positions
        # >= ctx, whose windows start at or after plain decode's, so
        # eviction composes). Off when the window can't bind (swa_binds
        # above): there would never be a behind-window page to free.
        self.swa_evict = (swa_binds and self.prefix_cache is None
                          and not spec_draft)
        if swa_binds and spec_draft:
            print(f"[engine] {model_cfg.name}: SWA + speculative decoding"
                  " — behind-window eviction OFF (the window-less draft"
                  " attends full context), so sequences hold O(context)"
                  " KV pages, not O(window)")
        if self.spec_ngram:
            from tpu_inference.engine.speculative import verify_round
            self._verify_jit = jax.jit(partial(verify_round, self),
                                       donate_argnums=(1,))
            # Compiled verify widths (tokens per round = width): the
            # full γ+1 round plus a narrow 2-wide probe round, so a
            # γ=0-throttled lane re-checks its echo at near-plain cost.
            # XLA keys on the drafts shape, so each (rung, width) pair
            # is its own executable — all warmed in warmup().
            gamma = engine_cfg.num_speculative_tokens
            self._spec_widths = sorted({2, gamma + 1})
        if self.spec_draft:
            assert draft_cfg.vocab_size == model_cfg.vocab_size, \
                "draft and target must share a tokenizer/vocab"
            self.draft_cfg = draft_cfg
            self.draft_mod = get_model_fns(draft_cfg)
            if draft_params is None:
                draft_params, _ = build_model(draft_cfg, seed=seed + 1)
            draft_params = maybe_quantize(draft_params)
            if mesh is not None:
                # Draft weights get the same mesh treatment as the target
                # (divisibility was fail-fast-checked above); the draft
                # pool reuses the tp-sharded kv layout.
                from tpu_inference.parallel import shardings as _shd
                draft_params = _shd.shard_params(draft_params, draft_cfg,
                                                 mesh)
            self.draft_params = draft_params
            self.draft_kv = kvc.alloc_kv_pages(draft_cfg, engine_cfg,
                                               sharding=kv_sh,
                                               scale_sharding=kv_scale_sh)
            from tpu_inference.engine.speculative import spec_round
            self._spec_jit = jax.jit(partial(spec_round, self),
                                     donate_argnums=(2, 3))
            self._draft_prefill_jit = jax.jit(
                partial(self._draft_prefill_fn), donate_argnums=(1,))

    # ------------------------------------------------------------------
    # Device graphs (pure functions of arrays; jitted once per bucket/batch)
    # ------------------------------------------------------------------

    def _prefill_fn(self, params, kv: KVPages, tokens, prompt_len, prefix_len,
                    block_table, key, temperature, top_p, top_k, seed,
                    rpen, rlast, window, sp_mode=None):
        """One sequence, tokens [1, S_bucket] right-padded.

        prefix_len > 0 means ``prefix_len`` tokens are already cached in this
        sequence's pages (multi-turn / chunked prefill); new tokens occupy
        positions [prefix_len, prefix_len + prompt_len).
        """
        cfg = self.model_cfg
        s = tokens.shape[1]
        ar = jnp.arange(s)[None, :]
        positions = prefix_len[:, None] + ar                     # [1, S]
        valid = ar < prompt_len[:, None]
        total_len = prefix_len + prompt_len
        positions = jnp.minimum(positions, self.engine_cfg.max_context - 1)
        attn = make_paged_attn(cfg, self.engine_cfg.page_size, block_table,
                               positions, valid, q_offset=prefix_len,
                               kv_len=total_len, mesh=self.mesh,
                               attn_backend=self.attn_backend,
                               sp_mode=sp_mode)
        hidden, kv = self.mod.forward_hidden(params, cfg, tokens, positions,
                                             kv, attn)
        last = jnp.take_along_axis(
            hidden, (prompt_len - 1)[:, None, None].astype(jnp.int32), axis=1
        )[:, 0]                                                  # [1, D]
        logits = self.mod.unembed(params, cfg, last)             # [1, V]
        sp = SamplingParams(temperature=temperature, top_p=top_p,
                            top_k=top_k, seed=seed)
        tok = sample(logits, key, sp, ctx=total_len, penalty_window=window,
                     repeat_penalty=rpen, repeat_last_n=rlast)
        return kv, tok, logits

    def _draft_prefill_fn(self, draft_params, draft_kv: KVPages, tokens,
                          prompt_len, prefix_len, block_table):
        """Populate the draft model's KV for the prompt (no sampling).
        Shapes mirror _prefill_fn; runs once per prefill chunk."""
        cfg = self.draft_cfg
        s = tokens.shape[1]
        ar = jnp.arange(s)[None, :]
        positions = prefix_len[:, None] + ar
        valid = ar < prompt_len[:, None]
        positions = jnp.minimum(positions, self.engine_cfg.max_context - 1)
        attn = make_paged_attn(cfg, self.engine_cfg.page_size, block_table,
                               positions, valid, q_offset=prefix_len,
                               kv_len=prefix_len + prompt_len,
                               mesh=self.mesh,
                               attn_backend=self.attn_backend)
        _, draft_kv = self.draft_mod.forward_hidden(
            draft_params, cfg, tokens, positions, draft_kv, attn)
        return draft_kv

    def _decode_multi_fn(self, params, kv: KVPages, tokens, ctx_lens,
                         block_tables, allowed, eos_ids, key, temperature,
                         top_p, top_k, seed, rpen, rlast, window,
                         k_steps: Optional[int] = None):
        """K fused decode steps under one dispatch (lax.scan on device).

        Sampled tokens feed back into the next step without leaving HBM;
        the host syncs once per K steps instead of per token, which is the
        difference between dispatch-latency-bound and compute-bound decode
        (SURVEY.md §7 hard part 3: host<->device overlap).

        allowed: [B] int32 — steps each slot may advance this call (folds
        budget, context cap, and page headroom). eos_ids: [B] int32, -1
        when the request has no EOS. window: [B, W] recent-token ring for
        the repetition penalty, updated on device each step so fused
        steps see their own samples. Returns (kv, out [K, B] int32, final
        carry tokens [B], final window [B, W]) with -1 out entries for
        slots that produced nothing at that step.
        """
        cfg = self.model_cfg
        ecfg = self.engine_cfg

        def step(carry, s):
            kv, tokens, ctx_lens, alive, window = carry
            act = alive & (s < allowed)
            positions = jnp.minimum(ctx_lens, ecfg.max_context - 1)[:, None]
            attn = make_paged_attn(cfg, ecfg.page_size, block_tables,
                                   positions, act[:, None],
                                   q_offset=ctx_lens, kv_len=ctx_lens + 1,
                                   attn_backend=self.attn_backend,
                                   mesh=self.mesh)
            hidden, kv = self.mod.forward_hidden(params, cfg, tokens[:, None],
                                                 positions, kv, attn)
            logits = self.mod.unembed(params, cfg, hidden[:, 0])
            sp = SamplingParams(temperature=temperature, top_p=top_p,
                                top_k=top_k, seed=seed)
            # The token being sampled will sit at absolute index ctx+1
            # (the current input token occupies ctx) — the seeded-stream
            # position that makes per-request seeds scheduling-invariant.
            toks = sample(logits, jax.random.fold_in(key, s), sp,
                          ctx=ctx_lens + 1, penalty_window=window,
                          repeat_penalty=rpen, repeat_last_n=rlast)
            toks = jnp.where(act, toks, tokens)
            window = roll_window(window, toks, act)
            out = jnp.where(act, toks, -1)
            alive = alive & jnp.where(act, toks != eos_ids, True)
            ctx_lens = ctx_lens + act.astype(jnp.int32)
            return (kv, toks, ctx_lens, alive, window), out

        if k_steps is None:
            k_steps = max(1, ecfg.decode_steps_per_call)
        alive0 = jnp.ones(tokens.shape, bool)
        (kv, final_tokens, _, _, final_window), outs = jax.lax.scan(
            step, (kv, tokens, ctx_lens, alive0, window),
            jnp.arange(k_steps, dtype=jnp.int32))
        # final_tokens [B] (and final_window) = each lane's carry after
        # the last step: the input for a chained next call, letting
        # callers dispatch call N+1 against call N's device-resident
        # output with no host sync (dispatch-ahead, SURVEY.md §7 hard
        # part 3 — the host/tunnel round trip otherwise gates decode
        # throughput).
        return kv, outs, final_tokens, final_window

    def _hybrid_step_fn(self, params, kv: KVPages,
                        p_tokens, p_prompt_len, p_prefix_len, p_block_table,
                        p_key, p_temp, p_top_p, p_top_k, p_seed, p_rpen,
                        p_rlast, p_window,
                        d_tokens, d_ctx_lens, d_block_tables, d_allowed,
                        d_eos_ids, d_key, d_temp, d_top_p, d_top_k, d_seed,
                        d_rpen, d_rlast, d_window):
        """One hybrid step: a [1, S_bucket] prefill chunk AND the [B]
        K-step fused decode under a single dispatch.

        The fusion is safe because the two halves are page-disjoint: the
        chunk writes (then attends over) only the prefilling sequence's
        block table, and every decode lane reads/writes only its own
        pages — so the sequential composition below computes exactly
        what the two serial dispatches compute, while the device sees
        one launch instead of a decode batch stalling a full chunk wall.
        Returns (kv, chunk's sampled token [1], decode outs [K, B],
        final carry tokens [B], final penalty window [B, W]) — the
        decode tail matches _decode_multi_fn so hybrid calls chain into
        the same dispatch-ahead pipeline as plain decode calls.
        """
        kv, p_tok, _ = self._prefill_fn(
            params, kv, p_tokens, p_prompt_len, p_prefix_len, p_block_table,
            p_key, p_temp, p_top_p, p_top_k, p_seed, p_rpen, p_rlast,
            p_window)
        kv, outs, final, final_window = self._decode_multi_fn(
            params, kv, d_tokens, d_ctx_lens, d_block_tables, d_allowed,
            d_eos_ids, d_key, d_temp, d_top_p, d_top_k, d_seed, d_rpen,
            d_rlast, d_window)
        return kv, p_tok, outs, final, final_window

    # ------------------------------------------------------------------
    # Host-side orchestration
    # ------------------------------------------------------------------

    def warmup(self) -> float:
        """Compile every prefill bucket + the decode graph before serving.

        Without this, the first requests pay XLA compile inside their TTFT
        (and the compile blocks the GIL, starving the HTTP event loop so
        streamed tokens burst out after headers). Shapes are what XLA keys
        on, so prompt_len=1 per bucket suffices; writes land on the trash
        page. Returns seconds spent.
        """
        t0 = time.perf_counter()
        ecfg = self.engine_cfg
        # Role-specialized warmup (README "P/D disaggregation"): a
        # prefill worker never dispatches the decode ladder and a decode
        # worker never dispatches a prompt prefill (adoption restores KV
        # without a forward), so each role compiles only its own phase's
        # graphs — per-role warmup drops to a fraction of the mixed
        # compile set. The OTHER phase still works (lazy compile) so a
        # degraded fleet's fallback routing never strands a request.
        warm_prefill = self.role != "decode"
        warm_decode = self.role != "prefill"
        prefill_batch_sizes = (self._prefill_batch_sizes if warm_prefill
                               else ())
        for p in prefill_batch_sizes:
            bt = jnp.zeros((p, self.max_pages), jnp.int32)
            one = jnp.ones((p,), jnp.int32)
            zero = jnp.zeros((p,), jnp.int32)
            tz = jnp.zeros((p,), jnp.float32)
            tp = jnp.ones((p,), jnp.float32)
            tk = jnp.zeros((p,), jnp.int32)
            sd = jnp.full((p,), -1, jnp.int32)
            rp = jnp.ones((p,), jnp.float32)
            rl = jnp.zeros((p,), jnp.int32)
            win = jnp.full((p, PENALTY_WINDOW), -1, jnp.int32)
            for bucket in ecfg.prefill_buckets:
                if bucket > ecfg.max_context:
                    continue
                toks = jnp.zeros((p, bucket), jnp.int32)
                self.kv, _, _ = self._prefill_jit(
                    self.params, self.kv, toks, one, zero, bt,
                    self._next_key(), tz, tp, tk, sd, rp, rl, win)
                if self.sp > 1 and bucket % self.sp == 0:
                    self.kv, _, _ = self._prefill_sp_jit(
                        self.params, self.kv, toks, one, zero, bt,
                        self._next_key(), tz, tp, tk, sd, rp, rl, win)
                if self.spec_draft:
                    self.draft_kv = self._draft_prefill_jit(
                        self.draft_params, self.draft_kv, toks, one, zero,
                        bt)
        def decode_half_args(b):
            """Decode-graph warmup operands (tokens .. penalty window) at
            rung ``b`` — shared by the plain decode graphs and the hybrid
            graphs' decode half so the two call shapes cannot drift
            apart."""
            return (jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
                    jnp.zeros((b, self.max_pages), jnp.int32),
                    jnp.zeros((b,), jnp.int32),
                    jnp.full((b,), -1, jnp.int32), self._next_key(),
                    jnp.zeros((b,), jnp.float32),
                    jnp.ones((b,), jnp.float32),
                    jnp.zeros((b,), jnp.int32),
                    jnp.full((b,), -1, jnp.int32),
                    jnp.ones((b,), jnp.float32), jnp.zeros((b,), jnp.int32),
                    jnp.full((b, PENALTY_WINDOW), -1, jnp.int32))

        if not warm_decode:
            jax.block_until_ready(self.kv)
            return time.perf_counter() - t0
        if self.spec_draft:
            b = ecfg.max_batch_size
            out = self._spec_jit(
                self.params, self.draft_params, self.kv, self.draft_kv,
                jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
                jnp.zeros((b, self.max_pages), jnp.int32),
                jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool),
                self._next_key(), jnp.zeros((b,), jnp.float32),
                jnp.ones((b,), jnp.float32), jnp.zeros((b,), jnp.int32))
            self.kv, self.draft_kv = out.kv, out.draft_kv
        else:
            decodes = [self._decode_multi_jit]
            if self._decode_one_jit is not self._decode_multi_jit:
                # The 1-step graph is a second full decode compile, but
                # decode_step()/decode_steps(max_steps=1) route to it
                # regardless of latency mode — warm it whenever it's a
                # distinct graph or a first single-step call pays a full
                # XLA compile mid-serving (ADVICE r3).
                decodes.append(self._decode_one_jit)
            # EVERY ladder rung compiles here: continuous batching moves
            # between rung graphs as occupancy changes, and a rung first
            # reached mid-serving must find its executable warm (the
            # mid-serving-compile failure mode ADVICE r3 flagged).
            for b in self.ladder:
                for decode in decodes:
                    self.kv, _, _, _ = decode(self.params, self.kv,
                                              *decode_half_args(b))
                if ecfg.decode_pipeline_depth > 1:
                    # Dispatch-ahead carry folds run jnp.where at [b] /
                    # [b, W] outside any jit — warm those tiny graphs
                    # per rung too.
                    carried = jnp.zeros((b,), bool)
                    tok = jnp.zeros((b,), jnp.int32)
                    win = jnp.full((b, PENALTY_WINDOW), -1, jnp.int32)
                    jnp.where(carried, tok, tok)
                    jnp.where(carried[:, None], win, win)
        if self.spec_ngram:
            # The verify-only graph compiles at EVERY ladder rung x
            # EVERY active verify width (the full γ+1 round AND the
            # narrow probe round; per-sequence adaptive γ below the
            # width lives in n_prop masking, never a new shape). The
            # γ=0 fallback rounds run the plain decode graphs warmed in
            # the else-branch above — between the three, no ngram-spec
            # dispatch can meet a cold executable mid-serving (the
            # test_ladder.py zero-compile pin, extended).
            for b in self.ladder:
                for width in self._spec_widths:
                    out = self._verify_jit(
                        self.params, self.kv, jnp.zeros((b,), jnp.int32),
                        jnp.zeros((b,), jnp.int32),
                        jnp.zeros((b, self.max_pages), jnp.int32),
                        jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool),
                        jnp.zeros((b, width - 1), jnp.int32),
                        jnp.zeros((b,), jnp.int32), self._next_key(),
                        jnp.zeros((b,), jnp.float32),
                        jnp.ones((b,), jnp.float32),
                        jnp.zeros((b,), jnp.int32),
                        jnp.ones((b,), jnp.float32),
                        jnp.zeros((b,), jnp.int32),
                        jnp.full((b, PENALTY_WINDOW), -1, jnp.int32))
                    self.kv = out.kv
        if ecfg.hybrid_prefill and not self.spec_enabled and warm_prefill:
            # One hybrid graph per REACHABLE prefill bucket per ladder
            # rung (the decode half dispatches at the current rung), so
            # the first long prompt under mixed traffic doesn't pay an
            # XLA compile mid-serving. Hybrid chunks never exceed the
            # chunk cap (budget pressure only shrinks them), so buckets
            # above bucket_for(cap) are unreachable and compiling them
            # would only slow boot — the compile count stays bounded at
            # reachable_buckets x rungs.
            bucket_cap = ecfg.bucket_for(
                min(ecfg.chunk_tokens_cap, ecfg.max_context))
            bt1 = jnp.zeros((1, self.max_pages), jnp.int32)
            one1, zero1 = jnp.ones((1,), jnp.int32), jnp.zeros((1,), jnp.int32)
            for bucket in ecfg.prefill_buckets:
                if bucket > ecfg.max_context or bucket > bucket_cap:
                    continue
                for b in self.ladder:
                    self.kv, _, _, _, _ = self._hybrid_jit(
                        self.params, self.kv,
                        jnp.zeros((1, bucket), jnp.int32), one1, zero1, bt1,
                        self._next_key(), jnp.zeros((1,), jnp.float32),
                        jnp.ones((1,), jnp.float32),
                        jnp.zeros((1,), jnp.int32),
                        jnp.full((1,), -1, jnp.int32),
                        jnp.ones((1,), jnp.float32),
                        jnp.zeros((1,), jnp.int32),
                        jnp.full((1, PENALTY_WINDOW), -1, jnp.int32),
                        *decode_half_args(b))
        jax.block_until_ready(self.kv)
        return time.perf_counter() - t0

    def embed(self, token_ids: List[int]) -> np.ndarray:
        """Mean-pooled final hidden state for one token sequence (the
        Ollama /api/embeddings backing). See embed_many."""
        return self.embed_many([token_ids])[0]

    # Max rows per embedding dispatch; lane counts pad to powers of two,
    # so compiles are bounded at ~5 batch shapes x sequence buckets and
    # one huge /api/embed list can't build an unbounded [N, S] forward.
    EMBED_CHUNK = 16

    def embed_many(self, batch: List[List[int]]) -> np.ndarray:
        """Mean-pooled final hidden states for N token sequences, batched
        into dense (cache-free) [n, S] forwards of at most EMBED_CHUNK
        rows — an /api/embed list input costs ceil(N/chunk) dispatches,
        not N. Sequence buckets are chosen per chunk; per-row length
        masks make padding invariant (pad sits causally after each row's
        valid tokens). Returns [N, d_model] f32."""
        from tpu_inference.models.common import make_dense_attn

        ecfg = self.engine_cfg
        if not batch:
            return np.zeros((0, self.model_cfg.d_model), np.float32)
        # Cap at the largest compiled bucket (bucket_for saturates there,
        # and the zero-padded buffer is bucket-sized).
        cap = min(ecfg.max_context - 1, ecfg.prefill_buckets[-1])
        rows = [list(ids)[-cap:] or [0] for ids in batch]
        with self._embed_lock:
            # Lazy singleton under a lock: concurrent first requests from
            # the server's worker threads must not each pay the compile.
            if self._embed_jit is None:
                cfg = self.model_cfg

                def fn(params, tokens, lengths):
                    s = tokens.shape[1]
                    pos = jnp.broadcast_to(
                        jnp.arange(s, dtype=jnp.int32)[None], tokens.shape)
                    hidden, _ = self.mod.forward_hidden(
                        params, cfg, tokens, pos, None,
                        make_dense_attn(cfg.sliding_window))
                    mask = (jnp.arange(s)[None, :] <
                            lengths[:, None])[..., None]
                    pooled = (jnp.sum(hidden * mask, axis=1)
                              / jnp.maximum(lengths[:, None], 1))
                    return pooled.astype(jnp.float32)

                self._embed_jit = jax.jit(fn)
        out = []
        for at in range(0, len(rows), self.EMBED_CHUNK):
            chunk = rows[at:at + self.EMBED_CHUNK]
            bucket = ecfg.bucket_for(max(len(r) for r in chunk))
            n = 1 << (len(chunk) - 1).bit_length()     # pad lanes to 2^k
            toks = np.zeros((n, bucket), np.int32)
            lengths = np.zeros((n,), np.int32)
            for i, r in enumerate(chunk):
                toks[i, :len(r)] = r
                lengths[i] = len(r)
            pooled = self._embed_jit(self.params, jnp.asarray(toks),
                                     jnp.asarray(lengths))
            out.append(np.asarray(pooled)[:len(chunk)])
        return np.concatenate(out, axis=0)

    def check_numerics(self) -> None:
        """Numerics sanitizer (SURVEY.md §5 race/sanitizer tier).

        Fails fast if any param leaf is non-finite, then runs one
        checkify'd forward (NaN/inf float checks compiled into the graph)
        on tiny inputs. Use at startup after loading a checkpoint, or from
        debug tooling after a suspect update. For always-on checking, run
        with ``--debug-nans`` (jax_debug_nans) instead — it re-runs any
        NaN-producing op un-jitted and pinpoints it.
        """
        from jax.experimental import checkify

        from tpu_inference.models.common import make_dense_attn

        leaves = jax.tree_util.tree_flatten_with_path(self.params)[0]
        bad = [jax.tree_util.keystr(path) for path, x in leaves
               if not bool(jnp.isfinite(x).all())]
        if bad:
            raise FloatingPointError(
                f"non-finite values in params at {bad}")

        cfg = self.model_cfg

        def fwd(params, tokens, positions):
            hidden, _ = self.mod.forward_hidden(
                params, cfg, tokens, positions, None,
                make_dense_attn(cfg.sliding_window))
            return self.mod.unembed(params, cfg, hidden)

        toks = jnp.zeros((1, 8), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32)[None], (1, 8))
        err, _ = jax.jit(checkify.checkify(
            fwd, errors=checkify.float_checks))(self.params, toks, pos)
        err.throw()

    def _check_degraded_modes(self) -> None:
        """Boot-time gate for known-degraded serving configurations
        (ADVICE r5): int4 KV on the Pallas backend on a real TPU without
        an on-chip Mosaic validation artifact has never had its
        nibble-packed kernels proven under the Mosaic compiler — warn
        loudly through the structured logger and hold the
        tpu_inf_degraded_mode gauge at 1 so dashboards see it."""
        if (self.attn_backend == "pallas"
                and self.engine_cfg.kv_quant == "int4"
                and jax.default_backend() == "tpu"
                and not int4_mosaic_validated()):
            self.telemetry.degraded_mode.set(1)
            telemetry.log_event(
                "degraded_mode", level="warning",
                reason="kv_quant=int4 + pallas on real TPU without an "
                       "on-chip Mosaic validation artifact "
                       "(benchmarks/results/mosaic_*.json with an int4 "
                       "check, or TPU_INF_INT4_VALIDATED=1)",
                model=self.model_cfg.name,
                attn_backend=self.attn_backend,
                kv_quant=self.engine_cfg.kv_quant)

    # -- Decode dispatch/bubble accounting (telemetry.py phase model).

    def _note_decode_entry(self, active_seqs: List["Sequence"]) -> float:
        """Record the host-side bubble since the last decode dispatch
        ended (if the decode streak is unbroken) and return the dispatch
        start timestamp."""
        now = time.perf_counter()
        last = self._last_decode_end
        self._pending_bubble = 0.0
        if last is not None and self.telemetry.enabled:
            gap = now - last
            self.telemetry.dispatch_bubble_s.observe(gap)
            self._pending_bubble = gap     # step-ledger host-bound input
            for seq in active_seqs:
                seq.bubble_s += gap
        return now

    def _note_decode_exit(self, t0: float,
                          active_seqs: List["Sequence"]) -> float:
        """Record one decode dispatch's host wall and refresh the bubble
        reference point. The streak survives only while some sequence is
        still live — cross-idle gaps are not bubbles. Returns the
        dispatch wall (the step ledger's device_s input)."""
        now = time.perf_counter()
        dt = now - t0
        tel = self.telemetry
        if tel.enabled:
            tel.decode_dispatch_s.observe(dt)
            tel.decode_dispatches.inc()
            for seq in active_seqs:
                seq.dispatch_wall_s += dt
        self._last_decode_end = (
            now if any(s is not None and not s.done for s in self.slots)
            else None)
        return dt

    def _next_key(self) -> jax.Array:
        self._step_count += 1
        return jax.random.fold_in(self._base_key, self._step_count)

    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def _prefill_tokens(self, seq: Sequence) -> List[int]:
        """Token stream the next (re)prefill must put into KV: the
        original prompt, plus — on a recompute-resume — every token
        generated before the preemption."""
        if seq.resume_base:
            return seq.prompt_tokens + seq.generated[:seq.resume_base]
        return seq.prompt_tokens

    def _pages_reserved(self, seq: Sequence) -> int:
        """Worst-case page need for admission control (capped at the
        per-sequence maximum, since ctx is clamped to max_context).

        With behind-window eviction the worst case is NOT prompt +
        max_new: live pages peak at the full prompt during prefill (no
        eviction until the first decode token), then drop to the
        window's span (+1 for the head page being written, +1 for
        window/page misalignment) — long-generation requests must not
        be queued for capacity they will never hold."""
        ecfg = self.engine_cfg
        base = self._prefill_tokens(seq)
        total = len(base) + seq.max_new_tokens - seq.resume_base
        need = kvc.pages_needed(total, ecfg.page_size)
        if self.swa_evict:
            # Dispatch-ahead can grant depth*K tokens of head pages
            # before eviction (at the fold) catches up — include them.
            win = self.model_cfg.sliding_window
            ahead = (ecfg.decode_steps_per_call
                     * max(1, ecfg.decode_pipeline_depth))
            window_span = -(-(win + ahead) // ecfg.page_size) + 2
            # The post-prefill transient: dispatch-ahead grants up to
            # ``ahead`` decode tokens (head pages allocated) BEFORE the
            # first fold-time eviction frees any behind-window page, so a
            # long-prompt sequence briefly holds its whole prompt PLUS
            # the dispatch-ahead burst (ADVICE r4: charging only the
            # prefill peak degrades to a decode stall under a
            # fully-committed pool).
            peak_tokens = min(len(base), ecfg.max_context)
            transient = kvc.pages_needed(
                min(peak_tokens + ahead, ecfg.max_context), ecfg.page_size)
            need = min(need, max(window_span, transient))
        return min(need, self.max_pages)

    def _pages_for_admission(self, seq: Sequence) -> int:
        """Pages a request is charged at admission. "reserve" mode —
        and the starvation guard's re-admission after
        preempt_max_per_request preemptions — charge the full worst
        case; "optimistic" charges the prompt footprint plus a small
        decode headroom, with watermark preemption as the safety net."""
        full = self._pages_reserved(seq)
        if (self.admission != "optimistic"
                or seq.preemptions >= self.engine_cfg.preempt_max_per_request):
            return full
        ecfg = self.engine_cfg
        prompt_pages = kvc.pages_needed(
            min(len(self._prefill_tokens(seq)), ecfg.max_context),
            ecfg.page_size)
        need = max(1, prompt_pages + ecfg.optimistic_headroom_pages)
        return min(full, need, self.max_pages)

    def _free_plus_evictable(self) -> int:
        n = self.allocator.num_free
        if self.prefix_cache is not None:
            n += self.prefix_cache.evictable
        return n

    def peek_prefix_pages(self, tokens: Sequence[int]) -> Tuple[int, int]:
        """(hit_pages, prompt_pages) the dp router scores this replica
        with: how many full KV pages of ``tokens`` this engine's prefix
        cache already holds, and how many pages the prompt needs in
        total. Mirrors _prefill_setup's truncation (keep the most recent
        max_context-1 tokens) and its max_tokens cap (the final prompt
        token is always recomputed for logits), so the peek counts
        exactly the pages a real prefill here could reuse.

        Side-effect-free and safe to call from any thread (PrefixCache.
        peek contract); the answer may be stale by the time the request
        prefills — the router tolerates that, the prefill re-checks.
        """
        ecfg = self.engine_cfg
        prompt_len = min(len(tokens), ecfg.max_context - 1)
        prompt_pages = kvc.pages_needed(prompt_len, ecfg.page_size)
        if self.prefix_cache is None or prompt_len <= 1:
            return 0, prompt_pages
        prompt = (tokens[-prompt_len:] if len(tokens) > prompt_len
                  else tokens)
        hit = self.prefix_cache.peek(prompt, max_tokens=prompt_len - 1)
        return hit, prompt_pages

    @property
    def pool_pressure(self) -> float:
        """1 - (free+evictable)/total: 0 = fully reclaimable, 1 = every
        page pinned by a running sequence (or chaos pressure)."""
        total = self.engine_cfg.num_pages - 1
        return 1.0 - self._free_plus_evictable() / max(total, 1)

    @property
    def under_pressure(self) -> bool:
        """Below the preemption low watermark — the router prefers
        replicas where this is False."""
        return (self._free_plus_evictable()
                < self.engine_cfg.preempt_watermark_pages)

    def set_page_pressure(self, n_pages: int) -> int:
        """Arm/disarm chaos_page_pressure: hold ``n_pages`` real pages
        out of the pool (clamped to what is currently free) so the
        exhaustion/preemption paths run deterministically on CPU.
        Returns the number of pages actually held.

        Mutates the allocator — call only from the engine thread (or
        while no scheduler is running); other threads use
        request_page_pressure and the engine loop applies it."""
        self.allocator.free(self._pressure_pages)
        self._pressure_pages = []
        n = max(0, min(int(n_pages), self.allocator.num_free))
        if n > 0:
            self._pressure_pages = self.allocator.allocate(n)
        self.chaos_page_pressure = len(self._pressure_pages)
        return self.chaos_page_pressure

    def request_page_pressure(self, n_pages: int) -> int:
        """Thread-safe arm/disarm request: stores the target (atomic
        int store); the scheduler loop applies it on the engine thread
        within one iteration. Returns the requested target."""
        n = max(0, int(n_pages))
        self._pressure_target = n
        return n

    def apply_pending_page_pressure(self) -> None:
        """Apply a cross-thread pressure request (engine thread only)."""
        target = self._pressure_target
        if target is not None:
            self._pressure_target = None
            self.set_page_pressure(target)

    def _allocate_reclaiming(self, n: int) -> List[int]:
        """Allocate n pages, evicting LRU prefix-cache pages on pressure —
        cached pages are reclaimable capacity, never reserved memory.
        With a host tier attached, the eviction DEMOTES pages to host
        RAM (engine/prefix_cache.py) instead of dropping their KV."""
        short = n - self.allocator.num_free
        if short > 0 and self.prefix_cache is not None:
            if self.host_pool is not None:
                # Demotes pay one device-stream sync per offload batch:
                # evict at least a swap chunk's worth so steady churn
                # amortizes the sync instead of paying it per page —
                # capped at the host tier's CAPACITY, so a tiny tier
                # never has its over-evicted extras destroyed (beyond
                # capacity they would land in the void, not the tier).
                short = max(short, min(kvc.SWAP_CHUNK,
                                       self.host_pool.capacity))
            self.prefix_cache.evict(short)
        return self.allocator.allocate(n)

    # ------------------------------------------------------------------
    # Tiered KV cache: device<->host page swaps (README "Tiered KV cache")
    # ------------------------------------------------------------------

    def _offload_pages(self, pages: List[int]) -> List["kvc.HostKVPage"]:
        """Demote-time device->host copy (the prefix cache's offload_fn):
        one bundled transfer for the whole victim batch, with swap
        telemetry. Engine thread only (reads the live pool)."""
        t0 = time.perf_counter()
        out = kvc.offload_pages(self.kv, pages)
        t1 = time.perf_counter()
        if out and self.host_pool is not None:
            # Pool accounting is part of the tier's stats surface (like
            # offloaded/restored totals) — NOT gated on telemetry.
            self.host_pool.note_swap_wall("out", t1 - t0)
        tel = self.telemetry
        if tel.enabled and out:
            tel.kv_swap_s.observe(t1 - t0)
            tel.kv_offload_pages.inc(len(out))
            nbytes = sum(hp.nbytes for hp in out)
            tel.kv_offload_bytes.inc(nbytes)
            # Swap-out spans have no single owning request (eviction
            # batches mix victims): they land in the recorder's
            # maintenance lane of the Chrome timeline instead.
            tel.recorder.add_maintenance("kv_swap_out", t0, t1,
                                         pages=len(out), bytes=nbytes)
        return out

    def _restore_batch(self, fresh: List[int],
                       entries: List["kvc.HostKVPage"],
                       trace_id: str = "") -> None:
        """Scatter host page copies into freshly allocated device pages
        (async dispatch — a following prefill chains behind it on
        device) and record swap telemetry. ``trace_id`` attributes the
        swap-in span to the request that triggered it (empty = a
        maintenance-lane span)."""
        t0 = time.perf_counter()
        self.kv = kvc.restore_pages(self.kv, fresh, entries)
        t1 = time.perf_counter()
        if self.host_pool is not None:
            # Pool accounting is part of the tier's stats surface —
            # NOT gated on telemetry (offloaded/restored totals aren't).
            self.host_pool.note_swap_wall("in", t1 - t0)
        tel = self.telemetry
        if tel.enabled:
            tel.kv_swap_s.observe(t1 - t0)
            tel.kv_restore_pages.inc(len(fresh))
            nbytes = sum(e.nbytes for e in entries)
            tel.kv_restore_bytes.inc(nbytes)
            if trace_id:
                tel.recorder.add("kv_swap_in", trace_id, t0, t1,
                                 pages=len(fresh), bytes=nbytes)
            else:
                tel.recorder.add_maintenance("kv_swap_in", t0, t1,
                                             pages=len(fresh),
                                             bytes=nbytes)

    def _restore_host_entries(self, pages: List[Optional[int]],
                              host_entries,
                              trace_id: str = "") -> List[int]:
        """Fill the host-tier slots of a tiered lookup result: allocate
        fresh device pages, swap the host copies in, and publish the
        restored digests back into the HBM tier (promote). On
        allocation failure every reference taken by the lookup is
        undone (HBM refs freed, host entries readmitted) and the
        MemoryError propagates — same contract as a cold allocation
        shortfall in _prefill_setup."""
        if not host_entries:
            return list(pages)
        try:
            fresh = self._allocate_reclaiming(len(host_entries))
        except MemoryError:
            self.allocator.free([p for p in pages if p is not None])
            self.prefix_cache.readmit_host(
                [(d, e) for _, d, e in host_entries])
            raise
        self._restore_batch(fresh, [e for _, _, e in host_entries],
                            trace_id=trace_id)
        out = list(pages)
        for (i, digest, _), page in zip(host_entries, fresh):
            out[i] = page
            self.prefix_cache.promote(digest, page)
        return out

    def _seq_digests(self, seq: Sequence,
                     prompt: List[int]) -> List[bytes]:
        """Chain digests of ``prompt`` (the truncated prefill stream),
        computed ONCE per fresh request and cached on the Sequence (the
        router's scoring pass may have filled them already — the
        triple-hash fix). Resume streams include generated tokens and
        may have shifted the truncation window, so they hash into their
        OWN cache slot, valid until the next preemption (preempt()
        clears it) — a queue-waiting resume being prefetched over
        several partial passes must not rehash a long stream per pass."""
        from tpu_inference.engine.prefix_cache import _chain_hashes
        if seq.resume_base:
            if seq.resume_digests is None:
                seq.resume_digests = _chain_hashes(
                    prompt, self.engine_cfg.page_size)
            return seq.resume_digests
        if seq.prefix_digests is None:
            seq.prefix_digests = _chain_hashes(prompt,
                                               self.engine_cfg.page_size)
        return seq.prefix_digests

    def prefetch_host_hits(self, seq: Sequence) -> int:
        """Queue-wait swap-in: restore a WAITING request's host-tier
        pages into cache-owned device pages, so its eventual admission
        sees plain HBM hits and prefill starts warm — the swap overlaps
        the queue wait instead of sitting in TTFT.

        Only genuinely free pages are used (prefetch never evicts
        someone else's warmth), the restore dispatch is async, and the
        promoted pages are ordinary evictable cache entries — pressure
        can re-demote them if the request never admits. Partial
        restores (free list shorter than the host hits) keep the
        request eligible for another pass next loop iteration.
        Returns pages promoted. Engine thread only."""
        if (self.prefix_cache is None or self.host_pool is None
                or seq.host_prefetched or seq.done):
            return 0
        free = self.allocator.num_free
        if free <= 0:
            # Retry when pages free up — checked BEFORE any prompt/hash
            # work: this runs every scheduler iteration while the head
            # request waits, and a full pool (the watermark-pressure
            # steady state) must cost O(1), not a rehash of a multi-
            # thousand-token resume stream.
            return 0
        ecfg = self.engine_cfg
        prompt = self._prefill_tokens(seq)[-(ecfg.max_context - 1):]
        if len(prompt) <= 1:
            seq.host_prefetched = True
            return 0
        digests = self._seq_digests(seq, prompt)
        limit = (len(prompt) - 1) // ecfg.page_size
        taken = self.prefix_cache.take_host_matches(digests, limit)
        if not taken:
            seq.host_prefetched = True
            return 0
        complete = len(taken) <= free
        if not complete:
            # Keep the FRONT of the run (later pages are unusable
            # without the earlier ones) and return the rest.
            self.prefix_cache.readmit_host(taken[free:])
            taken = taken[:free]
        fresh = self.allocator.allocate(len(taken))
        self._restore_batch(fresh, [e for _, e in taken],
                            trace_id=seq.trace_id or str(seq.request_id))
        for (digest, _), page in zip(taken, fresh):
            self.prefix_cache.adopt(digest, page)
        if complete:
            seq.host_prefetched = True
        return len(taken)

    # ------------------------------------------------------------------
    # KV page migration (README "Process fleet"): drain-time export of a
    # live sequence's KV pages in the host serialization layout, and
    # import of a sibling replica's export into this engine's host tier.
    # ------------------------------------------------------------------

    def _tokens_in_kv(self, seq: Sequence, drop_last: bool = False
                      ) -> List[int]:
        """The tokens actually resident in the sequence's KV pages, in
        page order: the prefill stream under the same max_context
        truncation the prefill used, plus the generated suffix
        (``drop_last`` excludes the just-sampled token the cache
        publish runs before writing back). The ONE stream
        reconstruction shared by _publish_to_cache, export_sequence_kv,
        and export_sequence_kv_live — their chain digests must never
        diverge."""
        base = self._prefill_tokens(seq)[-(self.engine_cfg.max_context
                                           - 1):]
        gen = seq.generated[seq.resume_base:]
        return base + (gen[:-1] if drop_last else gen)

    def export_sequence_kv(self, seq: Sequence
                           ) -> Tuple[List[bytes], List["kvc.HostKVPage"]]:
        """Drain-time migration export: (chain digests, host page
        copies) for the sequence's full, settled KV pages — prompt plus
        generated-so-far, exactly the stream a destination's
        recompute-resume prefill will hash, so the import lands as
        host-tier hits there and admission becomes a swap-in-resume.

        Only the contiguous run of full, non-SWA-evicted pages from
        page 0 exports (a chain hit must be contiguous from the start;
        the partial last page recomputes at the destination). Call with
        the scheduler stopped and the pipeline drained — it reads the
        live pool."""
        from tpu_inference.engine.prefix_cache import _chain_hashes
        if not seq.pages or seq.ctx_len <= 0:
            return [], []
        ecfg = self.engine_cfg
        in_kv = self._tokens_in_kv(seq)[:seq.ctx_len]
        digests = _chain_hashes(in_kv, ecfg.page_size)
        n = min(len(digests), len(seq.pages))
        run = 0
        while run < n and seq.pages[run] != 0:
            run += 1
        if run == 0:
            return [], []
        host = self._offload_pages(seq.pages[:run])
        self.migrate_out_pages += len(host)
        self.migrate_out_bytes += sum(hp.nbytes for hp in host)
        return digests[:run], host

    def export_sequence_kv_live(self, seq: Sequence
                                ) -> Tuple[List[bytes],
                                           List["kvc.HostKVPage"], int]:
        """P/D handoff export (README "P/D disaggregation"): the settled
        KV of a LIVE sequence — (full-page chain digests, host page
        copies, ctx_len). Unlike the drain export, the page list covers
        EVERY page holding the first ctx_len tokens, INCLUDING the
        partial final page: the destination restores it verbatim (its
        trailing rows are dead weight no reader past ctx_len touches)
        and resumes decode with zero recomputed tokens, where the
        drain/migrate path stops at the last full page and recomputes
        the remainder. Digests still cover only the full pages (a chain
        digest is defined on full pages) for host-tier import fallback.

        Returns ([], [], 0) when the sequence has no exportable KV
        (empty, or SWA-evicted pages punch holes in the run) — the
        caller then keeps the sequence local instead of handing off.
        Engine thread only; the offload's device_get orders after any
        in-flight dispatch by data dependency."""
        from tpu_inference.engine.prefix_cache import _chain_hashes
        if not seq.pages or seq.ctx_len <= 0:
            return [], [], 0
        ecfg = self.engine_cfg
        n_pages = -(-seq.ctx_len // ecfg.page_size)
        pages = seq.pages[:n_pages]
        if len(pages) < n_pages or any(p == 0 for p in pages):
            return [], [], 0
        in_kv = self._tokens_in_kv(seq)[:seq.ctx_len]
        digests = _chain_hashes(in_kv, ecfg.page_size)
        host = self._offload_pages(pages)
        self.handoffs_out += 1
        self.handoff_out_pages += len(host)
        return digests[:seq.ctx_len // ecfg.page_size], host, seq.ctx_len

    def adopt_sequence(self, seq: Sequence) -> int:
        """P/D handoff adoption (engine thread, at admission): restore
        the handoff's KV pages (seq.adopt_kv, incl. the partial final
        page) straight into freshly allocated device pages, bind a slot,
        and resume DECODE — no prefill dispatch runs, so nothing is
        recomputed and greedy continuation is byte-identical to the
        mixed topology by construction (same pool bytes, same last
        token). Raises on a malformed blob or pool shortfall; the
        scheduler's fallback then clears adopt_kv and recompute-resumes
        through the ordinary prefill path instead."""
        host_pages, ctx_len = seq.adopt_kv
        ecfg = self.engine_cfg
        expected = -(-ctx_len // ecfg.page_size)
        if ctx_len <= 0 or len(host_pages) != expected:
            raise ValueError(
                f"handoff blob has {len(host_pages)} pages for "
                f"ctx_len={ctx_len} (need {expected})")
        slot = self.free_slots()[0]
        seq.admit_idx = self._admit_counter
        self._admit_counter += 1
        fresh = self._allocate_reclaiming(len(host_pages))
        try:
            self._restore_batch(fresh, host_pages,
                                trace_id=seq.trace_id
                                or str(seq.request_id))
        except BaseException:
            self.allocator.free(fresh)
            raise
        seq.pages = fresh
        seq.pages_version += 1
        seq.ctx_len = ctx_len
        seq.slot = slot
        seq.adopt_kv = None
        # The whole resume stream (prompt + the tokens the handoff
        # replays) arrives as settled KV or recorded tokens — nothing
        # recomputes. cached_tokens reports exactly that to the
        # router's reused-vs-recomputed accounting.
        seq.cached_tokens = min(ctx_len + seq.resume_base,
                                ecfg.max_context - 1)
        seq.host_restored_pages += len(host_pages)
        now = time.perf_counter()
        seq.prefill_start = seq.prefill_start or now
        seq.first_token_time = now
        seq.adopted = True
        self.adoptions_in += 1
        self.swap_in_resumes += 1
        self.slots[slot] = seq
        return slot

    def request_import_host(self, entries) -> threading.Event:
        """Queue migrated (digest, HostKVPage) entries for adoption into
        the host tier. Any thread; returns an Event set once the engine
        loop has applied the import — the worker's import-kv RPC replies
        only then, so a subsequently submitted request is guaranteed to
        see the pages at prefill time."""
        done = threading.Event()
        with self._pending_imports_lock:
            self._pending_imports.append((list(entries), done))
        return done

    def apply_pending_imports(self) -> None:
        """Adopt queued migration imports (engine thread — called by the
        scheduler loop right before admission, next to
        apply_pending_page_pressure). No-ops without a host tier, but
        always signals completion so RPC callers never hang."""
        with self._pending_imports_lock:
            pending, self._pending_imports = self._pending_imports, []
        for entries, done in pending:
            try:
                if self.prefix_cache is not None and self.host_pool is not None:
                    # Pool-delta accounting: import_host may SKIP
                    # already-resident digests anywhere in the list, so
                    # summing a prefix of ``entries`` would charge the
                    # wrong pages' bytes.
                    bytes_before = self.host_pool.import_bytes_total
                    self.migrate_in_pages += self.prefix_cache.import_host(
                        entries)
                    self.migrate_in_bytes += (
                        self.host_pool.import_bytes_total - bytes_before)
            finally:
                done.set()

    def _grant_decode_steps(self, seq: Sequence, k_steps: int,
                            pred_ctx: Optional[int] = None,
                            pred_done: Optional[int] = None) -> int:
        """Steps this lane may advance in one fused call — folds the
        generation budget, the context cap, and KV-page headroom — and
        allocates the pages it needs. ``pred_*`` override ctx/generated
        with predicted values while dispatch-ahead calls are in flight.
        Shared by the sync and pipelined decode paths so grant semantics
        can't diverge."""
        ecfg = self.engine_cfg
        ctx = seq.ctx_len if pred_ctx is None else pred_ctx
        done = len(seq.generated) if pred_done is None else pred_done
        budget = seq.max_new_tokens - done
        # From ctx c the host keeps at most max_context - 1 - c tokens
        # (_maybe_finish caps at ctx + 1 >= max_context); granting more
        # would waste a forward pass + KV write per capped sequence.
        room = ecfg.max_context - 1 - ctx
        steps = max(0, min(k_steps, budget, room))
        if steps > 0:
            need = kvc.pages_needed(steps, ecfg.page_size, already=ctx)
            grantable = self._free_plus_evictable()
            if need > grantable:
                # Pool pressure: advance only as far as the slack in the
                # current last page plus the pages we can still grant.
                slack = len(seq.pages) * ecfg.page_size - ctx
                steps = min(steps, slack + grantable * ecfg.page_size)
                need = (kvc.pages_needed(steps, ecfg.page_size,
                                         already=ctx)
                        if steps > 0 else 0)
            if need > 0:
                seq.pages.extend(self._allocate_reclaiming(need))
        return steps

    def _fold_lane(self, seq: Sequence, toks) -> List[int]:
        """Fold device-produced tokens (iterable of ints, -1 = no token)
        into one sequence's host state; stops at done/-1. Shared by every
        decode sync path."""
        got: List[int] = []
        for tok in toks:
            if seq.done or tok < 0:
                break
            seq.ctx_len += 1
            seq.generated.append(tok)
            if seq.first_token_time == 0.0:
                seq.first_token_time = time.perf_counter()
            self._maybe_finish(seq, tok)
            got.append(tok)
        return got

    def can_admit(self, seq: Sequence) -> bool:
        return bool(self.free_slots()) and (
            self._free_plus_evictable() >= self._pages_for_admission(seq))

    def can_ever_admit(self, seq: Sequence) -> bool:
        """False if the request exceeds the pool even when fully idle."""
        return self._pages_reserved(seq) <= self.engine_cfg.num_pages - 1

    def _block_table_array(self, pages: List[int]) -> np.ndarray:
        bt = np.zeros((self.max_pages,), np.int32)
        bt[:len(pages)] = pages
        return bt

    def _prefill_setup(self, seq: Sequence, slot: int) -> List[int]:
        """Allocate pages (with prefix-cache reuse), bind the slot, and
        return the (possibly truncated) prompt to prefill."""
        ecfg = self.engine_cfg
        # Keep the most recent tokens of over-long prompts (leave room
        # for at least one generated token). On a recompute-resume the
        # "prompt" is the original prompt plus everything generated
        # before the preemption.
        prompt = self._prefill_tokens(seq)[-(ecfg.max_context - 1):]
        seq.admit_idx = self._admit_counter
        self._admit_counter += 1
        if seq.resume_base:
            self.resumes_total += 1
        # Prefix-cache hit: reuse full pages of an identical prior prefix
        # and skip their prefill compute — HBM hits are shared in place;
        # host-tier hits swap back into freshly allocated device pages
        # before the prefill resumes past them. Always recompute at
        # least the final prompt token — its logits seed the first
        # sampled token.
        shared: List[int] = []
        n_restored = 0
        if self.prefix_cache is not None:
            pages, host_entries, seq.cached_tokens = self.prefix_cache.lookup(
                prompt, max_tokens=len(prompt) - 1,
                digests=self._seq_digests(seq, prompt))
            shared = self._restore_host_entries(
                pages, host_entries,
                trace_id=seq.trace_id or str(seq.request_id))
            n_restored = len(host_entries)
        n_new = kvc.pages_needed(len(prompt), ecfg.page_size) - len(shared)
        try:
            seq.pages = shared + self._allocate_reclaiming(n_new)
        except MemoryError:
            self.allocator.free(shared)
            raise
        seq.pages_version += 1        # staging block-table rows re-key
        # Swap accounting AFTER the allocation can no longer fail: a
        # MemoryError-and-requeue retry must not double-count one
        # logical resume/restore in the span and counters.
        seq.host_restored_pages += n_restored
        if seq.resume_base and seq.cached_tokens:
            # The preemption's published pages survived (in HBM or via
            # the host tier): this resume swaps them in instead of
            # recomputing the whole prompt+generated stream.
            self.swap_in_resumes += 1
        seq.slot = slot
        seq.prefill_start = time.perf_counter()
        return prompt

    def _prefill_finish(self, seq: Sequence, prompt: List[int],
                        first: int) -> None:
        """Common post-prefill bookkeeping for one sequence."""
        seq.ctx_len = len(prompt)
        seq.generated.append(first)
        if seq.first_token_time == 0.0:
            # Resume prefills keep the ORIGINAL first-token time: the
            # client already received earlier tokens.
            seq.first_token_time = time.perf_counter()
        self.slots[seq.slot] = seq
        self._maybe_finish(seq, first)

    def _use_sp(self, offset: int, chunk_len: int, prompt_len: int,
                bucket: int) -> bool:
        """Ring-attention prefill is eligible for fresh single-chunk
        prompts on an sp>1 mesh (self-attention only, no cached prefix)."""
        return (self.sp > 1 and offset == 0 and chunk_len == prompt_len
                and bucket % self.sp == 0)

    def _stage_chunk_arrays(self, seq: Sequence, prompt: List[int],
                            offset: int, chunk_cap: int) -> dict:
        """Host arrays for one prefill chunk at ``offset`` — the SINGLE
        staging point shared by the serial dispatch (_prefill_one_chunk)
        and hybrid staging (_stage_hybrid_chunk / _stage_chunk_only_call),
        so the two scheduling modes cannot drift apart and byte-equality
        holds by construction.

        First sampled token's penalty window = the prompt tail (only the
        final chunk's sample is kept, so mid-chunk windows don't matter).
        """
        chunk = prompt[offset:offset + chunk_cap]
        bucket = self.engine_cfg.bucket_for(len(chunk))
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :len(chunk)] = chunk
        top_k, rseed = self._sampling_arrays(seq)
        rpen, rlast = self._penalty_arrays(seq)
        win = np.full((1, PENALTY_WINDOW), -1, np.int32)
        if rpen != 1.0:
            win[0] = self._penalty_window_row(seq)
        return {
            "seq": seq, "prompt": prompt, "chunk_tokens": len(chunk),
            "bucket": bucket, "tokens": toks,
            "prompt_len": np.asarray([len(chunk)], np.int32),
            "prefix_len": np.asarray([offset], np.int32),
            "block_table": self._block_table_array(seq.pages)[None],
            "temp": np.asarray([seq.temperature], np.float32),
            "top_p": np.asarray([seq.top_p], np.float32),
            "top_k": np.asarray([top_k], np.int32),
            "seed": np.asarray([rseed], np.int32),
            "rpen": np.asarray([rpen], np.float32),
            "rlast": np.asarray([rlast], np.int32),
            "window": win,
        }

    def _chunk_device_args(self, st: dict) -> tuple:
        """Device operands for a staged chunk, in _prefill_fn order
        (tokens .. penalty window, with a fresh key) — shared by every
        dispatch site that consumes _stage_chunk_arrays."""
        return (jnp.asarray(st["tokens"]), jnp.asarray(st["prompt_len"]),
                jnp.asarray(st["prefix_len"]),
                jnp.asarray(st["block_table"]), self._next_key(),
                jnp.asarray(st["temp"]), jnp.asarray(st["top_p"]),
                jnp.asarray(st["top_k"]), jnp.asarray(st["seed"]),
                jnp.asarray(st["rpen"]), jnp.asarray(st["rlast"]),
                jnp.asarray(st["window"]))

    def _prefill_one_chunk(self, seq: Sequence, prompt: List[int],
                           offset: int) -> Tuple[int, Any]:
        """Run one prefill chunk at ``offset``; returns (next_offset,
        sampled-token device array for the chunk)."""
        ecfg = self.engine_cfg
        chunk_cap = ecfg.chunk_tokens_cap
        st = self._stage_chunk_arrays(seq, prompt, offset, chunk_cap)
        use_sp = self._use_sp(offset, st["chunk_tokens"], len(prompt),
                              st["bucket"])
        prefill = self._prefill_sp_jit if use_sp else self._prefill_jit
        # Decode lanes active right now sit stalled behind this serial
        # chunk — exactly the stall hybrid steps remove, so the
        # histogram is scoped to CHUNKED-prefill dispatches (single-
        # chunk admission stalls are untouched by hybrid stepping and
        # already visible in prefill_dispatch_s). Mid-prefill sequences
        # are excluded by active_sequences, so this counts only victims.
        stalled = bool(self.active_sequences())
        t0 = time.perf_counter()
        self._last_decode_end = None     # prefill breaks the decode streak
        self.kv, tok, _ = prefill(self.params, self.kv,
                                  *self._chunk_device_args(st))
        if self.spec_draft:
            # Mirror the chunk into the draft model's KV (same pages).
            self.draft_kv = self._draft_prefill_jit(
                self.draft_params, self.draft_kv,
                jnp.asarray(st["tokens"]), jnp.asarray(st["prompt_len"]),
                jnp.asarray(st["prefix_len"]),
                jnp.asarray(st["block_table"]))
        if self.telemetry.enabled:
            dt = time.perf_counter() - t0
            self.telemetry.prefill_dispatch_s.observe(dt)
            self.telemetry.prefill_dispatches.inc()
            if stalled:
                # The stall histogram must record the chunk's DEVICE
                # wall, not the (async on TPU) enqueue overhead dt —
                # blocking here costs nothing extra: the stalled lanes
                # can't advance until this chunk completes anyway.
                jax.block_until_ready(tok)
                self.telemetry.decode_stall_during_prefill_s.observe(
                    time.perf_counter() - t0)
            seq.dispatch_wall_s += dt
            # Per-chunk trace span (README "Observability" span schema):
            # children of the request's prefill span, so a long prompt's
            # chunk cadence is visible on the trace timeline.
            self.telemetry.recorder.add(
                "prefill_chunk", seq.trace_id or str(seq.request_id),
                t0, t0 + dt, parent="prefill",
                offset=int(offset), tokens=int(st["chunk_tokens"]))
            c = st["chunk_tokens"]
            final = offset + c >= len(prompt)
            self._ledger_push(
                "prefill_chunk", rung=0, slots=1,
                tokens=1 if final else 0, chunk_tokens=c,
                device_s=dt, kv_read=c * offset + c * (c + 1) // 2,
                compile_event=st["bucket"]
                not in self._prefill_buckets_seen)
            self._prefill_buckets_seen.add(st["bucket"])
        return offset + st["chunk_tokens"], tok

    def _prefill_chunked(self, seq: Sequence, prompt: List[int]) -> None:
        """Serial (one-lane) prefill; chunks prompts that exceed the
        largest bucket. Each chunk attends to itself + all cached tokens
        (prefix_len); only the final chunk's sampled token is kept."""
        offset = seq.cached_tokens
        tok = None
        while offset < len(prompt):
            offset, tok = self._prefill_one_chunk(seq, prompt, offset)
        self._prefill_finish(seq, prompt, int(tok[0]))

    # -- Incremental (interleavable) prefill: one chunk per call, so the
    # -- scheduler can run decode steps between a long prompt's chunks
    # -- instead of stalling the whole batch for the full prefill.

    def prefill_begin(self, seq: Sequence,
                      slot: Optional[int] = None) -> int:
        """Set up an incremental prefill (pages, slot, cache lookup);
        drive it with prefill_step(). Returns the slot.

        The slot binds into ``self.slots`` HERE, not at finish: batch
        admission re-reads free_slots() between this sequence's chunks
        (that interleaving is the point of incremental prefill), and an
        unreserved slot would be handed to a second sequence, which the
        finishing prefill then silently overwrites — orphaning it.
        ``active_sequences`` excludes mid-prefill slots, so decode never
        touches the half-filled sequence."""
        if slot is None:
            slot = self.free_slots()[0]
        seq.prefill_prompt = self._prefill_setup(seq, slot)
        seq.prefill_offset = seq.cached_tokens
        self.slots[slot] = seq
        return slot

    def prefill_step(self, seq: Sequence) -> bool:
        """Run ONE chunk of an incremental prefill; True when complete
        (first token sampled and bookkeeping done)."""
        prompt = seq.prefill_prompt
        assert prompt is not None, "prefill_step without prefill_begin"
        self._chaos_step_gate()
        seq.prefill_offset, tok = self._prefill_one_chunk(
            seq, prompt, seq.prefill_offset)
        if seq.prefill_offset < len(prompt):
            return False
        self._prefill_finish(seq, prompt, int(tok[0]))
        seq.prefill_prompt = None
        return True

    def prefill(self, seq: Sequence, slot: Optional[int] = None) -> int:
        """Admit a sequence: allocate pages, run the prefill graph (chunked
        when the prompt exceeds the largest bucket), sample the first token.
        Returns the slot index."""
        if slot is None:
            slot = self.free_slots()[0]
        prompt = self._prefill_setup(seq, slot)
        self._prefill_chunked(seq, prompt)
        return slot

    def _prefill_run_batched(self, group: List[Tuple[Sequence, List[int]]],
                             bucket: int, use_sp: bool) -> None:
        """One multi-lane prefill dispatch: P sequences, same bucket.

        Lanes are padded up to a compiled batch size; dummy lanes carry
        prompt_len=1 with an all-zero block table, so their single write
        lands on the trash page and their sampled token is discarded.
        """
        ecfg = self.engine_cfg
        p = next(s for s in self._prefill_batch_sizes if s >= len(group))
        toks = np.zeros((p, bucket), np.int32)
        plen = np.ones((p,), np.int32)
        pref = np.zeros((p,), np.int32)
        bts = np.zeros((p, self.max_pages), np.int32)
        temps = np.zeros((p,), np.float32)
        top_ps = np.ones((p,), np.float32)
        top_ks = np.zeros((p,), np.int32)
        seeds = np.full((p,), -1, np.int32)
        rpens = np.ones((p,), np.float32)
        rlasts = np.zeros((p,), np.int32)
        wins = np.full((p, PENALTY_WINDOW), -1, np.int32)
        for i, (seq, prompt) in enumerate(group):
            chunk = prompt[seq.cached_tokens:]
            toks[i, :len(chunk)] = chunk
            plen[i] = len(chunk)
            pref[i] = seq.cached_tokens
            bts[i] = self._block_table_array(seq.pages)
            temps[i] = seq.temperature
            top_ps[i] = seq.top_p
            top_ks[i], seeds[i] = self._sampling_arrays(seq)
            rpens[i], rlasts[i] = self._penalty_arrays(seq)
            if rpens[i] != 1.0:
                wins[i] = self._penalty_window_row(seq)
        prefill = self._prefill_sp_jit if use_sp else self._prefill_jit
        t0 = time.perf_counter()
        self._last_decode_end = None     # prefill breaks the decode streak
        self.kv, tok, _ = prefill(
            self.params, self.kv, jnp.asarray(toks), jnp.asarray(plen),
            jnp.asarray(pref), jnp.asarray(bts), self._next_key(),
            jnp.asarray(temps), jnp.asarray(top_ps), jnp.asarray(top_ks),
            jnp.asarray(seeds), jnp.asarray(rpens), jnp.asarray(rlasts),
            jnp.asarray(wins))
        if self.spec_draft:
            self.draft_kv = self._draft_prefill_jit(
                self.draft_params, self.draft_kv, jnp.asarray(toks),
                jnp.asarray(plen), jnp.asarray(pref), jnp.asarray(bts))
        toks_out = np.asarray(tok)
        if self.telemetry.enabled:
            dt = time.perf_counter() - t0    # includes the token readback
            self.telemetry.prefill_dispatch_s.observe(dt)
            self.telemetry.prefill_dispatches.inc()
            for seq, _ in group:
                seq.dispatch_wall_s += dt
            graph_key = (bucket, p, use_sp)
            self._ledger_push(
                "prefill_chunk", rung=0, slots=len(group),
                tokens=len(group),
                chunk_tokens=int(plen[:len(group)].sum()),
                device_s=dt,
                kv_read=int((plen[:len(group)] * pref[:len(group)]
                             + plen[:len(group)]
                             * (plen[:len(group)] + 1) // 2).sum()),
                compile_event=graph_key not in self._prefill_buckets_seen)
            self._prefill_buckets_seen.add(graph_key)
        for i, (seq, prompt) in enumerate(group):
            self._prefill_finish(seq, prompt, int(toks_out[i]))

    def prefill_many(self, seqs: List[Sequence]) -> None:
        """Admit several sequences, batching same-bucket single-chunk
        prefills into one device dispatch (a burst of arrivals no longer
        pays one serial [1, S] forward each — the MXU sees [P, S]).

        Prompts needing multiple chunks fall back to the serial path.
        """
        self._chaos_step_gate()
        ecfg = self.engine_cfg
        chunk_cap = ecfg.chunk_tokens_cap
        slots = self.free_slots()
        if len(slots) < len(seqs):
            # zip truncation would silently drop (and strand) requests.
            raise RuntimeError(
                f"prefill_many: {len(seqs)} sequences but only "
                f"{len(slots)} free slots")
        staged: List[Tuple[Sequence, List[int]]] = []
        for seq, slot in zip(seqs, slots):
            staged.append((seq, self._prefill_setup(seq, slot)))
        groups: Dict[Tuple[int, bool], List[Tuple[Sequence, List[int]]]] = {}
        for seq, prompt in staged:
            rest = len(prompt) - seq.cached_tokens
            if rest <= chunk_cap:
                bucket = ecfg.bucket_for(rest)
                use_sp = self._use_sp(seq.cached_tokens, rest, len(prompt),
                                      bucket)
                groups.setdefault((bucket, use_sp), []).append((seq, prompt))
            else:
                self._prefill_chunked(seq, prompt)
        cap = self._prefill_batch_sizes[-1]
        for (bucket, use_sp), group in groups.items():
            for i in range(0, len(group), cap):
                self._prefill_run_batched(group[i:i + cap], bucket, use_sp)

    def _chaos_step_gate(self) -> None:
        """Engine-level fault injection, mirroring the HTTP _chaos_gate:
        runs at the top of every prefill/decode dispatch. The wedge
        sleeps BEFORE the failure roll so a wedged-and-failing replica
        exercises the watchdog first, like a real hung-then-killed call."""
        if self.chaos_step_wedge_s > 0:
            time.sleep(self.chaos_step_wedge_s)
        if (self.chaos_step_failure_rate > 0
                and _chaos_random.random() < self.chaos_step_failure_rate):
            raise ChaosStepError("chaos: injected engine step failure")

    def _maybe_finish(self, seq: Sequence, tok: int) -> None:
        if seq.eos_token_id is not None and tok == seq.eos_token_id:
            seq.done, seq.finish_reason = True, "stop"
        elif len(seq.generated) >= seq.max_new_tokens:
            seq.done, seq.finish_reason = True, "length"
        elif seq.ctx_len + 1 >= self.engine_cfg.max_context:
            seq.done, seq.finish_reason = True, "length"
        if seq.done:
            seq.finish_time = time.perf_counter()
        elif self.swa_evict:
            self._evict_behind_window(seq)

    def _evict_behind_window(self, seq: Sequence) -> None:
        """Free KV pages entirely behind the sliding window; the block-
        table slot becomes the trash page (0). No windowed reader ever
        touches them: the Pallas kernels' page grids start at the
        window's first page, and the dense path gathers-then-masks.
        In-flight dispatch-ahead calls staged with higher predicted ctx
        have even later window starts, so reuse-after-free can't race a
        reader. The per-sequence cursor makes total work O(pages freed)
        over a sequence's life, not O(pages) per accepted token."""
        win = self.model_cfg.sliding_window
        first_needed = max(0, seq.ctx_len - win) // self.engine_cfg.page_size
        j = seq.evicted_pages
        while j < min(first_needed, len(seq.pages)):
            if seq.pages[j]:
                self.allocator.free([seq.pages[j]])
                seq.pages[j] = 0
            j += 1
        seq.evicted_pages = j

    def _publish_to_cache(self, seq: Sequence) -> None:
        """Publish a sequence's full pages (prompt + generated history)
        to the prefix cache, so a follow-up turn resending the
        conversation — or a preempted sequence's recompute-resume —
        reuses them instead of re-prefilling."""
        if self.prefix_cache is None or not seq.pages:
            return
        # drop_last: the just-sampled token isn't written back yet.
        in_kv = self._tokens_in_kv(seq, drop_last=True)
        # Reuse the request's one hash pass (router or admission): only
        # the generated-suffix pages are hashed here. Resume streams may
        # have shifted the truncation window — they rehash.
        digests = None if seq.resume_base else seq.prefix_digests
        self.prefix_cache.insert(in_kv[:seq.ctx_len], seq.pages,
                                 digests=digests)
        self._publish_to_fabric(seq, digests)

    def _publish_to_fabric(self, seq: Sequence, digests) -> None:
        """Ship the settled prefix run to the fleet fabric pool (README
        "KV fabric"): the contiguous full-page prompt prefix, keyed by
        its chain digests, offloaded to host layout and handed to the
        armed publish callable. Bounded below by
        fabric_publish_min_pages (tiny prefixes aren't worth fleet
        space) and deduped against _fabric_published so steady traffic
        over one system prompt serializes it once, not per release."""
        if self.fabric_publish is None or not digests:
            return
        full = len(self._tokens_in_kv(seq, drop_last=True)[:seq.ctx_len]) \
            // self.engine_cfg.page_size
        k = min(len(digests), full, len(seq.pages))
        while k > 0 and not all(seq.pages[i] for i in range(k)):
            k -= 1
        if k < max(1, self.fabric_publish_min_pages):
            return
        fresh = [i for i in range(k)
                 if digests[i] not in self._fabric_published]
        if not fresh:
            return
        try:
            host_pages = kvc.offload_pages(
                self.kv, [seq.pages[i] for i in fresh])
            self.fabric_publish(
                [(digests[i], p) for i, p in zip(fresh, host_pages)])
        except Exception:
            return                        # publish is best-effort
        for i in fresh:
            self._fabric_published[digests[i]] = None
        while len(self._fabric_published) > 4096:
            self._fabric_published.popitem(last=False)
        self.fabric_published_pages += len(fresh)

    def release(self, seq: Sequence) -> None:
        """Free a finished sequence's pages and slot, publishing its full
        pages to the prefix cache first."""
        self._publish_to_cache(seq)
        self.allocator.free(seq.pages)
        seq.pages = []
        seq.prefill_prompt = None          # cancel/error mid-prefill
        if seq.slot >= 0 and self.slots[seq.slot] is seq:
            self.slots[seq.slot] = None
        self._stage_forget(seq)

    # ------------------------------------------------------------------
    # Preemption + recompute-resume (admission="optimistic")
    # ------------------------------------------------------------------

    def preempt(self, seq: Sequence) -> None:
        """Evict a running sequence under pool pressure: release its
        slot and pages but KEEP host-side prompt + generated tokens, so
        a later re-admission recompute-resumes it (re-prefill over
        prompt + generated; token-identical under greedy decoding).

        Pages are published to the prefix cache first — the resume
        re-prefill reuses whatever pressure hasn't evicted by then,
        while the cached copies stay reclaimable capacity."""
        assert all(seq.slot not in call["allowed"]
                   for call in self._inflight), \
            "preempt of a sequence with dispatch-ahead calls in flight"
        self._publish_to_cache(seq)
        self.allocator.free(seq.pages)
        seq.pages = []
        if seq.slot >= 0 and self.slots[seq.slot] is seq:
            self.slots[seq.slot] = None
        self._stage_forget(seq)
        seq.slot = -1
        seq.ctx_len = 0
        seq.evicted_pages = 0
        seq.cached_tokens = 0
        seq.prefill_prompt = None
        # The published pages may demote to host under the very pressure
        # that preempted this sequence — re-arm the queue-wait prefetch
        # so the resume swaps them back in while it waits.
        seq.host_prefetched = False
        seq.resume_digests = None      # stream/truncation change here
        seq.resume_base = len(seq.generated)
        seq.preemptions += 1
        self.preemptions_total += 1
        self._preempted_out.append(seq)
        telemetry.log_event(
            "request_preempted", level="info",
            request_id=seq.trace_id or str(seq.request_id),
            preemptions=seq.preemptions,
            generated_tokens=len(seq.generated),
            free_plus_evictable=self._free_plus_evictable())

    def take_preempted(self) -> List[Sequence]:
        """Sequences preempted since the last call, in preemption order.
        The caller requeues them at the HEAD of its wait queue for
        recompute-resume (FCFS fairness: they were admitted first)."""
        out, self._preempted_out = self._preempted_out, []
        return out

    def _preempt_victim(self, cands: List[Sequence]) -> Optional[Sequence]:
        """Most-recently-admitted candidate still holding preemption
        budget. Sequences past the starvation guard (re-admitted under
        full reservation) are exempt, so they provably finish."""
        limit = self.engine_cfg.preempt_max_per_request
        eligible = [s for s in cands if s.preemptions < limit]
        return max(eligible, key=lambda s: s.admit_idx) if eligible else None

    def _preempt_for_pressure(self, active_seqs: List[Sequence],
                              k_steps: int) -> List[Sequence]:
        """Optimistic admission's safety net, evaluated before decode
        grants: when the coming round's page needs cannot all be met AND
        free+evictable has fallen below the low watermark, preempt the
        most-recently-admitted sequences until the remainder fits (or no
        eligible victim is left). Returns the surviving active list."""
        if self.admission != "optimistic":
            return active_seqs
        ecfg = self.engine_cfg
        active = list(active_seqs)
        while len(active) > 1:
            need = sum(
                kvc.pages_needed(
                    min(k_steps,
                        max(0, s.max_new_tokens - len(s.generated)),
                        max(0, ecfg.max_context - 1 - s.ctx_len)),
                    ecfg.page_size, already=s.ctx_len)
                for s in active)
            avail = self._free_plus_evictable()
            if need <= avail or avail >= ecfg.preempt_watermark_pages:
                break
            victim = self._preempt_victim(active)
            if victim is None:
                break
            self.preempt(victim)
            active.remove(victim)
        return active

    def _starved(self, seq: Sequence) -> None:
        """A lane with zero page slack and zero grantable pages: under
        optimistic admission (budget allowing) it is preempted and
        requeued for recompute-resume; otherwise it fails with "oom"
        (reserve-mode admission makes that path exceptional)."""
        if (self.admission == "optimistic"
                and seq.preemptions < self.engine_cfg.preempt_max_per_request):
            self.preempt(seq)
            return
        seq.done, seq.finish_reason = True, "oom"
        seq.finish_time = time.perf_counter()

    def active_sequences(self) -> List[Sequence]:
        """Sequences decode may advance: bound, not finished, and not
        still mid-incremental-prefill (those hold their slot but have no
        complete KV yet)."""
        return [s for s in self.slots
                if s is not None and not s.done and s.prefill_prompt is None]

    def _sampling_arrays(self, seq: Sequence):
        """(top_k, seed) for one sequence, with engine defaults applied.

        Negative seeds mean "no seed" (the llama.cpp/Ollama -1 convention),
        mapping to the engine-global key stream; values are clamped into
        int32 range for the device arrays."""
        top_k = self.engine_cfg.top_k if seq.top_k is None else seq.top_k
        top_k = max(0, min(int(top_k), 2**31 - 1))
        if seq.seed is None or seq.seed < 0:
            seed = -1
        else:
            seed = int(seq.seed) & 0x7FFFFFFF
        return top_k, seed

    def _penalty_arrays(self, seq: Sequence):
        """(repeat_penalty, repeat_last_n) with Ollama conventions:
        last_n < 0 means 'whole context' (clamped to the static window),
        0 disables. Under DRAFT-model speculative decoding the penalty is
        ignored ENTIRELY (prefill included) — the q/p acceptance ratio
        needs the draft and target distributions unmodified, and a
        first-token-only penalty would be a silent half-application.
        ngram spec composes: proposals are one-hot (no p to corrupt), and
        verify_round penalizes each position's target distribution
        against the window rolled with its accepted prefix — exactly the
        sequential plain-decode behavior."""
        if self.spec_draft:
            return 1.0, 0
        rlast = int(seq.repeat_last_n)
        if rlast < 0:
            rlast = PENALTY_WINDOW
        return float(seq.repeat_penalty), min(rlast, PENALTY_WINDOW)

    @staticmethod
    def _penalty_window_row(seq: Sequence) -> np.ndarray:
        """Last W known tokens (prompt + generated), newest at the high
        end, -1 padded — the device-side ring picks up from here."""
        row = np.full((PENALTY_WINDOW,), -1, np.int32)
        hist = (seq.prompt_tokens + seq.generated)[-PENALTY_WINDOW:]
        if hist:
            row[-len(hist):] = hist
        return row

    # -- Batch ladder: rung selection + slot compaction (README
    # -- "Batch ladder"). The slot array is top-rung sized; dispatch
    # -- width is the smallest compiled rung covering the occupied
    # -- slots, so a near-empty batch never pays big-graph latency.

    def _rung_for_slots(self, seqs: List[Sequence]) -> int:
        """Smallest ladder rung whose graph covers every slot in
        ``seqs`` (the slots staged into the dispatch arrays)."""
        hi = max((s.slot for s in seqs), default=-1) + 1
        for r in self.ladder:
            if r >= hi:
                return r
        return self.ladder[-1]

    def _note_rung(self, rung: int) -> None:
        """Record the dispatch rung (gauge + graph-switch counter) and
        flag first-ever-rung dispatches for the step ledger (the compile
        event a warm-up-free boot pays on that dispatch)."""
        self._last_compile_event = rung not in self._rungs_seen
        self._rungs_seen.add(rung)
        if rung != self.decode_rung:
            self.rung_switches_total += 1
            self.decode_rung = rung
            self.rung_peak = max(self.rung_peak, rung)

    def _ledger_push(self, kind: str, *, rung: int, slots: int,
                     tokens: int, chunk_tokens: int = 0, steps: int = 1,
                     device_s: float = 0.0, kv_read: int = 0,
                     spec_accepted: int = 0,
                     staging_s: Optional[float] = None,
                     bubble_s: Optional[float] = None,
                     compile_event: Optional[bool] = None) -> None:
        """Push one per-dispatch record into the step ledger, folding in
        the staged bubble/staging micros (unless the caller captured
        them at stage time — pipelined calls push at SYNC, by which
        point the scratch belongs to a newer dispatch) and the KV-swap
        byte delta since the previous record. Callers gate on
        telemetry.enabled (the swap counters are NULL_METRIC otherwise).
        """
        tel = self.telemetry
        swap_total = (tel.kv_offload_bytes.value
                      + tel.kv_restore_bytes.value)
        swap = max(0.0, swap_total - self._last_swap_bytes_total)
        self._last_swap_bytes_total = swap_total
        if staging_s is None:
            staging_s = self._last_staging_s
            self._last_staging_s = 0.0
        if bubble_s is None:
            bubble_s = self._pending_bubble
            self._pending_bubble = 0.0
        if compile_event is None:
            compile_event = self._last_compile_event
            self._last_compile_event = False
        tel.step_ledger.push(
            kind, rung, slots, tokens, chunk_tokens, steps, device_s,
            staging_s, bubble_s, kv_read, swap, spec_accepted,
            compile_event)

    def _compact_slots(self) -> None:
        """Step-down helper: relocate bound sequences out of high slots
        into lower free ones so the next dispatch can run a smaller
        compiled rung once occupancy drops. A slot move is pure host
        bookkeeping — block tables ship per dispatch, KV pages never
        move — but it is only legal while NO dispatch-ahead call is in
        flight (in-flight calls address lanes by the slot they were
        staged at). Mid-incremental-prefill sequences relocate too:
        their chunk dispatches address pages, not slots."""
        if len(self.ladder) == 1 or self._inflight:
            return
        bound = [i for i, s in enumerate(self.slots) if s is not None]
        if not bound:
            return
        target = next(r for r in self.ladder if r >= len(bound))
        if bound[-1] < target:
            return                        # already fits the target rung
        free = [i for i in range(target) if self.slots[i] is None]
        for i in reversed(bound):
            if i < target or not free:
                break
            j = free.pop(0)
            seq = self.slots[i]
            self.slots[j], self.slots[i] = seq, None
            seq.slot = j

    def _stage_buffers(self, rung: int) -> dict:
        """Persistent per-rung staging arrays (stage_host_reuse). Rows
        refresh incrementally: per-dispatch fields (token, ctx) always;
        sampling params only when the slot's occupant changes; the
        block-table row only when its (len, evicted) key moves."""
        buf = self._stage_bufs.get(rung)
        if buf is None:
            buf = {
                "tokens": np.zeros((rung,), np.int32),
                "ctx": np.zeros((rung,), np.int32),
                "bts": np.zeros((rung, self.max_pages), np.int32),
                "temps": np.zeros((rung,), np.float32),
                "top_ps": np.ones((rung,), np.float32),
                "top_ks": np.zeros((rung,), np.int32),
                "seeds": np.full((rung,), -1, np.int32),
                "rpens": np.ones((rung,), np.float32),
                "rlasts": np.zeros((rung,), np.int32),
                "windows": np.full((rung, PENALTY_WINDOW), -1, np.int32),
                "owner": [None] * rung,
                "bt_key": [None] * rung,
            }
            self._stage_bufs[rung] = buf
        return buf

    def _stage_forget(self, seq: Sequence) -> None:
        """Drop a departing sequence's staging-buffer rows (every rung;
        identity scan because compaction may have left it cached under
        an older slot). Without this the owner lists would pin finished
        Sequences — and their full token histories — until the same
        slot happens to restage at the same rung."""
        for buf in self._stage_bufs.values():
            owner = buf["owner"]
            for i, s in enumerate(owner):
                if s is seq:
                    owner[i] = None
                    buf["bt_key"][i] = None

    def _stage_batch(self, active_seqs: List[Sequence], rung: int):
        """Fill the per-slot host arrays shared by both decode entry points:
        (tokens, ctx_lens, block_tables, temps, top_ps, top_ks, seeds,
        rpens, rlasts, windows) — [rung]-shaped ([rung, W] for windows).

        With ``stage_host_reuse`` (default) the arrays persist across
        dispatches and only changed rows are rewritten; the device gets
        COPIES because jnp.asarray aliases numpy memory on CPU and the
        buffers mutate next step. Rows of freed slots go stale, which is
        benign: their ``allowed`` is 0, so the graph masks every read
        and write (writes land on the trash page) and their token is
        discarded (-1)."""
        tel_on = self.telemetry.enabled
        t_stage = time.perf_counter() if tel_on else 0.0
        if not self._stage_reuse:
            # Legacy rebuild-per-dispatch (the bubble comparison arm).
            tokens = np.zeros((rung,), np.int32)
            ctx_lens = np.zeros((rung,), np.int32)
            bts = np.zeros((rung, self.max_pages), np.int32)
            temps = np.zeros((rung,), np.float32)
            top_ps = np.ones((rung,), np.float32)
            top_ks = np.zeros((rung,), np.int32)
            seeds = np.full((rung,), -1, np.int32)
            rpens = np.ones((rung,), np.float32)
            rlasts = np.zeros((rung,), np.int32)
            windows = np.full((rung, PENALTY_WINDOW), -1, np.int32)
            for seq in active_seqs:
                i = seq.slot
                tokens[i] = seq.last_token
                ctx_lens[i] = seq.ctx_len
                bts[i] = self._block_table_array(seq.pages)
                temps[i] = seq.temperature
                top_ps[i] = seq.top_p
                top_ks[i], seeds[i] = self._sampling_arrays(seq)
                rpens[i], rlasts[i] = self._penalty_arrays(seq)
                if rpens[i] != 1.0:
                    windows[i] = self._penalty_window_row(seq)
            if tel_on:
                self._last_staging_s = time.perf_counter() - t_stage
            return (tokens, ctx_lens, bts, temps, top_ps, top_ks, seeds,
                    rpens, rlasts, windows)
        buf = self._stage_buffers(rung)
        owner, bt_key = buf["owner"], buf["bt_key"]
        for seq in active_seqs:
            i = seq.slot
            buf["tokens"][i] = seq.last_token
            buf["ctx"][i] = seq.ctx_len
            if owner[i] is not seq:
                owner[i] = seq
                bt_key[i] = None
                buf["temps"][i] = seq.temperature
                buf["top_ps"][i] = seq.top_p
                buf["top_ks"][i], buf["seeds"][i] = \
                    self._sampling_arrays(seq)
                buf["rpens"][i], buf["rlasts"][i] = \
                    self._penalty_arrays(seq)
            # Pages mutate by growing (decode grants / prefill setup),
            # by behind-window eviction (entries zeroed, cursor moves),
            # or by wholesale replacement at a (re)prefill — keyed by
            # (version, len, evicted) so every one of those invalidates.
            key = (seq.pages_version, len(seq.pages), seq.evicted_pages)
            if bt_key[i] != key:
                bt_key[i] = key
                row = buf["bts"][i]
                n = len(seq.pages)
                row[:n] = seq.pages
                row[n:] = 0
            if buf["rpens"][i] != 1.0:
                buf["windows"][i] = self._penalty_window_row(seq)
        if tel_on:
            self._last_staging_s = time.perf_counter() - t_stage
        return (buf["tokens"].copy(), buf["ctx"].copy(), buf["bts"].copy(),
                buf["temps"].copy(), buf["top_ps"].copy(),
                buf["top_ks"].copy(), buf["seeds"].copy(),
                buf["rpens"].copy(), buf["rlasts"].copy(),
                buf["windows"].copy())

    def decode_step(self) -> Dict[int, int]:
        """One batched decode step (single-step view of the fused graph:
        ``allowed`` is capped at 1, so lanes advance exactly one token).
        Returns {request_id: new_token}. Prefer decode_steps() in serving
        loops — this exists for tests and fine-grained stepping."""
        return {rid: toks[0]
                for rid, toks in self.decode_steps(max_steps=1).items()}

    def decode_steps(self, max_steps: Optional[int] = None
                     ) -> Dict[int, List[int]]:
        """Up to ``decode_steps_per_call`` fused decode steps in ONE device
        dispatch. Returns {request_id: [tokens generated, in order]}.

        Per-sequence ``allowed`` folds the generation budget, the context
        cap, and KV-page headroom, so the device never writes a slot the
        host hasn't provisioned. EOS stops a lane on device; the host's
        ``_maybe_finish`` stays the source of truth for finish state.
        ``max_steps`` additionally caps every lane (decode_step uses 1).
        """
        self._chaos_step_gate()
        if self._inflight:
            # Mixing entry points: fold any dispatch-ahead state first so
            # ctx/pages bookkeeping stays consistent (tokens surface in
            # seq.generated; callers that care use decode_steps_pipelined
            # exclusively).
            self.drain_pipeline()
        if self.spec_draft:
            return self._spec_decode_steps(max_steps)
        if self.spec_ngram:
            return self._ngram_decode_steps(max_steps)
        return self._plain_decode_steps(max_steps)

    def _plain_decode_steps(self, max_steps: Optional[int] = None
                            ) -> Dict[int, List[int]]:
        """The non-speculative fused-K decode round (decode_steps body);
        also the dispatch ngram spec degrades to when NO slot has a
        proposal this round — plain fused decode is strictly better than
        a verify round that could only emit one token per lane."""
        ecfg = self.engine_cfg
        k_steps = max(1, ecfg.decode_steps_per_call)
        if max_steps is not None:
            k_steps = min(k_steps, max_steps)
        self._compact_slots()         # step the ladder down when possible
        active_seqs = self.active_sequences()
        if not active_seqs:
            return {}

        # Watermark check first: under optimistic admission, pressure
        # preempts the most-recently-admitted lanes BEFORE any grants,
        # so the surviving lanes advance at full k_steps.
        active_seqs = self._preempt_for_pressure(active_seqs, k_steps)
        allowed_by_slot: Dict[int, int] = {}
        for seq in active_seqs:
            steps = self._grant_decode_steps(seq, k_steps)
            if steps <= 0:
                # No budget/room should have finished already; zero pool
                # slack preempts (optimistic) or fails safely (reserve).
                self._starved(seq)
                continue
            allowed_by_slot[seq.slot] = steps
        active_seqs = [s for s in active_seqs
                       if not s.done and s.slot >= 0]
        if not active_seqs:
            return {}

        # Dispatch at the smallest compiled rung covering the batch.
        b = self._rung_for_slots(active_seqs)
        self._note_rung(b)
        (tokens, ctx_lens, bts, temps, top_ps, top_ks, seeds,
         rpens, rlasts, windows) = self._stage_batch(active_seqs, b)
        allowed = np.zeros((b,), np.int32)
        eos_ids = np.full((b,), -1, np.int32)
        for seq in active_seqs:
            allowed[seq.slot] = allowed_by_slot[seq.slot]
            if seq.eos_token_id is not None:
                eos_ids[seq.slot] = seq.eos_token_id

        # k_steps==1 runs the 1-iteration graph (one forward per visible
        # token) instead of masking K-1 steps of the fused graph.
        decode = self._decode_one_jit if k_steps == 1 else \
            self._decode_multi_jit
        t0 = self._note_decode_entry(active_seqs)
        self.kv, outs, _, _ = decode(
            self.params, self.kv, jnp.asarray(tokens), jnp.asarray(ctx_lens),
            jnp.asarray(bts), jnp.asarray(allowed), jnp.asarray(eos_ids),
            self._next_key(), jnp.asarray(temps), jnp.asarray(top_ps),
            jnp.asarray(top_ks), jnp.asarray(seeds), jnp.asarray(rpens),
            jnp.asarray(rlasts), jnp.asarray(windows))
        outs = np.asarray(outs)                                 # [K, B]
        dt = self._note_decode_exit(t0, active_seqs)
        kv_read = sum(s.ctx_len for s in active_seqs) * k_steps

        result: Dict[int, List[int]] = {}
        for seq in active_seqs:
            got = self._fold_lane(
                seq, (int(outs[s, seq.slot]) for s in range(k_steps)))
            if got:
                result[seq.request_id] = got
        if self.telemetry.enabled:
            n_tokens = sum(len(t) for t in result.values())
            self.telemetry.tokens_per_dispatch.observe(n_tokens)
            self._ledger_push("decode", rung=b, slots=len(active_seqs),
                              tokens=n_tokens, steps=k_steps,
                              device_s=dt, kv_read=kv_read)
        return result

    # ------------------------------------------------------------------
    # Pipelined decode (dispatch-ahead serving loop)
    # ------------------------------------------------------------------

    def _hybrid_chunk_cap(self, decode_tokens: int) -> int:
        """Chunk-token cap for one hybrid step: the serial chunk cap,
        further bounded by ``step_token_budget`` minus the decode tokens
        actually GRANTED for this dispatch (not lanes * K — lanes near
        their generation budget are granted fewer steps, and deducting
        their full K share would over-shrink the chunk), floored at
        page_size so the prefill always advances. Real (unpadded)
        tokens are what the budget counts; bucket padding is a
        compile-shape artifact."""
        ecfg = self.engine_cfg
        cap = ecfg.chunk_tokens_cap
        budget = ecfg.step_token_budget
        if budget > 0:
            cap = min(cap, max(ecfg.page_size, budget - decode_tokens))
        return cap

    def _stage_hybrid_chunk(self, seq: Sequence,
                            decode_tokens: int) -> Optional[dict]:
        """Host arrays for ``seq``'s next prefill chunk (no dispatch).

        Advances ``seq.prefill_offset`` at STAGE time, so chained hybrid
        dispatches can stage chunk N+1 while chunk N is still in flight
        — the device serializes them on the donated pool, and chunk N+1's
        prefix attention reads pages chunk N has written by then. Only
        the FINAL chunk's sampled token is read back (at sync). Returns
        None once the whole prompt is staged."""
        prompt = seq.prefill_prompt
        if prompt is None or seq.done or seq.prefill_offset >= len(prompt):
            return None
        offset = seq.prefill_offset
        st = self._stage_chunk_arrays(seq, prompt, offset,
                                      self._hybrid_chunk_cap(decode_tokens))
        seq.prefill_offset = offset + st["chunk_tokens"]
        st["final"] = seq.prefill_offset >= len(prompt)
        return st

    def _stage_chunk_only_call(self, chunk: dict) -> dict:
        """Dispatch one staged prefill chunk WITHOUT a decode half (no
        lane could advance this call) and wrap it as a pipeline call, so
        chained chunks keep flowing through _sync_oldest/drain exactly
        like hybrid calls. Counts as a prefill dispatch, not a hybrid
        step, and observes no decode stall — the lanes it would have
        stalled are covered by in-flight work."""
        t0 = time.perf_counter()
        self._last_decode_end = None   # prefill breaks the decode streak
        self.kv, p_tok, _ = self._prefill_jit(
            self.params, self.kv, *self._chunk_device_args(chunk))
        call = {"outs": None, "final": None, "final_window": None,
                "allowed": {}, "seqs": {}, "rung": 0,
                "prefill": {"seq": chunk["seq"], "prompt": chunk["prompt"],
                            "final": chunk["final"], "tok": p_tok}}
        if self.telemetry.enabled:
            dt = time.perf_counter() - t0
            self.telemetry.prefill_dispatch_s.observe(dt)
            self.telemetry.prefill_dispatches.inc()
            chunk["seq"].dispatch_wall_s += dt
            c = chunk["chunk_tokens"]
            off = int(chunk["prefix_len"][0])
            call["ledger"] = {
                "kind": "prefill_chunk", "rung": 0, "slots": 1,
                "tokens": 1 if chunk["final"] else 0,
                "chunk_tokens": c, "steps": 1, "dispatch_s": dt,
                "staging_s": 0.0, "bubble_s": 0.0,
                "kv_read": c * off + c * (c + 1) // 2,
                "compile": chunk["bucket"]
                not in self._prefill_buckets_seen}
            self._prefill_buckets_seen.add(chunk["bucket"])
        return call

    def _stage_decode_call(self, prefill_seq: Optional[Sequence] = None):
        """Stage one fused-decode dispatch from current host state plus
        the ctx deltas of still-in-flight calls (predicted ctx).

        With ``prefill_seq`` (a sequence mid-incremental-prefill), its
        next chunk rides the same dispatch: the hybrid graph advances
        the chunk and the decode lanes together (page-disjoint, so the
        fusion is value-identical to the serial order), and the call
        chains into the pipeline exactly like a plain decode call.

        Returns None when nothing can advance. Page/budget/room logic
        mirrors decode_steps, evaluated at the predicted positions; lanes
        that stop mid-flight (EOS) waste at most their staged steps,
        whose tokens the sync step discards (KV garbage at dead positions
        is always rewritten by a later owner before being attended).
        """
        ecfg = self.engine_cfg
        k_steps = max(1, ecfg.decode_steps_per_call)
        if not self._inflight:
            self._compact_slots()     # rung can step down between bursts
        # Predicted per-slot ctx advance from unsynced calls.
        ahead: Dict[int, int] = {}
        for call in self._inflight:
            for slot, steps in call["allowed"].items():
                ahead[slot] = ahead.get(slot, 0) + steps
        active_seqs = self.active_sequences()
        if not active_seqs and prefill_seq is None:
            return None
        allowed_by_slot: Dict[int, int] = {}
        staged: List[Sequence] = []
        for seq in active_seqs:
            lag = ahead.get(seq.slot, 0)
            steps = self._grant_decode_steps(
                seq, k_steps, pred_ctx=seq.ctx_len + lag,
                pred_done=len(seq.generated) + lag)
            if steps <= 0:
                if lag == 0:
                    # Nothing in flight can finish it and the pool has
                    # zero slack: preempt (optimistic; lag == 0 means no
                    # in-flight call touches it, so eviction is safe) or
                    # fail the sequence (decode_steps's oom semantics).
                    # Budget/room exhaustion can't land here —
                    # _maybe_finish already marked those done.
                    self._starved(seq)
                continue                      # ahead calls may still emit
            allowed_by_slot[seq.slot] = steps
            staged.append(seq)
        # Stage the chunk AFTER grant filtering: the step token budget
        # deducts only the lanes actually advancing in THIS dispatch, so
        # a call whose lanes are all covered by in-flight work doesn't
        # shrink the chunk for decode tokens it isn't producing.
        chunk = None
        if prefill_seq is not None:
            chunk = self._stage_hybrid_chunk(
                prefill_seq, sum(allowed_by_slot.values()))
        if not staged and chunk is None:
            return None
        if not staged:
            # No decode lane can advance this call (all grants covered by
            # in-flight work, or no lanes at all): dispatch the chunk on
            # the plain prefill graph instead of burning a dead B x K
            # decode scan inside the hybrid graph.
            return self._stage_chunk_only_call(chunk)

        # A lane _starved() preempted above has no slot anymore — drop
        # it before staging host arrays (seq.slot == -1 would index the
        # last batch row).
        active_seqs = [s for s in active_seqs
                       if not s.done and s.slot >= 0]
        # Ladder rung for this call: smallest compiled graph covering
        # the staged slots, never below any in-flight call's rung —
        # carry folds are element-wise over [rung] arrays, so every
        # in-flight call must share one width. Growth past the in-flight
        # rung is handled by the callers (they drain first); shrink lags
        # the pipeline depth, then steps down here.
        b = self._rung_for_slots(active_seqs)
        for call in self._inflight:
            b = max(b, call["rung"])
        self._note_rung(b)
        (tokens, ctx_lens, bts, temps, top_ps, top_ks, seeds,
         rpens, rlasts, windows) = self._stage_batch(active_seqs, b)
        allowed = np.zeros((b,), np.int32)
        eos_ids = np.full((b,), -1, np.int32)
        for seq in staged:
            allowed[seq.slot] = allowed_by_slot[seq.slot]
            ctx_lens[seq.slot] = seq.ctx_len + ahead.get(seq.slot, 0)
            if seq.eos_token_id is not None:
                eos_ids[seq.slot] = seq.eos_token_id
        tokens_d = jnp.asarray(tokens)
        window_d = jnp.asarray(windows)
        # Each continuing lane consumes the carry token (and penalty
        # window) of the NEWEST in-flight call that advanced it
        # (oldest-to-newest fold: later calls overwrite); lanes in no
        # in-flight call (fresh prefills) keep their host-known state.
        for call in self._inflight:
            if call["final"] is None:
                continue    # chunk-only call: no decode half, no carry
            carried = np.zeros((b,), bool)
            for slot in call["allowed"]:
                carried[slot] = True
            carried_d = jnp.asarray(carried)
            tokens_d = jnp.where(carried_d, call["final"], tokens_d)
            window_d = jnp.where(carried_d[:, None], call["final_window"],
                                 window_d)
        t0 = self._note_decode_entry(staged)
        if chunk is None:
            self.kv, outs, final, final_window = self._decode_multi_jit(
                self.params, self.kv, tokens_d, jnp.asarray(ctx_lens),
                jnp.asarray(bts), jnp.asarray(allowed), jnp.asarray(eos_ids),
                self._next_key(), jnp.asarray(temps), jnp.asarray(top_ps),
                jnp.asarray(top_ks), jnp.asarray(seeds), jnp.asarray(rpens),
                jnp.asarray(rlasts), window_d)
            p_tok = None
        else:
            self.kv, p_tok, outs, final, final_window = self._hybrid_jit(
                self.params, self.kv, *self._chunk_device_args(chunk),
                tokens_d, jnp.asarray(ctx_lens),
                jnp.asarray(bts), jnp.asarray(allowed), jnp.asarray(eos_ids),
                self._next_key(), jnp.asarray(temps), jnp.asarray(top_ps),
                jnp.asarray(top_ks), jnp.asarray(seeds), jnp.asarray(rpens),
                jnp.asarray(rlasts), window_d)
            self.hybrid_steps_total += 1
            self.telemetry.hybrid_steps.inc()
        # Non-blocking dispatch: the wall recorded here is host dispatch
        # overhead; the device wait surfaces in decode_sync_s at
        # _sync_oldest.
        dispatch_dt = self._note_decode_exit(t0, staged)
        if chunk is not None and self.telemetry.enabled:
            dt = time.perf_counter() - t0
            self.telemetry.hybrid_dispatch_s.observe(dt)
            chunk["seq"].dispatch_wall_s += dt
        call = {"outs": outs, "final": final,
                "final_window": final_window,
                "allowed": allowed_by_slot, "rung": b,
                "seqs": {s.slot: s for s in staged}}
        if chunk is not None:
            call["prefill"] = {"seq": chunk["seq"], "prompt": chunk["prompt"],
                               "final": chunk["final"], "tok": p_tok}
        if self.telemetry.enabled:
            # Step-ledger metadata captured at STAGE time (the scratch
            # micros belong to this dispatch); the record is pushed at
            # sync with device_s = dispatch + sync wall and the folded
            # token count.
            kv_read = sum(int(ctx_lens[s.slot]) * allowed_by_slot[s.slot]
                          for s in staged)
            compile_ev = self._last_compile_event
            self._last_compile_event = False
            if chunk is not None:
                c = chunk["chunk_tokens"]
                off = int(chunk["prefix_len"][0])
                kv_read += c * off + c * (c + 1) // 2
                hkey = ("hybrid", chunk["bucket"])
                compile_ev = compile_ev or (
                    hkey not in self._prefill_buckets_seen)
                self._prefill_buckets_seen.add(hkey)
            call["ledger"] = {
                "kind": "decode" if chunk is None else "hybrid",
                "rung": b, "slots": len(staged),
                # the final chunk's sampled first token folds at sync
                "tokens": 1 if chunk is not None and chunk["final"]
                else 0,
                "chunk_tokens": 0 if chunk is None
                else chunk["chunk_tokens"],
                "steps": k_steps, "dispatch_s": dispatch_dt,
                "staging_s": self._last_staging_s,
                "bubble_s": self._pending_bubble,
                "kv_read": kv_read, "compile": compile_ev}
            self._last_staging_s = 0.0
            self._pending_bubble = 0.0
        return call

    def _sync_oldest(self) -> Dict[int, List[int]]:
        """Block on the oldest in-flight call and fold its tokens into
        host state; tokens for lanes that finished in an earlier call are
        discarded (their compute was speculative)."""
        call = self._inflight.pop(0)
        if call.get("spec"):
            # ngram spec round staged into the pipeline: its fold is
            # emission-shaped (accept-prefix + caps), not K-step-shaped.
            return self._sync_spec_call(call)
        t0 = time.perf_counter()
        pf = call.get("prefill")
        if call["outs"] is not None:
            outs = np.asarray(call["outs"])           # [K, B]
        else:
            # Chunk-only call (no decode half): the blocking sync is on
            # the chunk's sampled token instead.
            outs = None
            if pf is not None:
                jax.block_until_ready(pf["tok"])
        sync_dt = time.perf_counter() - t0
        if self.telemetry.enabled:
            dt = sync_dt
            if outs is not None:
                self.telemetry.decode_sync_s.observe(dt)
            if pf is not None:
                # The chunk shared this call, so its request waited on
                # the same sync (the chunk's prefill compute usually
                # dominates it) — without this the long prompt's
                # timeline would show near-zero dispatch wall. Chunk-
                # only waits stay out of decode_sync_s (pure prefill
                # device time, not a decode sync).
                pf["seq"].dispatch_wall_s += dt
            for seq in call["seqs"].values():
                if not seq.done and self.slots[seq.slot] is seq:
                    seq.dispatch_wall_s += dt
        # The blocking sync is DEVICE time (already in decode_sync_s /
        # dispatch_wall_s): refresh the bubble reference point so the
        # next decode entry measures only host work after it — without
        # this, dispatch-ahead mode would re-count every device step as
        # "host-side bubble" and the phase_breakdown would blame the
        # host for a busy device.
        self._last_decode_end = (
            time.perf_counter()
            if any(s is not None and not s.done for s in self.slots)
            else None)
        result: Dict[int, List[int]] = {}
        for slot, seq in call["seqs"].items():
            if seq.done or self.slots[seq.slot] is not seq:
                continue
            got = self._fold_lane(
                seq, (int(outs[s, slot]) for s in range(outs.shape[0])))
            if got:
                result[seq.request_id] = got
        if pf is not None:
            # Hybrid call: the chunk's offset advanced at stage time; only
            # the FINAL chunk has host work left — fold its sampled token
            # and complete the incremental prefill. A cancel that landed
            # mid-flight skips the fold (the scheduler reaps the sequence;
            # its pages are released only after the pipeline settles).
            seq = pf["seq"]
            if (pf["final"] and not seq.done
                    and seq.prefill_prompt is not None
                    and seq.slot >= 0 and self.slots[seq.slot] is seq):
                self._prefill_finish(seq, pf["prompt"],
                                     int(np.asarray(pf["tok"])[0]))
                seq.prefill_prompt = None
        n_tokens = sum(len(t) for t in result.values())
        if self.telemetry.enabled and outs is not None:
            self.telemetry.tokens_per_dispatch.observe(n_tokens)
        led = call.get("ledger")
        if led is not None and self.telemetry.enabled:
            # Pipelined record lands at SYNC with the true device wall
            # (non-blocking dispatch + the blocking sync) and the folded
            # token count; the stage-time micros rode along in ``led``.
            tokens = (led["tokens"] if led["kind"] == "prefill_chunk"
                      else n_tokens + led["tokens"])
            self._ledger_push(
                led["kind"], rung=led["rung"], slots=led["slots"],
                tokens=tokens, chunk_tokens=led["chunk_tokens"],
                steps=led["steps"],
                device_s=led["dispatch_s"] + sync_dt,
                kv_read=led["kv_read"], staging_s=led["staging_s"],
                bubble_s=led["bubble_s"], compile_event=led["compile"])
        return result

    def _pressure_settle_round(self) -> Dict[int, List[int]]:
        """Optimistic admission under watermark pressure: settle device
        state before any preemption decision — in-flight calls hold
        predicted-ctx page grants — then run one synchronous round,
        which preempts as needed (and runs the chaos gate itself:
        gating in the caller too would double the injected failure rate
        on this branch). Shared by the plain and hybrid pipelined
        entry points so the pressure semantics cannot drift."""
        result = self.drain_pipeline()
        for rid, toks in self.decode_steps().items():
            result.setdefault(rid, []).extend(toks)
        return result

    def _pipeline_rung_blocked(self) -> bool:
        """True when staging now would need a bigger ladder rung than
        the in-flight calls were staged at — carry folds are element-
        wise over [rung] arrays, so the pipeline must settle before the
        batch grows past its compiled width. Growth is an occupancy-
        increasing moment (a fresh prefill just took a high slot), so
        the one-call hiccup is rare and bounded."""
        if not self._inflight or len(self.ladder) == 1:
            return False
        # Chunk-only prefill calls (rung 0) have no decode half — no
        # carry to fold, so they impose no width constraint and must
        # not masquerade as a cap (that would drain the pipeline every
        # chunk and re-serialize exactly the stall hybrid chaining
        # removes).
        rungs = [call["rung"] for call in self._inflight
                 if call["final"] is not None]
        if not rungs:
            return False
        cap = max(rungs)
        if cap >= self.ladder[-1]:
            return False
        active = self.active_sequences()
        if not active:
            return False
        return self._rung_for_slots(active) > cap

    def decode_steps_pipelined(self) -> Dict[int, List[int]]:
        """Dispatch-ahead serving step: keep up to
        ``decode_pipeline_depth`` fused-decode calls in flight; sync only
        the oldest. Token delivery lags dispatch by depth-1 calls, and
        device compute overlaps all host work in between.
        Falls back to the synchronous path when depth <= 1 or spec is on.
        """
        depth = self.engine_cfg.decode_pipeline_depth
        if depth <= 1 or self.spec_draft:
            return self.decode_steps()         # gate runs inside
        if self.admission == "optimistic" and self.under_pressure:
            return self._pressure_settle_round()
        self._chaos_step_gate()
        if self.spec_ngram:
            return self._ngram_steps_pipelined()
        result: Dict[int, List[int]] = {}
        if self._pipeline_rung_blocked():
            result = self.drain_pipeline()     # settle, then grow rung
        call = self._stage_decode_call()
        if call is not None:
            self._inflight.append(call)
        if not self._inflight:
            return result
        if len(self._inflight) >= depth or call is None:
            for rid, toks in self._sync_oldest().items():
                result.setdefault(rid, []).extend(toks)
        return result

    def hybrid_step_pipelined(self, seq: Sequence) -> Dict[int, List[int]]:
        """Serving step while ``seq`` is mid-incremental-prefill: advance
        its next chunk AND the decode lanes in ONE fused dispatch
        (EngineConfig.hybrid_prefill), so running lanes keep producing
        tokens instead of stalling a chunk wall per chunk.

        Chains into the same dispatch-ahead pipeline as plain decode
        calls: with depth > 1 the call is non-blocking and only the
        oldest in-flight call is synced; with depth <= 1 it dispatches
        and syncs immediately (synchronous mode). Once the prompt is
        fully staged, further calls degrade to plain decode staging and
        the final chunk's sampled token folds at its sync — the caller
        observes completion as ``seq.prefill_prompt is None``.
        Returns decode tokens folded by this call (possibly {}).
        """
        assert not self.spec_enabled, \
            "hybrid steps don't compose with speculative decoding"
        depth = max(1, self.engine_cfg.decode_pipeline_depth)
        if (self.admission == "optimistic" and self.under_pressure
                and self.active_sequences()):
            # Pressure settles first (drain + one synchronous preempting
            # round), then the chunk advances SERIALLY: its pages were
            # all allocated at prefill_begin, so it cannot deepen the
            # shortage, and skipping it would starve the prefill for as
            # long as pressure holds — a liveness regression vs serial
            # mode, which advances one chunk per iteration regardless.
            # (The active_sequences guard also protects direct
            # engine-API drivers: with no lanes there is nothing to
            # settle and the plain staging path below handles the
            # chunk.)
            result = self._pressure_settle_round()
            if seq.prefill_prompt is not None and not seq.done:
                self.prefill_step(seq)
            return result
        self._chaos_step_gate()
        result: Dict[int, List[int]] = {}
        if self._pipeline_rung_blocked():
            result = self.drain_pipeline()     # settle, then grow rung
        call = self._stage_decode_call(prefill_seq=seq)
        if call is not None:
            self._inflight.append(call)
        if not self._inflight:
            return result
        if depth <= 1 or len(self._inflight) >= depth or call is None:
            for rid, toks in self._sync_oldest().items():
                result.setdefault(rid, []).extend(toks)
        return result

    @property
    def pipeline_pending(self) -> bool:
        return bool(self._inflight)

    def abort_pipeline(self) -> None:
        """Discard in-flight calls WITHOUT folding (decode-error
        recovery): after an error their outputs are suspect, and leaving
        stale entries would poison ctx prediction / carry tokens for
        whatever request reuses those slots next."""
        self._inflight.clear()

    def drain_pipeline(self) -> Dict[int, List[int]]:
        """Sync every in-flight call (idle/finish/shutdown path)."""
        result: Dict[int, List[int]] = {}
        while self._inflight:
            for rid, toks in self._sync_oldest().items():
                result.setdefault(rid, []).extend(toks)
        return result

    def decode_steps_chained(self, n_calls: int) -> Dict[int, List[int]]:
        """Dispatch-ahead decode: ``n_calls`` fused-decode dispatches
        back-to-back, each consuming the previous call's device-resident
        final carry tokens — ZERO host syncs until the end (then one).

        This removes the host/tunnel round trip from the decode critical
        path (SURVEY.md §7 hard part 3); with K fused steps per call the
        device runs n_calls*K tokens per lane uninterrupted. Constraints
        of the mode: pages are pre-provisioned for the full run (raises
        MemoryError if the pool can't hold it), EOS/budget do not stop
        lanes early (bench / fixed-length batch mode — callers cap
        n_calls*K by the remaining budget).
        """
        ecfg = self.engine_cfg
        k_steps = max(1, ecfg.decode_steps_per_call)
        active_seqs = self.active_sequences()
        if not active_seqs:
            return {}
        total = n_calls * k_steps
        for seq in active_seqs:
            budget = seq.max_new_tokens - len(seq.generated)
            room = ecfg.max_context - 1 - seq.ctx_len
            if total > min(budget, room):
                # No mid-run stopping in this mode: the caller must size
                # n_calls*K within every lane's budget AND context room
                # (decode_steps folds these into `allowed` per step; here
                # they would overflow the block table / clamp positions).
                raise ValueError(
                    f"decode_steps_chained: n_calls*K={total} exceeds "
                    f"seq {seq.request_id}'s budget={budget} or context "
                    f"room={room}")
            need = kvc.pages_needed(total, ecfg.page_size,
                                    already=seq.ctx_len)
            if need > 0:
                seq.pages.extend(self._allocate_reclaiming(need))

        self._compact_slots()
        b = self._rung_for_slots(active_seqs)
        self._note_rung(b)
        (tokens, ctx_lens, bts, temps, top_ps, top_ks, seeds,
         rpens, rlasts, windows) = self._stage_batch(active_seqs, b)
        allowed = np.zeros((b,), np.int32)
        for seq in active_seqs:
            allowed[seq.slot] = k_steps
        no_eos = jnp.full((b,), -1, jnp.int32)
        allowed_d = jnp.asarray(allowed)
        bts_d = jnp.asarray(bts)
        temps_d, top_ps_d = jnp.asarray(temps), jnp.asarray(top_ps)
        top_ks_d, seeds_d = jnp.asarray(top_ks), jnp.asarray(seeds)
        rpens_d, rlasts_d = jnp.asarray(rpens), jnp.asarray(rlasts)

        tokens_dev = jnp.asarray(tokens)
        window_dev = jnp.asarray(windows)
        outs_all = []
        kv_read = sum(s.ctx_len for s in active_seqs) * total
        dispatch_wall = 0.0
        for c in range(n_calls):
            t0 = self._note_decode_entry(active_seqs)
            self.kv, outs, tokens_dev, window_dev = self._decode_multi_jit(
                self.params, self.kv, tokens_dev,
                jnp.asarray(ctx_lens + c * allowed, np.int32), bts_d,
                allowed_d, no_eos, self._next_key(), temps_d, top_ps_d,
                top_ks_d, seeds_d, rpens_d, rlasts_d, window_dev)
            outs_all.append(outs)
            dispatch_wall += self._note_decode_exit(t0, active_seqs)
        t_sync = time.perf_counter()
        jax.block_until_ready(tokens_dev)
        sync_dt = time.perf_counter() - t_sync
        if self.telemetry.enabled:
            self.telemetry.decode_sync_s.observe(sync_dt)
        # Device wait, not host bubble (same rationale as _sync_oldest).
        self._last_decode_end = time.perf_counter()

        result: Dict[int, List[int]] = {rid.request_id: []
                                        for rid in active_seqs}
        for outs in outs_all:
            outs = np.asarray(outs)
            for seq in active_seqs:
                got = [int(t) for t in outs[:, seq.slot] if t >= 0]
                seq.ctx_len += len(got)
                seq.generated.extend(got)
                if seq.first_token_time == 0.0:
                    seq.first_token_time = time.perf_counter()
                result[seq.request_id].extend(got)
        for seq in active_seqs:
            self._maybe_finish(seq, seq.last_token)
        if self.telemetry.enabled:
            # One record for the whole chained run (the mode's unit of
            # dispatch from the host's point of view: one sync).
            self._ledger_push(
                "decode", rung=b, slots=len(active_seqs),
                tokens=sum(len(t) for t in result.values()),
                steps=total, device_s=dispatch_wall + sync_dt,
                kv_read=kv_read)
        return result

    def _spec_grant(self, active_seqs: List[Sequence], s_len: int,
                    max_steps: Optional[int]) -> Tuple[List[Sequence],
                                                       Dict[int, int]]:
        """Per-slot emission caps + page grants for one spec round
        (draft or ngram): the device writes KV for up to ``s_len``
        positions, so provision pages for what fits and clamp emissions
        to written capacity. Prefix-cache-held pages are reclaimable
        capacity here just as in _grant_decode_steps — counting only the
        raw free list would starve spec rounds once the cache warms up.
        Starved lanes preempt (optimistic) or fail, mirroring the plain
        path. Returns (surviving sequences, {slot: emit_cap})."""
        ecfg = self.engine_cfg
        emit_by_slot: Dict[int, int] = {}
        for seq in active_seqs:
            budget = seq.max_new_tokens - len(seq.generated)
            room = ecfg.max_context - 1 - seq.ctx_len
            emit_cap = max(0, min(s_len, budget, room))
            if max_steps is not None:
                emit_cap = min(emit_cap, max_steps)
            want = min(s_len, room)
            # Provision against pages HELD, not ctx: a partially-accepted
            # round leaves the sequence holding pages past ceil(ctx/ps)
            # (the rejected tail's rows), and recharging from ctx every
            # round would leak one page per partial round until the
            # block table overflows max_pages_per_seq.
            total_pages = kvc.pages_needed(seq.ctx_len + want,
                                           ecfg.page_size)
            need = max(0, min(total_pages, self.max_pages)
                       - len(seq.pages))
            grantable = self._free_plus_evictable()
            if need > grantable:
                slack = len(seq.pages) * ecfg.page_size - seq.ctx_len
                emit_cap = min(emit_cap,
                               slack + grantable * ecfg.page_size)
                need = min(need, grantable)
            if emit_cap <= 0:
                self._starved(seq)
                continue
            if need > 0:
                seq.pages.extend(self._allocate_reclaiming(need))
            emit_by_slot[seq.slot] = emit_cap
        return ([s for s in active_seqs if not s.done and s.slot >= 0],
                emit_by_slot)

    def _spec_decode_steps(self, max_steps: Optional[int] = None
                           ) -> Dict[int, List[int]]:
        """One speculative round: draft proposes gamma tokens, target
        verifies them in a single forward, rejection sampling keeps an
        exact-distribution prefix. Emits 1..gamma+1 tokens per sequence.

        No KV rollback on rejection: host ctx_len only advances over kept
        tokens and attention masks the cache by kv_len, so rejected
        positions are dead rows that later writes overwrite."""
        ecfg = self.engine_cfg
        gamma = ecfg.num_speculative_tokens
        s_len = gamma + 1
        active_seqs = self.active_sequences()
        if not active_seqs:
            return {}
        active_seqs = self._preempt_for_pressure(active_seqs, s_len)
        active_seqs, emit_by_slot = self._spec_grant(active_seqs, s_len,
                                                     max_steps)
        if not active_seqs:
            return {}

        b = ecfg.max_batch_size       # draft spec runs single-rung (top)
        # Seeds and repetition penalties are not plumbed into spec rounds
        # (rejection sampling needs the unmodified target distribution).
        (tokens, ctx_lens, bts, temps, top_ps, top_ks,
         _seeds, _rpens, _rlasts, _windows) = self._stage_batch(active_seqs,
                                                               b)
        cap = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        for seq in active_seqs:
            cap[seq.slot] = len(seq.pages) * ecfg.page_size
            active[seq.slot] = True

        # Per-request seeds are not plumbed into spec rounds (the rejection
        # sampler consumes randomness at a data-dependent rate, so a
        # position-keyed stream would not reproduce anyway); spec uses the
        # engine-global key.
        t0 = self._note_decode_entry(active_seqs)
        out = self._spec_jit(
            self.params, self.draft_params, self.kv, self.draft_kv,
            jnp.asarray(tokens), jnp.asarray(ctx_lens), jnp.asarray(bts),
            jnp.asarray(cap), jnp.asarray(active), self._next_key(),
            jnp.asarray(temps), jnp.asarray(top_ps), jnp.asarray(top_ks))
        self.kv, self.draft_kv = out.kv, out.draft_kv
        emitted = np.asarray(out.emitted)                   # [B, gamma+1]
        n_acc = np.asarray(out.n_accepted)
        dt = self._note_decode_exit(t0, active_seqs)
        # Pre-fold context: the verify forward read the cache at the ctx
        # the lanes ENTERED the round with.
        kv_read = sum(s.ctx_len for s in active_seqs) * s_len
        acc0 = self.spec_accepted

        result: Dict[int, List[int]] = {}
        for seq in active_seqs:
            got: List[int] = []
            for j in range(s_len):
                if seq.done or len(got) >= emit_by_slot[seq.slot]:
                    break
                tok = int(emitted[seq.slot, j])
                if tok < 0:
                    break
                seq.ctx_len += 1
                seq.generated.append(tok)
                if seq.first_token_time == 0.0:
                    seq.first_token_time = time.perf_counter()
                self._maybe_finish(seq, tok)
                got.append(tok)
            # Acceptance-rate accounting: count only draft positions the
            # host could actually emit (emit_cap can truncate a round when
            # budget/context run out), and clamp accepted to that window —
            # otherwise capped rounds overcount and the rate drifts.
            drafted = min(gamma, emit_by_slot[seq.slot])
            accepted = min(int(n_acc[seq.slot]), drafted)
            self.spec_drafted += drafted
            self.spec_accepted += accepted
            if drafted > 0:
                self.telemetry.spec_accept_rate.observe(accepted / drafted)
                # Per-request spec exposure for the decode trace span.
                seq.spec_rounds += 1
                seq.spec_accepted_toks += accepted
            if got:
                result[seq.request_id] = got
        if self.telemetry.enabled:
            n_toks = sum(len(t) for t in result.values())
            self.telemetry.tokens_per_dispatch.observe(n_toks)
            self._ledger_push(
                "spec_verify", rung=b, slots=len(active_seqs),
                tokens=n_toks, device_s=dt, kv_read=kv_read,
                spec_accepted=self.spec_accepted - acc0)
        return result

    # ------------------------------------------------------------------
    # Draft-free n-gram speculation (spec_mode="ngram"; README
    # "Speculative decoding"). The host proposes continuations by suffix-
    # matching each sequence's own prompt+generated history (cheap numpy
    # in the host bubble), and a verify-only round scores γ+1 positions
    # in ONE target forward — every accepted token is a decode step the
    # chip never ran sequentially. Per-sequence EWMA acceptance throttles
    # cold streams to γ=0; rounds where nothing proposes run the plain
    # fused-K graph, so speculation can never lose.
    # ------------------------------------------------------------------

    def _seq_spec_gamma(self, seq: Sequence) -> int:
        """Current adaptive γ for one sequence, ticking the throttle
        probe countdown: a γ=0-throttled sequence re-earns one round of
        real proposals every ``spec_probe_every`` rounds, so a stream
        that turns echoic mid-generation recovers its speedup."""
        gamma = self.engine_cfg.num_speculative_tokens
        if seq.spec_gamma < 0:
            # Fresh streams EARN the full width: the first proposal
            # rides the narrow γ=1 verify (cost ≈ one plain step), and
            # one clean accept promotes to the full γ — so cold traffic
            # that never echoes pays narrow rounds, not γ+1-wide ones.
            seq.spec_gamma = 1 if gamma > 1 else gamma
        if seq.spec_gamma == 0:
            seq.spec_probe_countdown -= 1
            if seq.spec_probe_countdown <= 0:
                # Probe at γ=1: the narrow compiled verify width, so
                # re-checking an echo-free stream costs ~one plain
                # decode step. A clean accept lifts the EWMA and
                # restores the full γ next round.
                seq.spec_gamma = 1
        return seq.spec_gamma

    def _spec_update_adaptive(self, seq: Sequence, drafted: int,
                              accepted: int) -> None:
        """Fold one round's acceptance into the sequence's EWMA and
        throttle/restore its γ. Observes the per-round acceptance-rate
        histogram (the /metrics signal the replay artifact commits)."""
        if drafted <= 0:
            return
        ecfg = self.engine_cfg
        rate = accepted / drafted
        alpha = ecfg.spec_ewma_alpha
        seq.spec_accept_ewma += alpha * (rate - seq.spec_accept_ewma)
        self.telemetry.spec_accept_rate.observe(rate)
        # Per-request spec exposure for the decode trace span.
        seq.spec_rounds += 1
        seq.spec_accepted_toks += accepted
        thr = ecfg.spec_throttle_below
        if thr > 0 and seq.spec_accept_ewma < thr:
            if seq.spec_gamma != 0:
                self.spec_throttles_total += 1
            base = max(1, ecfg.spec_probe_every)
            # Consecutive failed probes double the re-check interval
            # (capped at 8x), so a stream that never echoes spends a
            # vanishing fraction of its rounds on probe verifies.
            seq.spec_probe_interval = min(
                8 * base, max(base, seq.spec_probe_interval * 2))
            seq.spec_gamma = 0
            seq.spec_probe_countdown = seq.spec_probe_interval
        else:
            seq.spec_gamma = ecfg.num_speculative_tokens
            seq.spec_probe_interval = 0

    def _ngram_proposals(self, active_seqs: List[Sequence]
                         ) -> Dict[int, np.ndarray]:
        """Host-side prompt-lookup proposals for every non-throttled
        lane: {slot: proposed token array (1..γ)}. Runs in the host
        bubble between dispatches; sequences with no history match (or
        throttled to γ=0) simply propose nothing."""
        ecfg = self.engine_cfg
        gammas = [self._seq_spec_gamma(seq) for seq in active_seqs]
        # Probe alignment: ANY lane proposing makes the round a verify
        # dispatch for the whole batch, so a lane whose probe is due
        # drags every still-throttled lane into the same probe round —
        # the batch pays one shared verify instead of one per lane's
        # independent countdown (failed probes re-throttle with their
        # own backed-off intervals as usual).
        if any(g > 0 and s.spec_probe_interval > 0
               for s, g in zip(active_seqs, gammas)):
            gammas = [1 if g == 0 else g for g in gammas]
        props: Dict[int, np.ndarray] = {}
        for seq, gamma in zip(active_seqs, gammas):
            if gamma <= 0:
                continue
            # Slice BEFORE concatenating: the proposer only reads the
            # trailing NGRAM_SCAN_CAP tokens, and a full prompt+generated
            # list concat would put O(context) Python copying per lane
            # per round on the decode critical path at long contexts.
            hist = seq.generated[-NGRAM_SCAN_CAP:]
            if len(hist) < NGRAM_SCAN_CAP:
                hist = (seq.prompt_tokens[len(hist) - NGRAM_SCAN_CAP:]
                        + hist)
            prop = ngram_propose(hist, gamma, ecfg.ngram_window)
            if prop.size:
                props[seq.slot] = prop
            elif seq.spec_probe_interval > 0:
                # A probing lane that found nothing to propose goes back
                # to sleep instead of staying armed (scanning every
                # round and firing a verify on the next garbage match);
                # no new evidence, so the interval doesn't double.
                seq.spec_gamma = 0
                seq.spec_probe_countdown = seq.spec_probe_interval
        return props

    def _gate_mixed_batch(self, active_seqs: List[Sequence],
                          proposals: Dict[int, np.ndarray]
                          ) -> Dict[int, np.ndarray]:
        """Mixed-batch guard for fused-K dispatch (K > 1): a verify
        round advances a NON-proposing lane by exactly one token, while
        a fallback round advances every lane by up to K — so a lone
        echoic lane must not drag a wide batch of echo-free bystanders
        into 1-token rounds. Dispatch the verify only when the
        proposers' expected accepted tokens (EWMA-weighted) at least
        cover one token per bystander; otherwise degrade the round to
        the plain fused-K graph. K == 1 has no bystander deficit (a
        verify round strictly dominates a 1-step call), so the gate is
        off there. Returns proposals, or {} to force the fallback."""
        k_steps = max(1, self.engine_cfg.decode_steps_per_call)
        if k_steps <= 1 or not proposals:
            return proposals
        by_slot = {s.slot: s for s in active_seqs}
        expected = sum(by_slot[slot].spec_accept_ewma * len(p)
                       for slot, p in proposals.items()
                       if slot in by_slot)
        bystanders = len(active_seqs) - len(proposals)
        return proposals if expected >= bystanders else {}

    def _spec_width_for(self, proposals: Dict[int, np.ndarray]) -> int:
        """Smallest compiled verify width (γ+1) covering this round's
        longest proposal — probe-only rounds (every proposal length 1)
        run the narrow graph at near-plain cost."""
        longest = max(len(p) for p in proposals.values())
        for w in self._spec_widths:
            if w >= longest + 1:
                return w
        return self._spec_widths[-1]

    def _dispatch_verify(self, active_seqs: List[Sequence],
                         proposals: Dict[int, np.ndarray], s_len: int):
        """Stage + dispatch one verify-only round at the smallest ladder
        rung covering the batch and the compiled width ``s_len``
        (non-blocking). Returns (VerifyRoundOut, {slot: n_proposed},
        rung)."""
        ecfg = self.engine_cfg
        gamma = s_len - 1
        b = self._rung_for_slots(active_seqs)
        self._note_rung(b)
        (tokens, ctx_lens, bts, temps, top_ps, top_ks,
         _seeds, rpens, rlasts, windows) = self._stage_batch(active_seqs, b)
        cap = np.zeros((b,), np.int32)
        act = np.zeros((b,), bool)
        drafts = np.zeros((b, gamma), np.int32)
        n_prop = np.zeros((b,), np.int32)
        for seq in active_seqs:
            cap[seq.slot] = len(seq.pages) * ecfg.page_size
            act[seq.slot] = True
            prop = proposals.get(seq.slot)
            if prop is not None and prop.size:
                n = min(len(prop), gamma)
                drafts[seq.slot, :n] = prop[:n]
                n_prop[seq.slot] = n
        # Per-request seeds are not plumbed into spec rounds (acceptance
        # consumes randomness at a data-dependent rate, so a position-
        # keyed stream would not reproduce anyway); greedy — where the
        # byte-identity guarantee lives — is unaffected.
        t0 = self._note_decode_entry(active_seqs)
        out = self._verify_jit(
            self.params, self.kv, jnp.asarray(tokens),
            jnp.asarray(ctx_lens), jnp.asarray(bts), jnp.asarray(cap),
            jnp.asarray(act), jnp.asarray(drafts), jnp.asarray(n_prop),
            self._next_key(), jnp.asarray(temps), jnp.asarray(top_ps),
            jnp.asarray(top_ks), jnp.asarray(rpens), jnp.asarray(rlasts),
            jnp.asarray(windows))
        self.kv = out.kv
        # Stash the (non-blocking) dispatch wall and the cache-read
        # estimate for whichever caller pushes this round's ledger
        # record (sync path: after the fold; pipelined: at sync).
        self._last_verify_dt = self._note_decode_exit(t0, active_seqs)
        self._last_verify_kv_read = (
            sum(s.ctx_len for s in active_seqs) * s_len)
        self.spec_rounds_total += 1
        if self.telemetry.enabled:
            full = ecfg.num_speculative_tokens
            gammas = [s.spec_gamma if s.spec_gamma >= 0 else full
                      for s in active_seqs]
            self.telemetry.spec_gamma_g.set(sum(gammas) / len(gammas))
        return out, {s.slot: int(n_prop[s.slot]) for s in active_seqs}, b

    def _fold_spec_emissions(self, seqs: Dict[int, Sequence],
                             emit_by_slot: Dict[int, int],
                             prop_by_slot: Dict[int, int],
                             emitted: np.ndarray, n_acc: np.ndarray
                             ) -> Dict[int, List[int]]:
        """Fold one verify round's emissions into host state (shared by
        the sync and dispatch-ahead ngram paths): emit caps truncate at
        budget/pool limits, EOS stops a lane mid-round via
        _maybe_finish, and each lane's acceptance updates its adaptive
        γ. Lanes cancelled/preempted while the call was in flight are
        skipped — their tokens were speculative compute."""
        result: Dict[int, List[int]] = {}
        s_len = emitted.shape[1]      # this round's compiled width
        for slot, seq in seqs.items():
            if seq.done or seq.slot != slot or self.slots[slot] is not seq:
                continue
            got: List[int] = []
            for j in range(s_len):
                if seq.done or len(got) >= emit_by_slot.get(slot, 0):
                    break
                tok = int(emitted[slot, j])
                if tok < 0:
                    break
                seq.ctx_len += 1
                seq.generated.append(tok)
                if seq.first_token_time == 0.0:
                    seq.first_token_time = time.perf_counter()
                self._maybe_finish(seq, tok)
                got.append(tok)
            # Same clamped accounting as the draft path: only positions
            # the host could emit count as drafted, and accepted clamps
            # to that window, so capped rounds can't drift the rate.
            drafted = min(prop_by_slot.get(slot, 0),
                          emit_by_slot.get(slot, 0))
            accepted = min(int(n_acc[slot]), drafted)
            self.spec_drafted += drafted
            self.spec_accepted += accepted
            self._spec_update_adaptive(seq, drafted, accepted)
            if got:
                result[seq.request_id] = got
        if self.telemetry.enabled:
            self.telemetry.tokens_per_dispatch.observe(
                sum(len(t) for t in result.values()))
        return result

    def _ngram_decode_steps(self, max_steps: Optional[int] = None
                            ) -> Dict[int, List[int]]:
        """One synchronous draft-free spec round: propose (host numpy),
        verify-accept (one target forward at the current ladder rung),
        fold. Rounds where NO slot proposes — cold streams, throttled
        streams, no history echo — run the plain fused-K decode graph
        instead, so ngram spec is never slower than plain decode."""
        ecfg = self.engine_cfg
        s_len = ecfg.num_speculative_tokens + 1
        self._compact_slots()         # rung steps down when occupancy drops
        active_seqs = self.active_sequences()
        if not active_seqs:
            return {}
        active_seqs = self._preempt_for_pressure(active_seqs, s_len)
        active_seqs = [s for s in active_seqs
                       if not s.done and s.slot >= 0]
        if not active_seqs:
            return {}
        proposals = self._gate_mixed_batch(
            active_seqs, self._ngram_proposals(active_seqs))
        if not proposals:
            self.spec_fallback_rounds += 1
            return self._plain_decode_steps(max_steps)
        s_len = self._spec_width_for(proposals)
        active_seqs, emit_by_slot = self._spec_grant(active_seqs, s_len,
                                                     max_steps)
        if not active_seqs:
            return {}
        out, prop_by_slot, rung = self._dispatch_verify(active_seqs,
                                                        proposals, s_len)
        acc0 = self.spec_accepted
        result = self._fold_spec_emissions(
            {s.slot: s for s in active_seqs}, emit_by_slot, prop_by_slot,
            np.asarray(out.emitted), np.asarray(out.n_accepted))
        if self.telemetry.enabled:
            self._ledger_push(
                "spec_verify", rung=rung, slots=len(active_seqs),
                tokens=sum(len(t) for t in result.values()),
                device_s=self._last_verify_dt,
                kv_read=self._last_verify_kv_read,
                spec_accepted=self.spec_accepted - acc0)
        return result

    def _stage_ngram_call(self) -> Optional[dict]:
        """Stage one spec round non-blocking for the dispatch-ahead
        pipeline (PR-4's hybrid-chunk pattern): the verify dispatch
        enters ``_inflight`` and the host overlaps its device time with
        scheduler work — admission, prefetch, callbacks, and the NEXT
        round's n-gram matching. Rounds with no proposals stage a plain
        fused-K decode call instead (the same dispatch-ahead machinery).
        Caller guarantees the pipeline is empty (proposals need the
        previous round's accepted tokens, so spec chains at depth 1 of
        staging: sync round N, stage round N+1)."""
        ecfg = self.engine_cfg
        s_len = ecfg.num_speculative_tokens + 1
        self._compact_slots()
        active_seqs = self.active_sequences()
        if not active_seqs:
            return None
        active_seqs = self._preempt_for_pressure(active_seqs, s_len)
        active_seqs = [s for s in active_seqs
                       if not s.done and s.slot >= 0]
        if not active_seqs:
            return None
        proposals = self._gate_mixed_batch(
            active_seqs, self._ngram_proposals(active_seqs))
        if not proposals:
            self.spec_fallback_rounds += 1
            return self._stage_decode_call()
        s_len = self._spec_width_for(proposals)
        active_seqs, emit_by_slot = self._spec_grant(active_seqs, s_len,
                                                     None)
        if not active_seqs:
            return None
        out, prop_by_slot, rung = self._dispatch_verify(active_seqs,
                                                        proposals, s_len)
        call = {"spec": True, "emitted": out.emitted,
                "n_accepted": out.n_accepted,
                "allowed": dict(emit_by_slot), "n_prop": prop_by_slot,
                "seqs": {s.slot: s for s in active_seqs},
                "rung": rung, "outs": None, "final": None,
                "final_window": None}
        if self.telemetry.enabled:
            # Stage-time micros ride on the call; the record lands at
            # sync with the true device wall (see _sync_spec_call).
            call["ledger"] = {
                "kind": "spec_verify", "rung": rung,
                "slots": len(active_seqs), "tokens": 0,
                "chunk_tokens": 0, "steps": 1,
                "dispatch_s": self._last_verify_dt,
                "staging_s": self._last_staging_s,
                "bubble_s": self._pending_bubble,
                "kv_read": self._last_verify_kv_read,
                "compile": self._last_compile_event}
            self._last_staging_s = 0.0
            self._pending_bubble = 0.0
            self._last_compile_event = False
        return call

    def _sync_spec_call(self, call: dict) -> Dict[int, List[int]]:
        """Block on an in-flight spec round and fold its emissions
        (the _sync_oldest arm for ``spec`` calls)."""
        t0 = time.perf_counter()
        emitted = np.asarray(call["emitted"])           # [B, γ+1] blocks
        n_acc = np.asarray(call["n_accepted"])
        sync_dt = time.perf_counter() - t0
        if self.telemetry.enabled:
            dt = sync_dt
            self.telemetry.decode_sync_s.observe(dt)
            for seq in call["seqs"].values():
                if not seq.done and seq.slot >= 0 \
                        and self.slots[seq.slot] is seq:
                    seq.dispatch_wall_s += dt
        # Device wait, not host bubble (same rationale as _sync_oldest).
        self._last_decode_end = (
            time.perf_counter()
            if any(s is not None and not s.done for s in self.slots)
            else None)
        acc0 = self.spec_accepted
        result = self._fold_spec_emissions(call["seqs"], call["allowed"],
                                           call["n_prop"], emitted, n_acc)
        led = call.get("ledger")
        if led is not None and self.telemetry.enabled:
            self._ledger_push(
                led["kind"], rung=led["rung"], slots=led["slots"],
                tokens=sum(len(t) for t in result.values()),
                steps=led["steps"],
                device_s=led["dispatch_s"] + sync_dt,
                kv_read=led["kv_read"], staging_s=led["staging_s"],
                bubble_s=led["bubble_s"], compile_event=led["compile"],
                spec_accepted=self.spec_accepted - acc0)
        return result

    def _ngram_steps_pipelined(self) -> Dict[int, List[int]]:
        """Dispatch-ahead serving step for ngram spec: sync the in-flight
        round (its accepted tokens seed the next proposals — spec rounds
        cannot chain blind like plain decode carries), then stage the
        next round non-blocking. At steady state one verify dispatch is
        always in flight while the host does scheduler work + the next
        round's n-gram matching — the PR-7 host bubble hides behind the
        device just like plain dispatch-ahead."""
        result: Dict[int, List[int]] = {}
        if self._inflight:
            for rid, toks in self._sync_oldest().items():
                result.setdefault(rid, []).extend(toks)
        call = self._stage_ngram_call()
        if call is not None:
            self._inflight.append(call)
        return result

    # ------------------------------------------------------------------
    # Convenience batch generation (tests, bench, config-1 path)
    # ------------------------------------------------------------------

    def generate(self, prompts: Sequence[Sequence[int]], max_new_tokens: int,
                 temperature: float = 0.0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None) -> List[List[int]]:
        """Generate for a batch of token-id prompts; returns generated ids."""
        seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                         max_new_tokens=max_new_tokens, temperature=temperature,
                         top_p=top_p, eos_token_id=eos_token_id)
                for i, p in enumerate(prompts)]
        for s in seqs:
            if not self.can_ever_admit(s):
                raise ValueError(
                    f"request {s.request_id} needs {self._pages_reserved(s)} "
                    f"pages; pool holds {self.engine_cfg.num_pages - 1}")
        results: Dict[int, List[int]] = {}
        pending = list(seqs)
        while pending or self.active_sequences():
            while pending and self.free_slots() and self.can_admit(pending[0]):
                self.prefill(pending.pop(0))
            self.decode_steps()
            # Optimistic admission may have preempted sequences; requeue
            # them at the head for recompute-resume.
            pending[0:0] = self.take_preempted()
            for s in [s for s in self.slots if s is not None and s.done]:
                results[s.request_id] = s.generated
                self.release(s)
        return [results[i] for i in range(len(seqs))]
