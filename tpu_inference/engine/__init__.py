"""Serving engine: paged KV cache, compiled prefill/decode graphs,
continuous-batching scheduler, sampling, speculative decoding."""
