"""HBM-aware engine sizing: derive batch and KV pool from the chip.

VERDICT r3 found the replay saturating at ``max_batch_size=8`` — "a batch
size chosen for tests, not for the chip": at 1B params + int8 KV a 16 GB
v5e supports batch 16-32 easily, and a server capped below the trace's
arrival rate measures queue depth, not the model. The reference had no
equivalent knob to size (its server half was an external Ollama binary);
this module is the TPU-native answer: compute what the chip's HBM
actually supports and serve with ``--max-batch-size auto --num-pages
auto``.

Sizing model (per chip, serving-engine residents only):

    usable  = (1 - reserve_frac) * hbm        # XLA runtime reservations
    budget  = usable - weights/tp - activation_headroom
    tokens  = budget // (kv_bytes_per_token / tp)
    pages   = tokens // page_size
    batch   = min(batch_cap, tokens // target_ctx)

``target_ctx`` is the context the operator expects a typical sequence to
hold (default: half the per-sequence maximum) — the pool is sized by
bytes, the batch by how many such sequences can decode concurrently
without page-pressure evictions. The cap keeps small models (1B on 16 GB
could hold hundreds of sequences) at a batch the MXU still benefits
from rather than one that only stretches tail latency.

Weight-byte estimates count embeddings + matmul params from the config
(exact enough for sizing; int8 adds per-channel scales and keeps
embeddings in model dtype — see models/quant.py). KV bytes follow the
pool layouts in engine/kv_cache.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# Per-chip HBM capacities (bytes). The canonical table — bench.py's
# fits-on-chip gate imports it via detect_hbm_bytes().
HBM_BY_DEVICE_KIND = {
    "TPU v5 lite": 16e9,
    "TPU v4": 32e9,
    "TPU v5p": 95e9,
    "TPU v6 lite": 32e9,
}
DEFAULT_HBM_BYTES = 16e9  # unknown chip / CPU smoke runs: size as a v5e

# Per-chip bf16 peak FLOP/s (the MFU denominator; bench.py keeps its own
# copy paired with HBM bandwidth for the roofline extras). Unknown chips
# / CPU report against a v5e so the /metrics MFU estimate always renders
# — on CPU it is a sizing exercise, like DEFAULT_HBM_BYTES.
PEAK_FLOPS_BY_DEVICE_KIND = {
    "TPU v5 lite": 394e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}
DEFAULT_PEAK_FLOPS = 394e12

# Per-chip HBM bandwidth (bytes/s) — the bytes-roofline denominator the
# step-ledger bottleneck verdicts (telemetry.roofline_report) divide by.
# Same unknown-chip stance as the peak-FLOPs table: CPU reports against
# a v5e so the attribution math always renders.
PEAK_HBM_BW_BY_DEVICE_KIND = {
    "TPU v5 lite": 819e9,
    "TPU v4": 1228e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
}
DEFAULT_PEAK_HBM_BW = 819e9


def estimate_param_count(model_cfg) -> int:
    """Parameter count from the architecture config (norms elided)."""
    d, f, L, V = (model_cfg.d_model, model_cfg.d_ff, model_cfg.n_layers,
                  model_cfg.vocab_size)
    kv_w = model_cfg.n_kv_heads * model_cfg.head_dim
    embed = V * d * (1 if model_cfg.tie_embeddings else 2)
    attn = 2 * d * d + 2 * d * kv_w
    if model_cfg.n_experts:
        ffn = model_cfg.n_experts * 3 * d * f + d * model_cfg.n_experts
    else:
        ffn = 3 * d * f
    return embed + L * (attn + ffn)


def weight_bytes(model_cfg, quant: str = "none") -> int:
    """Resident weight bytes. int8 stores matmul weights as one byte +
    per-output-channel f32 scales; int4 as half a byte + per-group
    scales (models/quant.py GROUP_SIZE=128: 4 scale bytes per 128
    codes ≈ 6% overhead); embeddings stay in model dtype
    (models/quant.py quantizes matmuls only)."""
    n = estimate_param_count(model_cfg)
    itemsize = 2  # bf16 serving dtype
    if quant in ("int8", "int4"):
        d, V = model_cfg.d_model, model_cfg.vocab_size
        embed = V * d * (1 if model_cfg.tie_embeddings else 2)
        matmul = n - embed
        if quant == "int4":
            from tpu_inference.models.quant import GROUP_SIZE

            # 0.5 B codes + one f32 scale per GROUP_SIZE weights.
            return embed * itemsize + int(matmul * (0.5 + 4 / GROUP_SIZE))
        # Scales: one f32 per output channel; ~d_model-ish rows per
        # matmul — well under 1% of codes. Budget 1% rather than walk
        # every shape.
        return embed * itemsize + int(matmul * 1.01)
    return n * itemsize


def kv_bytes_per_token(model_cfg, kv_quant: str = "none") -> int:
    """Pool bytes one token occupies across all layers (K and V).

    bf16: 2 * L * Hkv * D * 2; int8: codes (1 byte) + a per-(token,
    kv-head) f32 scale; int4: nibble-packed codes (D/2 bytes) + the
    same f32 scale — engine/kv_cache.py layouts."""
    L = model_cfg.n_layers
    hkv = model_cfg.n_kv_heads
    d = model_cfg.head_dim
    if kv_quant == "int8":
        return 2 * L * hkv * (d + 4)
    if kv_quant == "int4":
        return 2 * L * hkv * (d // 2 + 4)
    return 2 * L * hkv * d * 2


@dataclasses.dataclass(frozen=True)
class AutoSizing:
    max_batch_size: int
    num_pages: int
    # Evidence for logs/metrics: where the budget went (per chip).
    hbm_bytes: int
    weight_bytes_per_chip: int
    kv_pool_bytes_per_chip: int
    kv_bytes_per_token: int
    target_ctx: int


def auto_size(model_cfg, *, hbm_bytes: Optional[float] = None,
              quant: str = "none", kv_quant: str = "none", tp: int = 1,
              page_size: int = 16, max_pages_per_seq: int = 64,
              target_ctx: Optional[int] = None, batch_cap: int = 32,
              reserve_frac: float = 0.15,
              activation_headroom: int = 512 << 20,
              speculative: bool = False) -> AutoSizing:
    """Size ``max_batch_size`` and ``num_pages`` for the chip.

    Raises ValueError when the weights alone exceed the per-chip budget
    (the caller should quantize, raise tp, or pick a bigger chip) or
    when the KV budget can't hold even one full-length sequence.
    """
    hbm = float(hbm_bytes if hbm_bytes is not None else DEFAULT_HBM_BYTES)
    wb = weight_bytes(model_cfg, quant)
    per_chip_w = wb // tp
    usable = (1.0 - reserve_frac) * hbm
    budget = usable - per_chip_w - activation_headroom
    if budget <= 0:
        raise ValueError(
            f"{model_cfg.name}: weights (~{per_chip_w / 1e9:.1f} GB/chip, "
            f"quant={quant}, tp={tp}) + {activation_headroom >> 20} MB "
            f"activation headroom exceed {usable / 1e9:.1f} GB usable HBM "
            f"({hbm / 1e9:.0f} GB chip); use --quant int8, more tp, or a "
            "bigger chip")
    kv_tok = kv_bytes_per_token(model_cfg, kv_quant)
    tokens = int(budget // (kv_tok / tp))
    num_pages = tokens // page_size
    # Don't hoard HBM a small model can never address: cap the pool at
    # every slot holding a full-length sequence, with 4x slack for the
    # prefix cache and freed-page fragmentation.
    num_pages = min(num_pages, 4 * batch_cap * max_pages_per_seq)
    # Page 0 is the allocator's reserved trash page (kv_cache.py):
    # admission only ever grants num_pages - 1, so the token/batch math
    # must budget on the usable count (ADVICE r4).
    tokens = min(tokens, (num_pages - 1) * page_size)
    if num_pages < max_pages_per_seq + 1:  # +1: trash page (kv_cache.py)
        raise ValueError(
            f"{model_cfg.name}: KV budget ({budget / 1e9:.2f} GB/chip) "
            f"holds only {num_pages} pages < one full sequence "
            f"({max_pages_per_seq}); lower --max-pages-per-seq or "
            "shrink the pool bytes with --kv-quant int8 (or int4)")
    ctx = int(target_ctx) if target_ctx else (page_size * max_pages_per_seq
                                              // 2)
    ctx = max(1, min(ctx, page_size * max_pages_per_seq))
    win = getattr(model_cfg, "sliding_window", 0)
    if win and not speculative:
        # (Only when eviction will actually run: spec decode disables it
        # — a window-less draft reads the full context, so each running
        # sequence keeps O(context) pages; see engine.swa_evict.)
        # Behind-window eviction (engine._evict_behind_window) caps a
        # running SWA sequence's live KV at ~window tokens — batch
        # sizes against that, not the full context. (The prefill peak
        # briefly holds the whole prompt; the page-span margin covers
        # typical prompts, and admission charges the true peak.)
        ctx = min(ctx, win + 2 * page_size)
    batch = max(1, min(batch_cap, tokens // ctx))
    return AutoSizing(
        max_batch_size=batch, num_pages=num_pages, hbm_bytes=int(hbm),
        weight_bytes_per_chip=int(per_chip_w),
        kv_pool_bytes_per_chip=int(num_pages * page_size * kv_tok // tp),
        kv_bytes_per_token=kv_tok, target_ctx=ctx)


def detect_host_ram_bytes() -> int:
    """Available host RAM in bytes: /proc/meminfo MemAvailable (the
    kernel's own estimate of allocatable-without-swapping memory),
    falling back to half of the sysconf total on platforms without it.
    The host KV tier's auto-sizing input."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    import os

    try:
        return (os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")) // 2
    except (ValueError, OSError, AttributeError):
        return 8 << 30


def auto_host_cache_pages(model_cfg, *, kv_quant: str = "none",
                          page_size: int = 16,
                          host_ram_bytes: Optional[int] = None,
                          fraction: float = 0.5,
                          reserve_bytes: int = 2 << 30) -> int:
    """Size ``--host-cache-pages auto`` from the machine's available
    RAM: ``fraction`` of (available - reserve) divided by the page's
    byte cost in the serving kv_quant layout. The reserve keeps the OS,
    the Python heap, and tokenizer/weight staging out of the tier's
    budget; 0 when the machine has no headroom (the tier then simply
    stays off rather than inviting the OOM killer)."""
    avail = (detect_host_ram_bytes() if host_ram_bytes is None
             else int(host_ram_bytes))
    budget = max(0, int((avail - reserve_bytes) * fraction))
    per_page = page_size * kv_bytes_per_token(model_cfg, kv_quant)
    return budget // max(per_page, 1)


def detect_hbm_bytes() -> float:
    """Per-chip HBM of the visible device (table lookup; CPU and unknown
    chips size as a 16 GB v5e so smoke runs exercise the same math)."""
    import jax

    return HBM_BY_DEVICE_KIND.get(jax.devices()[0].device_kind,
                                  DEFAULT_HBM_BYTES)


def detect_peak_flops() -> float:
    """Per-chip bf16 peak FLOP/s of the visible device — the denominator
    of the /metrics MFU estimate (CPU and unknown chips report against a
    v5e, same stance as detect_hbm_bytes)."""
    import jax

    return PEAK_FLOPS_BY_DEVICE_KIND.get(jax.devices()[0].device_kind,
                                         DEFAULT_PEAK_FLOPS)


def detect_peak_hbm_bw() -> float:
    """Per-chip HBM bandwidth (bytes/s) of the visible device — the
    bytes-roofline denominator for step-ledger bottleneck verdicts."""
    import jax

    return PEAK_HBM_BW_BY_DEVICE_KIND.get(jax.devices()[0].device_kind,
                                          DEFAULT_PEAK_HBM_BW)


def decode_ladder_rungs(top: int, base: int = 8) -> tuple:
    """The compiled-decode-graph ladder for a top batch size: doubling
    rungs from ``base`` (8/16/32/64...) strictly below ``top``, plus
    ``top`` itself. The engine compiles every rung at warmup and moves
    between them as occupancy changes, so a near-empty batch never pays
    the top rung's per-step latency (README "Batch ladder").

        top=32 -> (8, 16, 32);  top=24 -> (8, 16, 24);  top=8 -> (8,)

    ``top <= base`` collapses to the single legacy rung — small serving
    configs (tests, CPU smoke) keep exactly one compiled decode graph.
    """
    top = int(top)
    if top <= 0:
        raise ValueError(f"decode ladder needs a positive top, got {top}")
    rungs = []
    r = base
    while r < top:
        rungs.append(r)
        r *= 2
    rungs.append(top)
    return tuple(rungs)


def validate_ladder(rungs, top: int) -> tuple:
    """THE ladder invariant — strictly increasing positive rungs ending
    at ``top`` (the engine's slot-array size) — shared by
    parse_decode_ladder (CLI, before any model loads) and
    InferenceEngine.__init__ (boot), so the two sites cannot drift.
    Returns the rungs as a tuple."""
    rungs = tuple(rungs)
    if (not rungs or list(rungs) != sorted(set(rungs)) or rungs[0] < 1
            or rungs[-1] != top):
        raise ValueError(
            f"decode_ladder {list(rungs)} must be strictly increasing, "
            f"positive, and end at max_batch_size ({top})")
    return rungs


def parse_decode_ladder(spec: str, top: int) -> tuple:
    """THE --decode-ladder parser, shared by the server CLI and the
    benchmarks so their accepted grammar cannot drift: 'auto' (doubling
    rungs up to ``top``), 'off' (one graph at ``top``), or comma rungs
    like '8,16,32' — which must end at ``top``, the engine's slot-array
    size. Raises ValueError with a usage-quality message; CLI callers
    turn that into an argparse error before any model loads."""
    if spec == "auto":
        return decode_ladder_rungs(top)
    if spec == "off":
        return (top,)
    try:
        rungs = tuple(int(r) for r in spec.split(","))
    except ValueError:
        raise ValueError(
            f"--decode-ladder {spec!r}: expected 'auto', 'off', or "
            "comma-separated rungs like '8,16,32'")
    # The engine's boot-time invariant, applied HERE so a bad spec is a
    # usage error before any checkpoint loads, per the contract above.
    return validate_ladder(rungs, top)


# Chip-seconds one decode token costs relative to one prefill token in
# the pd-split heuristic: decode is memory-bound single-token dispatch
# work (the whole weight stream per token) while prefill amortizes the
# stream over the prompt, so a decode token "weighs" several prefill
# tokens when dividing workers between the phases.
PD_DECODE_COST_FACTOR = 4.0


def pd_worker_roles(dp: int, spec: str,
                    prompt_token_rate: Optional[float] = None,
                    decode_token_rate: Optional[float] = None) -> tuple:
    """Size the prefill:decode worker split for ``--pd-ratio`` (README
    "P/D disaggregation"): returns a dp-length role tuple
    ``("prefill",)*P + ("decode",)*D``.

    ``spec`` is either an explicit ``"P:D"`` ratio (scaled to dp, each
    side floored at one worker) or ``"auto"``: split by each phase's
    share of chip-seconds, computed from the observed prompt/decode
    token mix when the caller has one (``*_token_rate``, tokens per
    second offered to each phase) and from the BurstGPT-shaped default
    (512-token prompts, 128-token replies) otherwise, with decode
    tokens weighted PD_DECODE_COST_FACTOR heavier per token.

    Raises ValueError with flag-spelling messages (CLI callers turn
    them into usage errors before any model loads)."""
    if dp < 2:
        raise ValueError(
            f"--pd-ratio needs dp >= 2 (got dp={dp}): the split puts "
            "prefill and decode on different workers")
    if spec == "auto":
        p_rate = float(prompt_token_rate) if prompt_token_rate else 512.0
        d_rate = float(decode_token_rate) if decode_token_rate else 128.0
        share = p_rate / (p_rate + PD_DECODE_COST_FACTOR * d_rate)
    else:
        try:
            p_part, d_part = (int(x) for x in spec.split(":"))
        except ValueError:
            raise ValueError(
                f"--pd-ratio {spec!r}: expected 'auto' or 'P:D' "
                "(e.g. '1:1', '1:3')")
        if p_part < 1 or d_part < 1:
            raise ValueError(
                f"--pd-ratio {spec!r}: both sides must be >= 1")
        share = p_part / (p_part + d_part)
    n_prefill = max(1, min(dp - 1, round(dp * share)))
    return ("prefill",) * n_prefill + ("decode",) * (dp - n_prefill)


def resolve_model_and_checkpoint(model: str,
                                 checkpoint: Optional[str] = None):
    """(model_cfg, checkpoint_path) from a preset name, an HF checkpoint
    dir, or "auto" with ``checkpoint`` set. THE model-resolution rule:
    build_server and the pre-boot sizing path both call this, so the
    model that gets sized is always the model that boots."""
    import os

    from tpu_inference.config import PRESETS

    if model in PRESETS:
        return PRESETS[model](), checkpoint
    from tpu_inference.models import weights

    src = checkpoint if (model == "auto" and checkpoint) else model
    if not (isinstance(src, str)
            and os.path.exists(os.path.join(src, "config.json"))):
        raise ValueError(
            f"unknown model {model!r}: not a preset "
            f"({', '.join(sorted(PRESETS))}) and not a HF checkpoint "
            f"directory with a config.json")
    return weights.config_from_hf(src), (checkpoint or src)


def resolve_model_config(model: str, checkpoint: Optional[str] = None):
    """Model config only (see resolve_model_and_checkpoint)."""
    return resolve_model_and_checkpoint(model, checkpoint)[0]


def int_or_auto(v: str):
    """argparse type for --max-batch-size/--num-pages: an int or the
    literal 'auto' (clean usage error on anything else)."""
    import argparse

    if v == "auto":
        return v
    try:
        return int(v)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected an integer or 'auto', got {v!r}")


def resolve_sizing_args(args) -> tuple:
    """Shared CLI hook: turn 'auto' in ``args.max_batch_size`` /
    ``args.num_pages`` into chip-derived values (no-op when both are
    ints). Reads model/checkpoint/quant/kv_quant/tp/page_size/
    max_pages_per_seq and the optional target_ctx/batch_cap attrs.
    Returns (max_batch_size, num_pages)."""
    mbs, pages = args.max_batch_size, args.num_pages
    if "auto" not in (mbs, pages):
        return mbs, pages
    mcfg = resolve_model_config(args.model, args.checkpoint)
    sz = auto_size(
        mcfg, hbm_bytes=detect_hbm_bytes(), quant=args.quant,
        kv_quant=args.kv_quant, tp=args.tp, page_size=args.page_size,
        max_pages_per_seq=args.max_pages_per_seq,
        target_ctx=getattr(args, "target_ctx", 0) or None,
        batch_cap=getattr(args, "batch_cap", 32),
        speculative=bool(getattr(args, "draft_model", None)))
    if mbs == "auto":
        mbs = sz.max_batch_size
    if pages == "auto":
        pages = sz.num_pages
    import sys

    print(f"[autosize] {mcfg.name}: batch={mbs} num_pages={pages} "
          f"(hbm {sz.hbm_bytes / 1e9:.0f} GB, weights/chip "
          f"{sz.weight_bytes_per_chip / 1e9:.2f} GB, kv pool/chip "
          f"{sz.kv_pool_bytes_per_chip / 1e9:.2f} GB, target ctx "
          f"{sz.target_ctx})", file=sys.stderr)
    return mbs, pages
