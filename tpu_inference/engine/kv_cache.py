"""Paged KV cache: an HBM block pool with per-sequence block tables.

The reference delegates all KV management to its external Ollama server
(SURVEY.md §0); this is the TPU-native equivalent of vLLM's PagedAttention
memory model, re-designed for XLA's static-shape world:

- Device side, per layer: one pool array ``[L, P, page, Hkv, D]`` for K and V.
  Page 0 is a reserved **trash page**: padded / inactive token slots write
  there, so every scatter has a valid static target and no branching.
- Sequences address the pool through **block tables** ``[B, max_pages]``
  (int32 page ids, 0-filled), recomputed on the host and shipped each step —
  tiny arrays, so host->device traffic stays negligible.
- Writes are flat scatters (token -> page*page_size + offset); reads gather a
  sequence's pages into a contiguous [B, max_pages*page, Hkv, D] view for the
  dense-reference attention path. The Pallas decode kernel (kernels/) reads
  pages directly from HBM instead of materializing the gather.

Host side, ``PageAllocator`` is a free-list with refcounts so shared prompt
prefixes can map the same physical pages (copy-on-write is unnecessary for
inference: pages are append-only within a sequence).
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_inference import integrity
from tpu_inference.config import EngineConfig, ModelConfig


class KVPages(NamedTuple):
    """Device-side KV pool. k, v: [L, num_pages, page_size, Hkv, head_dim].

    With int8 KV quantization (EngineConfig.kv_quant), k/v hold int8
    codes and ``k_scale``/``v_scale`` hold per-(token, kv-head) f32
    scales ``[L, num_pages, page_size, Hkv]`` — symmetric quantization
    over the head_dim axis, the standard KV-cache scheme. Decode HBM
    traffic for the KV working set halves vs bf16; dequantization
    happens on the consumer side (in-kernel for Pallas, at gather for
    the dense path). ``None`` scales = unquantized pool.

    With int4 (kv_quant="int4") k/v hold **uint8 nibble-packed** codes
    ``[..., head_dim // 2]`` — byte i carries code i (low nibble) and
    code i + head_dim/2 (high nibble), so unpacking is a concat, never
    an interleave — with the same per-(token, head) scale pools. KV HBM
    traffic quarters vs bf16. The mode is carried by the pool DTYPE
    (uint8 = packed int4, int8 = int8), which stays static under jit —
    a bool field here would become a traced pytree leaf inside the
    decode-step carry.
    """

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def packed_int4(self) -> bool:
        return self.k.dtype == jnp.uint8


def alloc_kv_pages(model_cfg: ModelConfig, engine_cfg: EngineConfig,
                   dtype=None, sharding=None,
                   scale_sharding=None) -> KVPages:
    """Allocate the pool; with ``sharding`` each chip materializes only its
    shard (never the full replicated pool — at 70B scale that would OOM)."""
    shape = (model_cfg.n_layers, engine_cfg.num_pages, engine_cfg.page_size,
             model_cfg.n_kv_heads, model_cfg.head_dim)
    dtype = dtype or model_cfg.dtype
    if engine_cfg.kv_quant not in ("none", "int8", "int4"):
        raise ValueError(f"unknown kv_quant mode {engine_cfg.kv_quant!r}; "
                         "one of ('none', 'int8', 'int4')")
    if engine_cfg.kv_quant == "int4" and model_cfg.head_dim % 2:
        raise ValueError("kv_quant='int4' needs an even head_dim to "
                         f"nibble-pack, got {model_cfg.head_dim}")
    if engine_cfg.kv_quant != "none":
        code_dtype = (jnp.uint8 if engine_cfg.kv_quant == "int4"
                      else jnp.int8)
        code_shape = (shape[:-1] + (shape[-1] // 2,)
                      if engine_cfg.kv_quant == "int4" else shape)
        zeros = jax.jit(lambda: jnp.zeros(code_shape, code_dtype),
                        out_shardings=sharding)
        szeros = jax.jit(lambda: jnp.zeros(shape[:-1], jnp.float32),
                         out_shardings=scale_sharding)
        return KVPages(k=zeros(), v=zeros(), k_scale=szeros(),
                       v_scale=szeros())
    zeros = jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sharding)
    return KVPages(k=zeros(), v=zeros())


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-(token, head) symmetric int8 over head_dim.

    x: [B, S, Hkv, D] -> (codes int8 [B,S,Hkv,D], scale f32 [B,S,Hkv]).
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def quantize_kv_int4(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-(token, head) symmetric int4 over head_dim, nibble-packed.

    x: [B, S, Hkv, D] -> (packed uint8 [B,S,Hkv,D//2], scale f32
    [B,S,Hkv]). Codes live in [-7, 7]; byte i = code i (low nibble) |
    code i+D/2 (high nibble) so unpack is a concat along D.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -7, 7).astype(jnp.int32)
    half = x.shape[-1] // 2
    lo, hi = q[..., :half], q[..., half:]
    packed = ((hi << 4) | (lo & 0xF)) & 0xFF
    return packed.astype(jnp.uint8), scale


def unpack_int4_kv(packed: jax.Array) -> jax.Array:
    """uint8 nibble-packed codes [..., D//2] -> int32 codes [..., D].

    Pure integer ops (compare/select sign extension, no bitcasts), so it
    lowers both through XLA (dense gather path) and Mosaic (in-kernel
    dequant in the paged decode/prefill kernels).
    """
    p = packed.astype(jnp.int32)
    lo = p & 0xF
    hi = (p >> 4) & 0xF
    lo = lo - jnp.where(lo > 7, 16, 0)
    hi = hi - jnp.where(hi > 7, 16, 0)
    return jnp.concatenate([lo, hi], axis=-1)


def slot_mapping(block_tables: jax.Array, positions: jax.Array,
                 valid: jax.Array, page_size: int) -> jax.Array:
    """Map absolute token positions to flat pool slots.

    block_tables: [B, max_pages]; positions: [B, S]; valid: [B, S] bool.
    Invalid tokens map to slot 0 (the trash page). Returns [B, S] int32.
    """
    page_of_pos = positions // page_size                     # [B, S]
    page_ids = jnp.take_along_axis(block_tables, page_of_pos, axis=1)
    slots = page_ids * page_size + positions % page_size
    return jnp.where(valid, slots, 0).astype(jnp.int32)


def write_kv(kv: KVPages, layer_idx: jax.Array, k_new: jax.Array,
             v_new: jax.Array, slots: jax.Array) -> KVPages:
    """Scatter new K/V ([B, S, Hkv, D]) into the pool at flat ``slots`` [B,S].

    Quantized pools quantize on the way in (codes + per-token-head scale
    scatter to the same flat slots)."""
    L, P, pg, H, D = kv.k.shape
    flat = slots.reshape(-1)
    if kv.quantized:
        qfn = quantize_kv_int4 if kv.packed_int4 else quantize_kv
        k_new, ks = qfn(k_new)
        v_new, vs = qfn(v_new)
        ksf = kv.k_scale.reshape(L, P * pg, H)
        vsf = kv.v_scale.reshape(L, P * pg, H)
        ksf = ksf.at[layer_idx, flat].set(ks.reshape(-1, H))
        vsf = vsf.at[layer_idx, flat].set(vs.reshape(-1, H))
        k_scale = ksf.reshape(L, P, pg, H)
        v_scale = vsf.reshape(L, P, pg, H)
    else:
        k_scale, v_scale = kv.k_scale, kv.v_scale
    kf = kv.k.reshape(L, P * pg, H, D)
    vf = kv.v.reshape(L, P * pg, H, D)
    kf = kf.at[layer_idx, flat].set(k_new.reshape(-1, H, D).astype(kv.k.dtype))
    vf = vf.at[layer_idx, flat].set(v_new.reshape(-1, H, D).astype(kv.v.dtype))
    return KVPages(k=kf.reshape(L, P, pg, H, D), v=vf.reshape(L, P, pg, H, D),
                   k_scale=k_scale, v_scale=v_scale)


def gather_kv(kv: KVPages, layer_idx: jax.Array,
              block_tables: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Gather each sequence's pages into contiguous
    [B, max_pages*pg, H, head_dim].

    ``d_pool`` is the pool's trailing dim as STORED — head_dim, except
    head_dim/2 for packed-int4 pools (two nibbles per byte; the kernels'
    d_pool convention) — so the gather below is [B, max_pages*pg, H,
    d_pool] until unpack_int4_kv doubles it back to head_dim.
    Quantized pools dequantize after the gather (f32 out — the dense
    attention path computes in f32 anyway)."""
    b, mp = block_tables.shape
    _, _, pg, H, d_pool = kv.k.shape
    k = kv.k[layer_idx][block_tables].reshape(b, mp * pg, H, d_pool)
    v = kv.v[layer_idx][block_tables].reshape(b, mp * pg, H, d_pool)
    if kv.packed_int4:
        k, v = unpack_int4_kv(k), unpack_int4_kv(v)
    if kv.quantized:
        ks = kv.k_scale[layer_idx][block_tables].reshape(b, mp * pg, H)
        vs = kv.v_scale[layer_idx][block_tables].reshape(b, mp * pg, H)
        k = k.astype(jnp.float32) * ks[..., None]
        v = v.astype(jnp.float32) * vs[..., None]
    return k, v


class PageAllocator:
    """Host-side free-list allocator with refcounts (prefix sharing).

    Page 0 is reserved as the trash page and never allocated. The engine's
    admission control (SURVEY.md §5 "Failure detection": OOM-safe admission)
    asks ``can_allocate`` before scheduling a sequence.
    """

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._refs = [0] * num_pages
        self._cached = [False] * num_pages
        # Pages held ONLY by the prefix cache (refs == 1 and cached):
        # reclaimable capacity. Kept as an O(1) counter updated on the
        # engine thread so metrics scrapes from other threads read a
        # GIL-atomic int instead of iterating a mutating dict.
        self.evictable_count = 0
        # Optional observer fired on every evictability flip —
        # (page, became_evictable) — at exactly the points the counter
        # moves. The prefix cache uses it to keep an evictable-ordered
        # structure, so evict() pops victims in O(evicted) instead of
        # scanning the whole (mostly share-pinned) LRU table.
        self.on_evictable = None
        # Lifetime alloc/free churn counters, exported by telemetry as
        # tpu_inf_kv_page_{allocs,frees}_total (read-through, so the
        # allocator itself never imports the metrics layer). Plain ints:
        # engine-thread writes, GIL-atomic reads from scrape threads.
        self.pages_allocated_total = 0
        self.pages_freed_total = 0

    @property
    def num_free(self) -> int:
        return len(self._free)

    def _flip_evictable(self, page: int, up: bool) -> None:
        self.evictable_count += 1 if up else -1
        if self.on_evictable is not None:
            self.on_evictable(page, up)

    def mark_cached(self, page: int) -> None:
        """Flag a page as prefix-cache-held (cache owns one of its refs)."""
        assert self._refs[page] > 0 and not self._cached[page]
        self._cached[page] = True
        if self._refs[page] == 1:
            self._flip_evictable(page, True)

    def unmark_cached(self, page: int) -> None:
        assert self._cached[page]
        self._cached[page] = False
        if self._refs[page] == 1:
            self._flip_evictable(page, False)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def allocate(self, n: int = 1) -> List[int]:
        if len(self._free) < n:
            raise MemoryError(f"KV pool exhausted: need {n}, have {len(self._free)}")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        self.pages_allocated_total += n
        return pages

    def share(self, page: int) -> int:
        """Increment refcount for a prefix-shared page."""
        assert self._refs[page] > 0
        self._refs[page] += 1
        if self._cached[page] and self._refs[page] == 2:
            self._flip_evictable(page, False)  # no longer sole-referenced
        return page

    def refcount(self, page: int) -> int:
        return self._refs[page]

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p == 0:
                continue
            assert self._refs[p] > 0, f"double free of page {p}"
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)
                self.pages_freed_total += 1
            elif self._refs[p] == 1 and self._cached[p]:
                self._flip_evictable(p, True)  # cache is now sole holder


def pages_needed(n_tokens: int, page_size: int,
                 already: int = 0) -> int:
    """Pages to add so a sequence of ``already`` tokens can hold n_tokens more."""
    total = -(-(already + n_tokens) // page_size)
    have = -(-already // page_size)
    return max(0, total - have)


# ---------------------------------------------------------------------------
# Host tier: device<->host page copies (tiered KV cache, README "Tiered
# KV cache"). Evicted prefix-cache pages demote to host RAM instead of
# being dropped, and promote back into freshly allocated device pages
# when a returning prompt needs them — device<->host copies are cheap
# relative to re-prefilling the tokens they hold.
# ---------------------------------------------------------------------------


class HostKVPage(NamedTuple):
    """Host copy of ONE pool page, in the pool's stored layout: k/v are
    ``[L, page_size, Hkv, d_pool]`` in the pool dtype (bf16, int8 codes,
    or uint8 nibble-packed int4 — the copy is layout-agnostic, so every
    quantization mode round-trips bit-exactly), scales ``[L, page_size,
    Hkv]`` f32 or None for unquantized pools."""

    k: np.ndarray
    v: np.ndarray
    k_scale: Optional[np.ndarray] = None
    v_scale: Optional[np.ndarray] = None

    @property
    def nbytes(self) -> int:
        n = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            n += self.k_scale.nbytes + self.v_scale.nbytes
        return n


# Fixed gather/scatter width: every swap pads its page-index vector to
# a multiple of this and runs in SWAP_CHUNK-page groups, so XLA compiles
# exactly ONE gather and ONE scatter graph per pool dtype — a variable
# width would pay a fresh compile mid-serving the first time each batch
# size appears (pad slots target page 0, the trash page).
SWAP_CHUNK = 8


def _chunk_indices(pages: List[int]):
    """Yield SWAP_CHUNK-wide int32 index arrays covering ``pages``,
    zero-padded (trash page) at the tail."""
    for at in range(0, len(pages), SWAP_CHUNK):
        group = pages[at:at + SWAP_CHUNK]
        idx = np.zeros((SWAP_CHUNK,), np.int32)
        idx[:len(group)] = group
        yield len(group), idx


def offload_pages(kv: KVPages, pages: List[int]) -> List[HostKVPage]:
    """Copy ``pages`` out of the device pool into host memory.

    All chunk gathers are dispatched first and fetched with ONE
    device_get (one stream sync for the whole batch), then split per
    page so each HostKVPage owns its bytes. Blocks until any in-flight
    dispatch that last donated the pool has settled — correct by
    construction, and the eviction path that calls this was about to
    reuse the pages anyway."""
    n = len(pages)
    if n == 0:
        return []
    chunks = []
    for count, idx_np in _chunk_indices(pages):
        idx = jnp.asarray(idx_np)
        arrs = [kv.k[:, idx], kv.v[:, idx]]
        if kv.quantized:
            arrs += [kv.k_scale[:, idx], kv.v_scale[:, idx]]
        chunks.append((count, arrs))
    host = jax.device_get([arrs for _, arrs in chunks])
    out: List[HostKVPage] = []
    for (count, _), fetched in zip(chunks, host):
        k, v = fetched[0], fetched[1]
        ks, vs = (fetched[2], fetched[3]) if kv.quantized else (None, None)
        # .copy(): the per-page slices must not pin the padded buffer.
        out.extend(
            HostKVPage(k[:, i].copy(), v[:, i].copy(),
                       ks[:, i].copy() if ks is not None else None,
                       vs[:, i].copy() if vs is not None else None)
            for i in range(count))
    return out


@partial(jax.jit, donate_argnums=(0,))
def _scatter_pool(pool: jax.Array, idx: jax.Array,
                  data: jax.Array) -> jax.Array:
    """In-place (donated) page scatter: pool[:, idx] = data. Padding rows
    target page 0 (trash), so duplicate trash indices are harmless."""
    return pool.at[:, idx].set(data)


def restore_pages(kv: KVPages, pages: List[int],
                  host_pages: List[HostKVPage]) -> KVPages:
    """Scatter host page copies back into the device pool at freshly
    allocated page ids. Non-blocking: the scatters are dispatched async
    (donated pool, same stream), so a following prefill chains behind
    them on device and decode lanes staged through the dispatch-ahead
    pipeline never stall on the swap-in."""
    n = len(pages)
    if n == 0:
        return kv
    assert n == len(host_pages)
    k, v = kv.k, kv.v
    k_scale, v_scale = kv.k_scale, kv.v_scale
    at = 0
    for count, idx_np in _chunk_indices(pages):
        group = host_pages[at:at + count]
        at += count
        idx = jnp.asarray(idx_np)

        def _bulk(host_attr, pool):
            first = getattr(group[0], host_attr)
            data = np.zeros((first.shape[0], SWAP_CHUNK) + first.shape[1:],
                            first.dtype)
            for i, hp in enumerate(group):
                data[:, i] = getattr(hp, host_attr)
            return _scatter_pool(pool, idx, jnp.asarray(data))

        k = _bulk("k", k)
        v = _bulk("v", v)
        if kv.quantized:
            k_scale = _bulk("k_scale", k_scale)
            v_scale = _bulk("v_scale", v_scale)
    return KVPages(k=k, v=v, k_scale=k_scale, v_scale=v_scale)


class HostPagePool:
    """Capacity accounting for the host-RAM KV tier (the actual page
    bytes live in the prefix cache's host-tier table; this tracks how
    many pages they may occupy and the lifetime churn counters exported
    by telemetry). Host side only — no device state."""

    def __init__(self, capacity_pages: int):
        self.capacity = max(0, int(capacity_pages))
        self.used = 0
        self.bytes_resident = 0
        # Lifetime churn (read-through telemetry counters).
        self.offloaded_total = 0          # pages demoted device -> host
        self.restored_total = 0           # pages promoted host -> device
        self.evicted_total = 0            # second-tier (host LRU) drops
        self.imported_total = 0           # pages migrated in (fleet drain)
        self.offload_bytes_total = 0
        self.restore_bytes_total = 0
        self.import_bytes_total = 0
        # Cumulative host wall spent in device<->host swap batches
        # (engine-reported), per direction — the tier's total swap cost
        # without histogram math, surfaced in /healthz host_cache.
        self.swap_out_s_total = 0.0
        self.swap_in_s_total = 0.0

    def note_swap_wall(self, direction: str, seconds: float) -> None:
        """Accumulate one swap batch's host wall ("out" = demote
        device->host, "in" = promote host->device)."""
        if direction == "out":
            self.swap_out_s_total += seconds
        else:
            self.swap_in_s_total += seconds

    def can_hold(self, n: int = 1) -> bool:
        return self.used + n <= self.capacity

    @property
    def free(self) -> int:
        return self.capacity - self.used

    def note_offload(self, nbytes: int) -> None:
        self.used += 1
        self.bytes_resident += nbytes
        self.offloaded_total += 1
        self.offload_bytes_total += nbytes

    def note_restore(self, nbytes: int) -> None:
        self.used -= 1
        self.bytes_resident -= nbytes
        self.restored_total += 1
        self.restore_bytes_total += nbytes

    def note_evict(self, nbytes: int) -> None:
        self.used -= 1
        self.bytes_resident -= nbytes
        self.evicted_total += 1

    def note_import(self, nbytes: int) -> None:
        """A page migrated IN from another replica's drain export (fleet
        KV migration): occupies capacity like a demote, but counted
        separately — imports are warmth received, not local churn."""
        self.used += 1
        self.bytes_resident += nbytes
        self.imported_total += 1
        self.import_bytes_total += nbytes

    def readmit(self, nbytes: int) -> bool:
        """Undo one note_restore for an entry a failed swap-in returns:
        reverses the restore counters, then re-admits the entry IF the
        capacity an intervening demote may have claimed still allows it
        (False = caller must drop the entry; the RAM cap always wins)."""
        self.restored_total -= 1
        self.restore_bytes_total -= nbytes
        if not self.can_hold(1):
            self.evicted_total += 1
            return False
        self.used += 1
        self.bytes_resident += nbytes
        return True


# ---------------------------------------------------------------------------
# Migration wire format (README "Process fleet"): HostKVPage batches
# serialized for the fleet's drain-time KV migration channel. The layout
# is the host tier's stored layout verbatim — pool-dtype k/v blocks plus
# optional f32 scales — so any kv_quant mode round-trips bit-exactly and
# an imported page is indistinguishable from a locally demoted one.
# ---------------------------------------------------------------------------


def _np_dtype(name: str) -> np.dtype:
    """np.dtype by name, reaching into ml_dtypes for bfloat16 (numpy
    only knows it once ml_dtypes registered it — jax imports do that,
    but a standalone deserializer must not rely on import order)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def serialize_host_pages_parts(pages: List[HostKVPage]) -> List[bytes]:
    """The blob of :func:`serialize_host_pages` as its constituent
    buffers — ``[u32 header_len + json header, page buffers...]`` in
    stream order. The zero-copy plane writes these straight into an
    arena slab (RegionWriter.alloc_parts) so the payload is copied
    exactly once, into shared memory; the relay plane joins them into
    one frame blob. The embedded digest is chained across the parts —
    no intermediate body concatenation on either plane."""
    import json
    import struct

    if not pages:
        return [struct.pack(">I", 2) + b"{}"]
    first = pages[0]
    meta = {
        "n": len(pages),
        "k_dtype": np.dtype(first.k.dtype).name,
        "k_shape": list(first.k.shape),
        "scaled": first.k_scale is not None,
    }
    if meta["scaled"]:
        meta["scale_dtype"] = np.dtype(first.k_scale.dtype).name
        meta["scale_shape"] = list(first.k_scale.shape)
    parts = []
    for hp in pages:
        parts.append(np.ascontiguousarray(hp.k).tobytes())
        parts.append(np.ascontiguousarray(hp.v).tobytes())
        if meta["scaled"]:
            parts.append(np.ascontiguousarray(hp.k_scale).tobytes())
            parts.append(np.ascontiguousarray(hp.v_scale).tobytes())
    # Per-blob digest (README "Failure model"): CRC-32C over the raw
    # page bytes, carried inside the header so every adopt/import path
    # can verify end-to-end — across processes, sockets, and any future
    # storage hop — independent of the frame-level checksum.
    crc = 0
    for p in parts:
        crc = integrity.crc32c(p, crc)
    meta["crc32c"] = crc
    header = json.dumps(meta).encode()
    return [struct.pack(">I", len(header)) + header] + parts


def serialize_host_pages(pages: List[HostKVPage]) -> bytes:
    """Pack host page copies into one binary blob:
    ``[u32 header_len][json header][raw k|v|k_scale|v_scale per page]``.
    All pages in a batch come from one pool, so shapes/dtypes are
    batch-constant and live once in the header."""
    return b"".join(serialize_host_pages_parts(pages))


def deserialize_host_pages(blob: bytes,
                           copy: bool = True) -> List[HostKVPage]:
    """Inverse of :func:`serialize_host_pages`. Each returned page owns
    its bytes (copies out of the blob), so the caller may drop the blob
    and the pages live independently in the host tier.

    ``copy=False`` returns read-only page views over the blob instead
    (each array's ``.base`` keeps the blob alive) — the one-shot adopt
    path hands them straight to the device restore and never needs an
    owning copy, which at multi-MiB handoff blobs is the difference
    between one memcpy of the payload and two."""
    import json
    import struct

    if len(blob) < 4:
        raise integrity.KVIntegrityError(
            f"KV blob truncated ({len(blob)} bytes)")
    (hlen,) = struct.unpack(">I", blob[:4])
    if 4 + hlen > len(blob):
        raise integrity.KVIntegrityError(
            f"KV blob header overruns blob ({hlen} > {len(blob) - 4})")
    try:
        meta = json.loads(blob[4:4 + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise integrity.KVIntegrityError(
            f"KV blob header unparseable: {e}") from None
    if not meta:
        return []
    want = meta.get("crc32c")
    if want is not None:
        got = integrity.crc32c(blob[4 + hlen:])
        if got != want:
            raise integrity.KVIntegrityError(
                "KV blob digest mismatch "
                f"(want 0x{want:08x} got 0x{got:08x})")
    k_dtype = _np_dtype(meta["k_dtype"])
    k_shape = tuple(meta["k_shape"])
    k_size = int(np.prod(k_shape)) * k_dtype.itemsize
    scaled = meta.get("scaled", False)
    if scaled:
        s_dtype = _np_dtype(meta["scale_dtype"])
        s_shape = tuple(meta["scale_shape"])
        s_size = int(np.prod(s_shape)) * s_dtype.itemsize
    at = 4 + hlen
    out: List[HostKVPage] = []

    def take(n, dtype, shape):
        nonlocal at
        arr = np.frombuffer(blob, dtype=dtype, count=int(np.prod(shape)),
                            offset=at).reshape(shape)
        if copy:
            arr = arr.copy()
        at += n
        return arr

    for _ in range(meta["n"]):
        k = take(k_size, k_dtype, k_shape)
        v = take(k_size, k_dtype, k_shape)
        ks = vs = None
        if scaled:
            ks = take(s_size, s_dtype, s_shape)
            vs = take(s_size, s_dtype, s_shape)
        out.append(HostKVPage(k, v, ks, vs))
    return out


def verify_host_pages_blob(blob: bytes) -> Optional[str]:
    """Structural + digest check WITHOUT materializing pages — the
    router's cheap gate before forwarding a handoff/migrate blob to a
    destination worker. Returns None when sound, else the rejection
    reason. A pre-digest blob (no ``crc32c`` in its header) passes the
    structure check only."""
    import json
    import struct

    if not blob:
        return None
    if len(blob) < 4:
        return f"KV blob truncated ({len(blob)} bytes)"
    (hlen,) = struct.unpack(">I", blob[:4])
    if 4 + hlen > len(blob):
        return f"KV blob header overruns blob ({hlen} > {len(blob) - 4})"
    try:
        meta = json.loads(blob[4:4 + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        return f"KV blob header unparseable: {e}"
    want = meta.get("crc32c") if meta else None
    if want is not None:
        got = integrity.crc32c(blob[4 + hlen:])
        if got != want:
            return ("KV blob digest mismatch "
                    f"(want 0x{want:08x} got 0x{got:08x})")
    return None
