"""tpu_inference — a TPU-native distributed LLM inference framework.

This package is the in-tree server half that the reference repo
(`anthonychiuhy/distributed-llm-inference`, see SURVEY.md) delegates to an
external Ollama endpoint (reference: traffic_generator/main.py:306). Everything
here is designed TPU-first:

- models/   pure-function JAX model definitions (Llama, Mixtral, GPT-2) over
            parameter pytrees; bfloat16 matmuls on the MXU, f32 accumulation.
- kernels/  Pallas TPU kernels (paged attention) + dense jnp reference paths.
- engine/   paged KV cache (HBM block pool), continuous-batching scheduler,
            prefill/decode compiled as separate bucketed XLA graphs, sampling,
            speculative decoding.
- parallel/ jax.sharding.Mesh construction, TP/EP NamedSharding specs, ring
            attention (shard_map + ppermute) for sequence parallelism.
- server/   aiohttp HTTP server speaking the Ollama /api/generate NDJSON
            protocol (wire contract: SURVEY.md §2c) so the benchmark harness
            drives a TPU slice unchanged.
"""

__version__ = "0.1.0"

from tpu_inference.config import (  # noqa: F401
    EngineConfig,
    ModelConfig,
    ParallelConfig,
    ServerConfig,
)
