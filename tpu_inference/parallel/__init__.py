"""Parallelism: device mesh + NamedSharding specs (TP/EP/DP/SP).

The reference has no distributed backend — its only cross-process hop is a
client-side HTTP POST (reference: traffic_generator/main.py:257); server-side
parallelism belonged to the external Ollama/vLLM deployment (SURVEY.md §2b).
Here parallelism is first-class and TPU-native: a `jax.sharding.Mesh` over
the slice, `NamedSharding` annotations on weights and KV pages, and XLA
emitting the all-reduce/all-to-all collectives over ICI.
"""

from tpu_inference.parallel.mesh import build_mesh
from tpu_inference.parallel.shardings import (
    kv_sharding,
    param_shardings,
    param_specs,
    shard_params,
    validate_tp,
)

__all__ = [
    "build_mesh",
    "param_specs",
    "param_shardings",
    "shard_params",
    "kv_sharding",
    "validate_tp",
]
