"""Device mesh construction.

Axes (ParallelConfig): ``dp`` replicates the model for throughput, ``tp``
shards attention heads / FFN hidden / experts with all-reduce (or all-to-all
for MoE) over ICI, ``sp`` shards the sequence dim for ring attention.
Any axis of size 1 is a no-op; the specs in shardings.py reference axis
*names*, so the same annotations work at every mesh shape.
"""

from __future__ import annotations

from typing import Optional, Sequence  # noqa: F401 (Optional in annotations)

import jax
import numpy as np
from jax.sharding import Mesh

from tpu_inference.config import ParallelConfig

AXES = ("dp", "tp", "sp")


def build_mesh(pcfg: ParallelConfig,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Mesh over the first dp*tp*sp devices, axes ('dp', 'tp', 'sp').

    On a real slice, `jax.devices()` order follows the physical torus, so
    contiguous tp groups ride ICI neighbors; dp is the outermost (slowest)
    axis, which is the standard layout for replica-over-slice serving.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = pcfg.n_devices
    if len(devices) < n:
        raise ValueError(
            f"mesh needs {n} devices (dp={pcfg.dp} tp={pcfg.tp} "
            f"sp={pcfg.sp}); only {len(devices)} visible")
    # tp is the chattiest axis (per-layer all-reduce), so make tp groups
    # contiguous in device order (= ICI neighbors on a torus): lay devices
    # out as (dp, sp, tp) then swap to the (dp, tp, sp) axis order.
    arr = np.asarray(devices[:n]).reshape(pcfg.dp, pcfg.sp, pcfg.tp)
    return Mesh(arr.transpose(0, 2, 1), AXES)
