"""Multi-host (multi-slice) distributed runtime: ICI within a slice,
DCN across slices.

The reference's only "distributed backend" is client-side HTTP to an
external server (SURVEY.md §2b: no NCCL/MPI/Gloo in-repo); the TPU-native
equivalent is XLA collectives compiled over the hardware fabrics. This
module owns the process-level setup those collectives need:

- ``initialize()`` wraps ``jax.distributed.initialize`` — the JAX runtime
  handshake that makes every host see the global device set (the moral
  equivalent of NCCL rendezvous, but handled by the runtime; no
  user-space transport code to write).
- ``build_hybrid_mesh()`` lays out a mesh whose *inner* axes (tp, sp)
  stay inside a slice (ICI: ~100s of GB/s, per-layer all-reduce lives
  here) and whose *outer* axis (dp) spans slices over DCN (~10s of
  GB/s — only replica-parallel traffic, which is zero in steady-state
  serving). This is the scaling-book recipe: chatty axes innermost.

Failure model (SURVEY.md §5): JAX's multi-controller runtime fails at
initialization if any host is absent, and a host loss kills the job —
recovery is restart + reload weights (models/weights.py Orbax/safetensors
load streams shards directly to their owning hosts). The serving layer's
per-request timeouts and OOM-safe admission handle request-level faults;
process-level elasticity is restart-based, as is standard for TPU pods.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from tpu_inference.config import ParallelConfig
from tpu_inference.parallel.mesh import AXES


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime. No-ops on a single process.

    On TPU pods the three arguments are discovered from the metadata
    server automatically; pass them explicitly for CPU/GPU multi-process
    or tests. Safe to call more than once.
    """
    # jax.distributed.is_initialized arrived after 0.4.x; on older jax
    # probe the runtime's own already-initialized state (the same fields
    # whose presence makes a second initialize() raise).
    initialized = getattr(jax.distributed, "is_initialized", None)
    if initialized is not None:
        if initialized():
            return
    else:
        from jax._src.distributed import global_state
        if (global_state.client is not None
                or global_state.coordinator_address is not None):
            return
    if (coordinator_address is None
            and os.environ.get("JAX_COORDINATOR_ADDRESS") is None
            and num_processes is None and jax.process_count() == 1):
        return                      # single-process: nothing to set up
    # 0.4.x jaxlib ships the CPU backend with collectives off ("none"),
    # so a multi-process CPU psum dies with "Multiprocess computations
    # aren't implemented"; select gloo when the knob exists and nothing
    # chose otherwise. CPU-only: TPU/GPU collectives are unaffected.
    try:
        if jax.config.read("jax_cpu_collectives_implementation") in (
                None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except AttributeError:
        pass
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def build_hybrid_mesh(pcfg: ParallelConfig,
                      devices: Optional[Sequence[jax.Device]] = None,
                      num_slices: Optional[int] = None) -> Mesh:
    """Mesh over a multi-slice system: dp outermost over DCN, tp/sp
    contiguous within each slice over ICI.

    ``num_slices`` defaults to the device set's slice count (via
    ``device.slice_index`` on multi-slice TPU; 1 elsewhere). Requires
    dp % num_slices == 0 — replicas never straddle a DCN boundary, so
    the per-layer tp all-reduces and sp ppermutes stay on ICI.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = pcfg.n_devices
    if len(devices) < n:
        raise ValueError(f"mesh needs {n} devices; {len(devices)} visible")
    devices = devices[:n]

    if num_slices is None:
        slice_ids = {getattr(d, "slice_index", 0) for d in devices}
        num_slices = len(slice_ids)
    if num_slices > 1:
        if pcfg.dp % num_slices != 0:
            raise ValueError(
                f"dp={pcfg.dp} must be a multiple of num_slices="
                f"{num_slices}: a replica may not straddle DCN")
        per_slice = n // num_slices
        by_slice = sorted(devices,
                          key=lambda d: (getattr(d, "slice_index", 0),
                                         d.id))
        # [slice, within-slice] -> (dp, tp, sp) with dp split as
        # (slice, replica-within-slice) and tp innermost (ICI neighbors).
        arr = np.asarray(by_slice).reshape(
            num_slices, pcfg.dp // num_slices, pcfg.sp, pcfg.tp)
        arr = arr.reshape(pcfg.dp, pcfg.sp, pcfg.tp)
    else:
        arr = np.asarray(devices).reshape(pcfg.dp, pcfg.sp, pcfg.tp)
    return Mesh(arr.transpose(0, 2, 1), AXES)


def replica_meshes(mesh: Mesh) -> list:
    """Per-dp-row (tp, sp) submeshes this process participates in.

    Multi-host DP serving runs one engine per dp row: the row never
    straddles DCN (``build_hybrid_mesh`` guarantees it), so its tp/sp
    collectives stay on ICI. Each host builds engines only for the rows
    it holds devices of; hosts inside a multi-host slice share their
    row's mesh and run that engine as multi-controller SPMD. Returns
    ``[(row_index, submesh), ...]`` where the submesh keeps the dp axis
    at size 1 so the production sharding specs apply unchanged.
    """
    local = set(jax.local_devices())
    out = []
    for i in range(mesh.devices.shape[0]):
        row = mesh.devices[i:i + 1]
        if any(d in local for d in row.flat):
            out.append((i, Mesh(row, mesh.axis_names)))
    return out


def process_local_engine_role(mesh: Mesh) -> dict:
    """What this host contributes to the mesh (serving-topology info for
    logs/metrics): local device count and whether it hosts mesh row 0
    (the row whose host typically runs the HTTP frontend)."""
    local = set(jax.local_devices())
    flat = list(mesh.devices.flat)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices_in_mesh": sum(1 for d in flat if d in local),
        "hosts_frontend": flat[0] in local,
    }
