"""Inference pipeline parallelism: layer stages over a ``pp`` mesh axis.

The last of the survey's named parallelism strategies (SURVEY.md §2b:
DP/TP/PP/SP/EP): the stacked-layer parameter pytree shards along its
LAYER axis, each stage owns ``n_layers / pp`` consecutive blocks, and
activations flow stage-to-stage with ``jax.lax.ppermute`` in a
GPipe-style micro-batch schedule — the TPU-idiomatic shape of pipeline
parallelism (collective-permute over ICI intra-slice, DCN inter-slice;
XLA overlaps the permute with the next micro-batch's compute). PP is
the inter-slice scaling tier in the scaling-book recipe: TP saturates
ICI inside a slice, PP spans slices where all-reduce would be
DCN-bound, because its only cross-stage traffic is one activation
tensor per micro-batch.

Scope: full-sequence forward (prefill-shaped). This demonstrates the
sharding + schedule against the unsharded oracle; the serving engine's
production scaling axes remain (dp, tp, sp) — for paged decode the
natural composition shards the KV pool's layer dim with the stages
(each stage already holds only its layers' pages), which this module's
layer-slab layout is designed to line up with.

SPMD notes: every stage executes every step's full program (embedding,
its local blocks, final norm + unembed) with non-owned results masked
to zero and combined by one ``psum`` at the end — the standard
"compute-and-mask" pipelining formulation that keeps the program
identical across devices (no data-dependent control flow for XLA).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_inference.compat import shard_map
from tpu_inference.config import ModelConfig
from tpu_inference.models import llama
from tpu_inference.models.common import make_dense_attn, rms_norm


def stage_specs(params: dict) -> dict:
    """Partition specs: blocks shard their leading (layer) axis over
    ``pp``; embeddings / norms / head replicate."""
    return {
        name: (jax.tree.map(lambda _: P("pp"), sub)
               if name == "blocks" else jax.tree.map(lambda _: P(), sub))
        for name, sub in params.items()
    }


def pp_forward(params: dict, cfg: ModelConfig, tokens: jax.Array,
               positions: jax.Array, mesh: Mesh,
               n_micro: int | None = None) -> jax.Array:
    """Pipeline-parallel full-sequence logits, == llama.forward output.

    tokens/positions: [B, S]; B must divide into ``n_micro``
    micro-batches (default: the pp degree, the smallest count that
    fills the pipe). Total steps = n_micro + pp - 1.
    """
    pp = mesh.shape["pp"]
    if cfg.family != "llama":
        raise ValueError(
            f"pp_forward supports the llama family (got {cfg.family!r}); "
            "MoE layer stacks ([L, E, ...] experts) need EP-aware stages")
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers {cfg.n_layers} % pp {pp} != 0")
    b = tokens.shape[0]
    if n_micro is None:
        n_micro = pp
    if n_micro < 1 or b % n_micro:
        raise ValueError(f"batch {b} % n_micro {n_micro} != 0")
    l_local = cfg.n_layers // pp
    mb = b // n_micro
    attn = make_dense_attn(cfg.sliding_window)

    def stage_fn(params, tokens, positions):
        s = jax.lax.axis_index("pp")
        blocks = params["blocks"]          # local slab [l_local, ...]
        t_micro = tokens.reshape(n_micro, mb, -1)
        p_micro = positions.reshape(n_micro, mb, -1)

        def run_local(x, pos):
            ids = s * l_local + jnp.arange(l_local)

            def body(carry, scanned):
                layer_idx, lp = scanned
                x, _ = llama.decoder_block(cfg, layer_idx, lp, carry,
                                           pos, None, attn)
                return x, None

            x, _ = jax.lax.scan(body, x, (ids, blocks))
            return x

        seq = tokens.shape[-1]
        carry = jnp.zeros((mb, seq, cfg.d_model), cfg.dtype)
        out = jnp.zeros((n_micro, mb, seq, cfg.d_model), cfg.dtype)
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        for t in range(n_micro + pp - 1):
            recv = jax.lax.ppermute(carry, "pp", perm)
            # Stage 0 injects micro-batch t (static index; clamped after
            # the last injection — those steps' stage-0 output is dead).
            inject = llama.embed_tokens(params, cfg,
                                        t_micro[min(t, n_micro - 1)])
            x_in = jnp.where(s == 0, inject, recv)
            # Stage s works on micro-batch t - s (traced index, clipped;
            # out-of-range steps compute masked garbage — SPMD bubbles).
            mb_idx = jnp.clip(t - s, 0, n_micro - 1)
            pos = jax.lax.dynamic_index_in_dim(p_micro, mb_idx, 0,
                                               keepdims=False)
            carry = run_local(x_in, pos)
            # The LAST stage finished micro-batch t - (pp - 1).
            done = t - (pp - 1)
            if done >= 0:
                h = rms_norm(carry, params["final_norm"],
                             cfg.norm_eps, cfg.norm_offset)
                out = out.at[done].set(jnp.where(s == pp - 1, h, 0.0))
        # Only the last stage wrote non-zero hidden states; the combine
        # moves d_model-sized data (NOT logits — unembed happens once,
        # replicated, outside the pipe, so cross-stage traffic stays
        # activation-sized as the module docstring promises).
        return jax.lax.psum(out, "pp").reshape(b, seq, cfg.d_model)

    fn = shard_map(stage_fn, mesh=mesh,
                   in_specs=(stage_specs(params), P(), P()),
                   out_specs=P(), check_vma=False)
    hidden = fn(params, tokens, positions)
    return llama.unembed(params, cfg, hidden)
