"""NamedSharding specs for model params and the paged KV pool.

Megatron-style tensor parallelism expressed declaratively: annotate the
weights, let GSPMD place the collectives.

- QKV projections shard the *head* (output) dim; the attention output
  projection shards its *input* dim — one all-reduce per attention block.
- SwiGLU gate/up shard the hidden (f) dim; down shards its input — one
  all-reduce per FFN.
- Mixtral experts shard the *expert* dim over the same ``tp`` axis
  (expert parallelism): the dispatch/combine einsums in
  models/mixtral.py:moe_ffn become all-to-alls over ICI.
- Embedding and lm_head shard the vocab dim (vocab-parallel logits).
- KV pages shard the kv-head dim, which keeps the paged pool's per-chip
  slice aligned with the head-sharded K/V projections — no resharding
  between projection, cache write, and attention.

The reference has no analogue of any of this (SURVEY.md §2b: parallelism was
a property of its external server); the sharding design follows the
jax-ml scaling-book recipe: pick a mesh, annotate, let XLA insert
collectives.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_inference.config import ModelConfig
from tpu_inference.models.quant import QuantizedArray


def _llama_specs(cfg: ModelConfig) -> dict:
    specs = {
        "embed": P("tp", None),
        "blocks": {
            "attn_norm": P(),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "ffn_norm": P(),
            "w_gate": P(None, None, "tp"),
            "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
        },
        "final_norm": P(),
    }
    if cfg.qkv_bias:
        # Qwen2 q/k/v biases follow their projection's head (output) dim.
        specs["blocks"]["bq"] = P(None, "tp")
        specs["blocks"]["bk"] = P(None, "tp")
        specs["blocks"]["bv"] = P(None, "tp")
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def _mixtral_specs(cfg: ModelConfig) -> dict:
    return {
        "embed": P("tp", None),
        "blocks": {
            "attn_norm": P(),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "ffn_norm": P(),
            "w_router": P(),
            # Expert parallelism: experts distributed over the tp axis.
            "w_gate": P(None, "tp", None, None),
            "w_up": P(None, "tp", None, None),
            "w_down": P(None, "tp", None, None),
        },
        "final_norm": P(),
        "lm_head": P(None, "tp"),
    }


def _gpt2_specs(cfg: ModelConfig) -> dict:
    # w_qkv packs [q|k|v] along the output dim (3*d_model wide). A contiguous
    # tp shard of the packed dim crosses the q/k/v boundaries unless tp is a
    # multiple of 3, so GSPMD reshards around the split in gpt2._block —
    # correct but costs extra collectives. gpt2 is the CPU-stub/parity model
    # (BASELINE.json config 0), never the TP-serving flagship, so the simple
    # packed sharding is kept.
    return {
        "embed": P("tp", None),
        "pos_embed": P(),
        "blocks": {
            "ln1_w": P(), "ln1_b": P(),
            "w_qkv": P(None, None, "tp"),
            "b_qkv": P(None, "tp"),
            "w_proj": P(None, "tp", None),
            "b_proj": P(),
            "ln2_w": P(), "ln2_b": P(),
            "w_fc": P(None, None, "tp"),
            "b_fc": P(None, "tp"),
            "w_out": P(None, "tp", None),
            "b_out": P(),
        },
        "ln_f_w": P(), "ln_f_b": P(),
    }


def validate_tp(cfg: ModelConfig, tp: int) -> None:
    """Fail fast (with a named dimension) when tp can't evenly shard the
    model, instead of an opaque GSPMD error deep inside engine init."""
    checks = [
        ("n_heads", cfg.n_heads),
        ("n_kv_heads", cfg.n_kv_heads),
        ("d_ff", cfg.d_ff),
        ("vocab_size", cfg.vocab_size),
    ]
    if cfg.n_experts:
        checks.append(("n_experts", cfg.n_experts))
    for name, dim in checks:
        if dim % tp != 0:
            raise ValueError(
                f"tp={tp} does not divide {name}={dim} for model "
                f"{cfg.name!r}; choose tp from the divisors of {name}")


def param_specs(cfg: ModelConfig) -> dict:
    """PartitionSpec pytree with the same structure as the family's params."""
    fam = {"llama": _llama_specs, "mixtral": _mixtral_specs,
           "gpt2": _gpt2_specs}[cfg.family]
    return fam(cfg)


def _scale_spec(spec: P, leaf) -> P:
    """Spec for a QuantizedArray's scale: same as the weight's. The
    contraction dim is size 1 in an int8 scale (unshard it — replicated)
    but holds G groups in an int4 scale, where it must follow the
    weight's contraction-dim sharding so each chip keeps the scales for
    its own weight shard."""
    ndim = leaf.q.ndim
    entries = list(spec) + [None] * (ndim - len(spec))
    if leaf.scale.shape[-2] == 1:
        entries[ndim - 2] = None
    return P(*entries)


def param_shardings(cfg: ModelConfig, mesh: Mesh,
                    params: Optional[dict] = None) -> Any:
    """NamedSharding pytree for the family's params.

    Without ``params`` the tree mirrors ``param_specs`` (plain-array
    leaves). With ``params`` (possibly holding int8 ``QuantizedArray``
    leaves, models/quant.py) the result mirrors the actual params tree:
    the quantized payload takes the weight's spec, the scale the same
    spec with its reduced contraction dim unsharded.
    """
    validate_tp(cfg, mesh.shape.get("tp", 1))
    specs = param_specs(cfg)
    if params is None:
        return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))

    def mk(spec: P, leaf: Any):
        if isinstance(leaf, QuantizedArray):
            sspec = _scale_spec(spec, leaf)
            ngrp = leaf.scale.shape[-2]
            axis = sspec[leaf.q.ndim - 2] if len(sspec) >= leaf.q.ndim - 1 \
                else None
            if ngrp > 1 and axis is not None:
                n = int(mesh.shape.get(axis, 1))
                if ngrp % n:
                    # Fail here with a named leaf, not deep inside GSPMD
                    # placement (same job validate_tp does for head/ff
                    # divisibility — the grouped constraint depends on
                    # the quantized leaf, so it's checked at shard time).
                    raise ValueError(
                        f"int4 grouped scales: {ngrp} groups on a "
                        f"contraction dim sharded over {axis}={n} don't "
                        f"divide evenly; use a tp that divides the group "
                        f"count (dim/{2 * leaf.q.shape[-2] // ngrp}, "
                        "codes nibble-packed) or --quant int8")
            return QuantizedArray(
                q=NamedSharding(mesh, spec),
                scale=NamedSharding(mesh, sspec))
        return NamedSharding(mesh, spec)

    return jax.tree.map(mk, specs, params,
                        is_leaf=lambda x: isinstance(x, P))


def shard_params(params: dict, cfg: ModelConfig, mesh: Mesh) -> dict:
    """Place a params pytree onto the mesh per `param_specs`."""
    return jax.tree.map(jax.device_put, params,
                        param_shardings(cfg, mesh, params))


def kv_spec() -> P:
    """KV pool [L, pages, page_size, Hkv, head_dim]: shard kv heads on tp."""
    return P(None, None, None, "tp", None)


def kv_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, kv_spec())


def kv_scale_sharding(mesh: Mesh) -> NamedSharding:
    """Scale pool [L, pages, page_size, Hkv] (int8 KV): heads on tp,
    aligned with the code pool so in-kernel dequant stays chip-local."""
    return NamedSharding(mesh, P(None, None, None, "tp"))
