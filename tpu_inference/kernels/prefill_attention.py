"""Pallas paged prefill attention (flash-style online softmax over KV pages).

Prefill previously gathered every page of a sequence into a contiguous
[B, MP*page, H, D] buffer and materialized dense [B, H, S, S_kv] scores
(models/common.py dense path) — O(S^2) HBM traffic and VMEM pressure that
walls at long context. This kernel streams each KV page HBM->VMEM once per
query block and folds it into running (m, l, acc) online-softmax state:
memory is O(S·page), the gather never materializes, and both the prompt's
own KV and any cached prefix are read from the same paged pool (the engine
writes the current chunk's KV before attending, so pool pages are the
single source of truth).

Layout mirrors the decode kernel (kernels/paged_attention.py): grid
(B, S/bq, MP) with the page index innermost; each instance carries a
whole query block for every kv head — q viewed [Hkv, bq*R, D] so each
page contributes one head-batched [bq*R, pg] MXU contraction per head.
Causality and cache validity fuse into one mask (k_pos <= q_pos and
k_pos < kv_len, plus k_pos > q_pos - sliding_window for SWA models);
pages entirely in the causal future or past kv_len are skipped via
@pl.when. With a sliding window the page axis is RELATIVE per query
block (scalar-prefetch index maps offset from the block's window
start), so each block touches O(block_q + window) pages, not O(S).

Reference has no analogue (client-only, SURVEY.md §0); this is the
prefill half of the vLLM-style PagedAttention pair, re-designed for
Mosaic/TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


# Shared with the decode kernel — one f32-consuming unpack wrapper over
# the single packing contract in engine/kv_cache.py.
from tpu_inference.kernels.paged_attention import _unpack_int4  # noqa: E402


def _prefill_kernel(block_tables_ref, kv_len_ref, q_offset_ref, q_ref, k_ref,
                    v_ref, *rest, page_size: int, block_q: int, n_rep: int,
                    scale: float, quantized: bool, packed: bool = False,
                    sliding_window: int = 0):
    if quantized:
        ks_ref, vs_ref, out_ref, m_ref, l_ref, acc_ref = rest
    else:
        out_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    qb = pl.program_id(1)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kv_len = kv_len_ref[b]
    q_off = q_offset_ref[b]
    q_lo = q_off + qb * block_q
    if sliding_window:
        # Page index is RELATIVE to the first page this query block's
        # window can reach (BlockSpec index maps apply the same offset):
        # pages touched per block are O(block_q + window), not O(S).
        win_first = jnp.maximum(q_lo - sliding_window + 1, 0)
        page_start = (win_first // page_size + p) * page_size
    else:
        page_start = p * page_size
    # Highest query position in this block; later pages are all-masked.
    q_hi = q_lo + block_q - 1

    @pl.when((page_start < kv_len) & (page_start <= q_hi))
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)               # [Hkv, bq*R, D]
        # Mosaic wants batched dot dims in matching positions: kv-head
        # leading on both sides.
        if packed:
            k = _unpack_int4(k_ref[0]).transpose(1, 0, 2)    # [Hkv, pg, D]
            v = _unpack_int4(v_ref[0]).transpose(1, 0, 2)
        else:
            k = k_ref[0].astype(jnp.float32).transpose(1, 0, 2)  # [Hkv,pg,D]
            v = v_ref[0].astype(jnp.float32).transpose(1, 0, 2)
        if quantized:
            k = k * ks_ref[0].astype(jnp.float32).transpose(1, 0)[:, :, None]
            v = v * vs_ref[0].astype(jnp.float32).transpose(1, 0)[:, :, None]

        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [Hkv, bq*R, pg]
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) // n_rep
        q_pos = q_lo + row
        k_pos = page_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        valid = (k_pos <= q_pos) & (k_pos < kv_len)
        if sliding_window:
            valid &= k_pos > q_pos - sliding_window
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[:]                                 # [Hkv, bq*R, 1]
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=2, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        pr = jnp.exp(s - m_new)
        # Fully-masked rows: exp(NEG_INF - NEG_INF) = 1; zero them.
        pr = jnp.where(s > NEG_INF / 2, pr, 0.0)
        o = jax.lax.dot_general(
            pr, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [Hkv, bq*R, D]
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(pr, axis=2, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + o

    @pl.when(p == num_pages - 1)
    def _flush():
        denom = jnp.maximum(l_ref[:], 1e-20)
        out_ref[0, 0] = (acc_ref[:] / denom).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_q", "interpret",
                                             "sliding_window"))
def paged_prefill_attention(q: jax.Array, k_pages: jax.Array,
                            v_pages: jax.Array, block_tables: jax.Array,
                            kv_len: jax.Array, q_offset: jax.Array,
                            k_scale: jax.Array | None = None,
                            v_scale: jax.Array | None = None,
                            block_q: int = 128,
                            interpret: bool | None = None,
                            sliding_window: int = 0) -> jax.Array:
    """Prefill attention over the paged KV pool.

    q:            [B, S, Hq, D]  (the current chunk's queries)
    k/v_pages:    [P, page_size, Hkv, D]  (one layer's pool; the chunk's
                  own KV must already be written)
    block_tables: [B, MP] int32 physical page ids (0 = trash page)
    kv_len:       [B] total valid tokens (cached prefix + this chunk)
    q_offset:     [B] absolute position of q[:, 0] (= prefix length)
    k/v_scale:    [P, page_size, Hkv] f32 when the pool is quantized —
                  int8 codes or uint8 nibble-packed int4 (trailing dim
                  D/2); dequant happens in VMEM per page.
    Returns [B, S, Hq, D] in q.dtype.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    # uint8 pool = nibble-packed int4 codes (engine/kv_cache.py); the
    # pool's trailing dim is D/2 bytes and the kernel unpacks in VMEM.
    packed = k_pages.dtype == jnp.uint8
    b, s, hq, d = q.shape
    _, page_size, hkv, d_pool = k_pages.shape
    n_rep = hq // hkv
    mp = block_tables.shape[1]
    scale = 1.0 / (d ** 0.5)
    # Largest divisor of s not exceeding block_q (buckets are usually
    # powers of two, but any length must work — e.g. a 192 bucket).
    bq = next(b for b in range(min(block_q, s), 0, -1) if s % b == 0)
    n_qb = s // bq

    # [B, S, Hq, D] -> [B, QB, Hkv, bq*R, D]: GQA groups contiguous so a
    # row's kv head is row // n_rep within its block.
    q_g = (q.reshape(b, n_qb, bq, hkv, n_rep, d)
           .transpose(0, 1, 3, 2, 4, 5)
           .reshape(b, n_qb, hkv, bq * n_rep, d))

    if sliding_window:
        # A query block's window reaches back window-1 positions from
        # its first query and forward to its last: bq + window - 1
        # positions -> at most that many pages + 1 for misalignment.
        n_page_axis = min(mp, -(-(bq + sliding_window - 1) // page_size) + 1)

        def page_idx(i, qb, p, bt, kl, qo):
            first = jnp.maximum(qo[i] + qb * bq - sliding_window + 1, 0)
            # Clamp: relative pages past the block table are compute-
            # masked in the kernel; the DMA just needs a legal id.
            return bt[i, jnp.minimum(first // page_size + p, mp - 1)]
    else:
        n_page_axis = mp

        def page_idx(i, qb, p, bt, kl, qo):
            return bt[i, p]

    page_spec = pl.BlockSpec((1, page_size, hkv, d_pool),
                             lambda i, qb, p, bt, kl, qo: (
                                 page_idx(i, qb, p, bt, kl, qo), 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, 1, hkv, bq * n_rep, d),
                     lambda i, qb, p, bt, kl, qo: (i, qb, 0, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [q_g, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, page_size, hkv),
            lambda i, qb, p, bt, kl, qo: (
                page_idx(i, qb, p, bt, kl, qo), 0, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,        # block_tables, kv_len, q_offset
        grid=(b, n_qb, n_page_axis),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, hkv, bq * n_rep, d),
            lambda i, qb, p, bt, kl, qo: (i, qb, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, bq * n_rep, 1), jnp.float32),   # running max
            pltpu.VMEM((hkv, bq * n_rep, 1), jnp.float32),   # running sum
            pltpu.VMEM((hkv, bq * n_rep, d), jnp.float32),   # running out
        ],
    )
    out = pl.pallas_call(
        functools.partial(_prefill_kernel, page_size=page_size, block_q=bq,
                          n_rep=n_rep, scale=scale, quantized=quantized,
                          packed=packed, sliding_window=sliding_window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_qb, hkv, bq * n_rep, d),
                                       q.dtype),
        interpret=interpret,
    )(block_tables, kv_len, q_offset, *operands)
    return (out.reshape(b, n_qb, hkv, bq, n_rep, d)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(b, s, hq, d))
