"""Ring attention: causal attention with the sequence sharded over a mesh
axis (sequence/context parallelism for long-context prefill).

Each device holds one contiguous sequence shard of Q, K, V. K/V shards
rotate around the ring (``lax.ppermute`` — XLA lowers it to ICI
neighbor transfers), while every device folds each visiting K/V chunk
into flash-style online-softmax state for its local Q. After
``axis_size`` steps every Q row has attended to every K/V row at or
before it; peak memory per chip stays O(S/n), enabling contexts n× the
single-chip limit.

This is the TPU-native replacement for the reference's (absent)
long-context support: SURVEY.md §5 notes the reference clamps prompts to
1024 tokens client-side (traffic_generator/main.py:92-93,163-165) and
delegates all attention to its external server. Design follows the
ring-attention / blockwise-parallel-transformer pattern (PAPERS.md) with
XLA collectives instead of hand-rolled RDMA.

Communication note: ppermute sends ride ICI when the ``sp`` axis maps to
physically adjacent chips (parallel/mesh.py lays tp innermost, then sp);
compute per step is O((S/n)^2) while each transfer is O(S/n), so XLA can
overlap the next chunk's transfer with the current chunk's attention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_inference import compat
from tpu_inference.compat import shard_map

NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, k_pos, scale, n_rep, sliding_window=0):
    """One (local Q) x (visiting KV chunk) block: masked scores + partial
    softmax stats. q: [B,Sq,Hq,D] f32; k/v: [B,Sk,Hkv,D] raw dtype (GQA
    expansion + f32 upcast happen here, per block, so the ring rotates the
    small raw shards). ``sliding_window`` > 0 additionally masks keys more
    than window-1 positions behind the query (matches
    models.common.dense_causal_attention). Returns (m [B,H,Sq],
    l [B,H,Sq], o [B,Sq,H,D])."""
    if n_rep != 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    k = k.astype(jnp.float32)
    v = v.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
    if sliding_window:
        mask &= (k_pos[None, None, None, :]
                 > q_pos[None, None, :, None] - sliding_window)
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                              # [B, H, Sq]
    # exp(NEG_INF - NEG_INF) = 1 on fully-masked rows; zero them via mask.
    pr = jnp.exp(s - m[..., None]) * mask
    l = jnp.sum(pr, axis=-1)                             # [B, H, Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", pr, v)
    return m, l, o


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str = "sp",
                         sliding_window: int = 0) -> jax.Array:
    """Per-shard body; call under shard_map with the sequence dim sharded
    over ``axis_name``. q: [B, S_loc, Hq, D]; k/v: [B, S_loc, Hkv, D]
    (GQA expanded internally). ``sliding_window`` > 0 applies the SWA
    mask (each query sees itself + the window-1 tokens before it); fully
    behind-window chunks skip their einsums just like fully-future ones.
    Returns [B, S_loc, Hq, D] in q.dtype."""
    n = compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, hq, d = q.shape
    hkv = k.shape[2]
    n_rep = hq // hkv
    scale = 1.0 / (d ** 0.5)

    qf = q.astype(jnp.float32)
    local_pos = jnp.arange(s_loc, dtype=jnp.int32)
    q_pos = idx * s_loc + local_pos

    m = jnp.full((b, hq, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, hq, s_loc), jnp.float32)
    acc = jnp.zeros((b, s_loc, hq, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    k_cur, v_cur = k, v          # raw dtype, Hkv heads: minimal ring bytes
    for step in range(n):
        src = (idx - step) % n          # chunk id this device now holds
        k_pos = src * s_loc + local_pos

        def attend(ops):
            kc, vc = ops
            return _block_attend(qf, kc, vc, q_pos, k_pos, scale, n_rep,
                                 sliding_window)

        def skip(ops):
            # Mark the constants as device-varying so both cond branches
            # agree under shard_map's varying-axis typing (compat.pvary:
            # pcast on current jax, pvary on older, no-op on 0.4.x).
            vals = (jnp.full((b, hq, s_loc), NEG_INF, jnp.float32),
                    jnp.zeros((b, hq, s_loc), jnp.float32),
                    jnp.zeros((b, s_loc, hq, d), jnp.float32))
            return compat.pvary(vals, (axis_name,))

        # Chunks entirely in the causal future contribute nothing; skip
        # their einsums (the ring still rotates them — wall-clock per step
        # is set by the busiest device, but ~half the fleet-wide FLOPs and
        # energy go away). A zigzag shard layout would balance the load
        # too; that changes the caller-visible sharding, so not done here.
        # Under SWA, chunks entirely behind every local query's window are
        # equally dead: max k_pos <= min(q_pos) - window.
        skippable = src * s_loc > q_pos[-1]
        if sliding_window:
            skippable |= (src * s_loc + s_loc - 1
                          <= q_pos[0] - sliding_window)
        m_blk, l_blk, o_blk = jax.lax.cond(skippable, skip, attend,
                                           (k_cur, v_cur))
        m_new = jnp.maximum(m, m_blk)
        a_prev = jnp.exp(m - m_new)
        a_blk = jnp.exp(m_blk - m_new)
        l = l * a_prev + l_blk * a_blk
        acc = (acc * a_prev.transpose(0, 2, 1)[..., None]
               + o_blk * a_blk.transpose(0, 2, 1)[..., None])
        m = m_new
        if step != n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    denom = jnp.maximum(l.transpose(0, 2, 1)[..., None], 1e-20)
    return (acc / denom).astype(q.dtype)


def seq_sharded_call(body, q, k, v, mesh: Mesh, axis_name: str,
                     sliding_window: int = 0):
    """Shared wrapper for sequence-parallel attention kernels: reshard
    q/k/v so the sequence dim shards over ``axis_name`` (batch/head dims
    replicated), run the per-shard ``body`` under shard_map, return with
    the same sequence sharding. Used by ring and ulysses."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(functools.partial(body, axis_name=axis_name,
                                     sliding_window=sliding_window),
                   mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    sh = NamedSharding(mesh, spec)
    return fn(jax.device_put(q, sh), jax.device_put(k, sh),
              jax.device_put(v, sh))


@functools.partial(jax.jit,
                   static_argnames=("mesh", "axis_name", "sliding_window"))
def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Mesh, axis_name: str = "sp",
                   sliding_window: int = 0) -> jax.Array:
    """Full-sequence causal attention, sequence-sharded over ``axis_name``.

    q: [B, S, Hq, D]; k/v: [B, S, Hkv, D] with S divisible by the axis
    size. ``sliding_window`` > 0 applies the SWA mask (Mistral-style).
    """
    return seq_sharded_call(ring_attention_local, q, k, v, mesh, axis_name,
                            sliding_window)
