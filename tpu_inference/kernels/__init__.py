"""TPU kernels (Pallas/Mosaic) — the framework's native-performance tier.

The reference repo contains no native code at all (SURVEY.md §2b: zero
C++/Rust/CUDA components; the GPU kernels it relies on live inside its
external Ollama server). Pallas kernels are the TPU-idiomatic equivalent
of that missing tier: hand-scheduled HBM->VMEM pipelines for the ops XLA
can't fuse well on its own (paged-KV attention), validated against the
dense jnp reference paths in models/common.py.
"""

from tpu_inference.kernels.paged_attention import paged_attention  # noqa: F401
from tpu_inference.kernels.prefill_attention import (  # noqa: F401
    paged_prefill_attention)
from tpu_inference.kernels.ring_attention import (  # noqa: F401
    ring_attention, ring_attention_local)
