"""Pallas paged-attention decode kernel (flash-style online softmax).

One query token per sequence attends over its KV pages scattered through
the HBM pool (engine/kv_cache.py). The dense fallback path first gathers
a sequence's pages into a contiguous buffer ([B, max_pages*page, H, D])
every layer, every step — a full extra HBM round trip of the KV working
set. This kernel instead streams each page HBM->VMEM exactly once and
folds it into running (max, sum, acc) online-softmax state, the standard
TPU pattern for decode attention (vLLM's PagedAttention re-designed for
Mosaic; reference has no analogue — SURVEY.md §2b).

Mechanics:
- ``PrefetchScalarGridSpec`` with the block table + kv lengths as scalar
  prefetch: the KV BlockSpec's index_map reads ``block_tables[b, p]`` to
  pick which physical page the pipeline DMAs next — the gather never
  materializes.
- Grid (B, MP), page index innermost; VMEM scratch (m, l, acc) carries
  the online-softmax state across a sequence's pages and is flushed to
  the output on the last page.
- GQA folded in-kernel: q viewed [Hkv, n_rep, D], each KV head's page
  serves its n_rep query heads via one MXU contraction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _unpack_int4(packed):
    """uint8 nibble-packed [..., D//2] -> f32 [..., D]. ONE copy of the
    packing contract (engine/kv_cache.py unpack_int4_kv: integer
    compare/select sign extension, Mosaic-friendly); the f32 cast is
    this kernel's consumption dtype."""
    from tpu_inference.engine.kv_cache import unpack_int4_kv

    return unpack_int4_kv(packed).astype(jnp.float32)


def _decode_kernel(block_tables_ref, kv_len_ref, q_ref, k_ref, v_ref,
                   *rest, page_size: int, scale: float, quantized: bool,
                   packed: bool = False, sliding_window: int = 0):
    if quantized:
        ks_ref, vs_ref, out_ref, m_ref, l_ref, acc_ref = rest
    else:
        out_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(1)
    num_pages = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kv_len = kv_len_ref[b]
    if sliding_window:
        # Grid position p is RELATIVE to the window's first page (the
        # BlockSpec index maps apply the same offset), so decode reads
        # O(window) pages however long the context is — the property
        # SWA models (Mistral) are built around.
        win_start = jnp.maximum(kv_len - sliding_window, 0)
        page_start = (win_start // page_size + p) * page_size
    else:
        page_start = p * page_size

    @pl.when(page_start < kv_len)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)                  # [Hkv, R, D]
        # Mosaic requires dot_general batch dims at matching positions, so
        # bring the kv-head dim to the front before the batched contractions.
        if packed:
            # int4: one uint8 read of half a page's bytes, unpacked in VMEM.
            k = _unpack_int4(k_ref[0]).transpose(1, 0, 2)    # [Hkv, pg, D]
            v = _unpack_int4(v_ref[0]).transpose(1, 0, 2)
        else:
            k = k_ref[0].astype(jnp.float32).transpose(1, 0, 2)  # [Hkv,pg,D]
            v = v_ref[0].astype(jnp.float32).transpose(1, 0, 2)
        if quantized:
            # int8 codes * per-(token, head) scale — dequant in VMEM, so
            # HBM sees one int8 read of the page.
            k = k * ks_ref[0].astype(jnp.float32).transpose(1, 0)[:, :, None]
            v = v * vs_ref[0].astype(jnp.float32).transpose(1, 0)[:, :, None]

        # scores[h, r, t] = <q[h, r], k[h, t]> * scale
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale    # [Hkv, R, pg]
        pos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=2)
        valid = pos < kv_len
        if sliding_window:
            # Window edge can fall inside this page.
            valid = jnp.logical_and(valid, pos >= kv_len - sliding_window)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[:]                                  # [Hkv, R]
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=2)                         # [Hkv, R]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        pr = jnp.exp(s - m_new[:, :, None])                # [Hkv, R, pg]
        # o[h, r, d] = sum_t pr[h, r, t] * v[h, t, d]
        o = jax.lax.dot_general(
            pr, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [Hkv, R, D]
        m_ref[:] = m_new
        l_ref[:] = l_prev * alpha + jnp.sum(pr, axis=2)
        acc_ref[:] = acc_ref[:] * alpha[:, :, None] + o

    @pl.when(p == num_pages - 1)
    def _flush():
        denom = jnp.maximum(l_ref[:], 1e-20)[:, :, None]
        out_ref[0] = (acc_ref[:] / denom).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "sliding_window"))
def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    block_tables: jax.Array, kv_len: jax.Array,
                    k_scale: jax.Array | None = None,
                    v_scale: jax.Array | None = None,
                    interpret: bool | None = None,
                    sliding_window: int = 0) -> jax.Array:
    """Decode attention over the paged KV pool.

    q:            [B, Hq, D]   (one query token per sequence)
    k/v_pages:    [P, page_size, Hkv, D]  (one layer's pool)
    block_tables: [B, MP] int32 physical page ids (0 = trash page)
    kv_len:       [B] int32 valid tokens per sequence (incl. current)
    k/v_scale:    [P, page_size, Hkv] f32 — present when the pool holds
                  int8 codes (engine/kv_cache.py quantize_kv) or uint8
                  nibble-packed int4 codes (quantize_kv_int4; pool
                  trailing dim D/2); dequant happens in VMEM after each
                  page's DMA.
    sliding_window > 0 (SWA, Mistral): only the pages overlapping the
    last ``sliding_window`` positions are streamed — the grid's page
    axis shrinks to the window's page span and the index maps offset
    into the block table from the window's first page, so decode cost
    is O(window), not O(context).
    Returns [B, Hq, D] in q.dtype.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    # uint8 pool = nibble-packed int4 codes (engine/kv_cache.py); the
    # pool's trailing dim is D/2 bytes and the kernel unpacks in VMEM.
    packed = k_pages.dtype == jnp.uint8
    b, hq, d = q.shape
    _, page_size, hkv, d_pool = k_pages.shape
    n_rep = hq // hkv
    mp = block_tables.shape[1]
    scale = 1.0 / (d ** 0.5)

    q_g = q.reshape(b, hkv, n_rep, d)

    if sliding_window:
        # A window of W positions spans at most ceil(W/page)+1 pages
        # when unaligned to page boundaries.
        n_page_axis = min(mp, -(-sliding_window // page_size) + 1)

        def page_idx(i, p, bt, kl):
            start = jnp.maximum(kl[i] - sliding_window, 0) // page_size
            # Clamp: relative pages past the sequence's last page are
            # compute-masked in the kernel; the DMA just needs a legal id.
            return bt[i, jnp.minimum(start + p, mp - 1)]
    else:
        n_page_axis = mp

        def page_idx(i, p, bt, kl):
            return bt[i, p]

    page_spec = pl.BlockSpec((1, page_size, hkv, d_pool),
                             lambda i, p, bt, kl: (page_idx(i, p, bt, kl),
                                                   0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, hkv, n_rep, d), lambda i, p, bt, kl: (i, 0, 0, 0)),
        page_spec,
        page_spec,
    ]
    operands = [q_g, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec((1, page_size, hkv),
                                  lambda i, p, bt, kl: (
                                      page_idx(i, p, bt, kl), 0, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # block_tables, kv_len
        grid=(b, n_page_axis),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, hkv, n_rep, d),
                               lambda i, p, bt, kl: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, n_rep), jnp.float32),       # running max
            pltpu.VMEM((hkv, n_rep), jnp.float32),       # running sum
            pltpu.VMEM((hkv, n_rep, d), jnp.float32),    # running out
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, page_size=page_size, scale=scale,
                          quantized=quantized, packed=packed,
                          sliding_window=sliding_window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, n_rep, d), q.dtype),
        interpret=interpret,
    )(block_tables, kv_len, *operands)
    return out.reshape(b, hq, d)
