"""Ulysses-style sequence parallelism: all-to-all head-scatter attention.

The second of the two canonical sequence/context-parallel schemes (the
other is ring attention, kernels/ring_attention.py — the reference has
neither; it clamps prompts to 1024 tokens client-side, SURVEY.md §5).
Instead of rotating K/V shards around a ring, two ``all_to_all``
collectives re-shard the activations between layouts:

    [B, S/n, H,   D]   (sequence-sharded — the layer's layout)
        -- all_to_all(split=heads, concat=seq) -->
    [B, S,   H/n, D]   (head-sharded: every device sees the FULL
                        sequence for its head group)
        -- plain causal attention, no cross-device bookkeeping --
        -- all_to_all(split=seq, concat=heads) -->
    [B, S/n, H,   D]

Trade-offs vs the ring (both kept; EngineConfig.sp_attn picks):

- **Latency/hops**: Ulysses is 2 collective phases regardless of axis
  size; the ring is n-1 sequential ppermute steps. On short-to-medium
  prompts the ring's per-step latency dominates and Ulysses wins.
- **Load balance**: causal masking makes ring step cost skewed (early
  ranks finish their useful work sooner); Ulysses gives every device
  the same full-sequence attention for H/n heads.
- **Bytes on the wire**: Ulysses moves q+k+v+out once each
  (~4·S/n·H·D per device); the ring moves only k+v, (n-1) times
  (~2·(n-1)·S/n·Hkv·D). With strong GQA (Hkv << Hq) the ring can move
  fewer bytes for large n.
- **Memory**: Ulysses materializes full-sequence scores per local head
  group (O(S²·H/n)); the ring stays O((S/n)²) — for extreme contexts
  prefer the ring.
- **Divisibility**: Ulysses needs both Hq and Hkv divisible by the sp
  axis size (after tp head sharding); the ring only needs S divisible.

Design follows the DeepSpeed-Ulysses pattern (PAPERS.md) with XLA
``all_to_all`` (lowered to ICI all-to-all on TPU) instead of NCCL.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh

from tpu_inference import compat

def ulysses_attention_local(q: jax.Array, k: jax.Array, v: jax.Array,
                            axis_name: str = "sp",
                            sliding_window: int = 0) -> jax.Array:
    """Per-shard body; call under shard_map with the sequence dim sharded
    over ``axis_name``. q: [B, S_loc, Hq, D]; k/v: [B, S_loc, Hkv, D].
    Requires the local Hq and Hkv to be divisible by the axis size.
    ``sliding_window`` > 0 applies the SWA mask (the head-sharded
    attention sees the full sequence, so the window term needs no
    cross-device bookkeeping at all). Returns [B, S_loc, Hq, D] in
    q.dtype.

    The head-sharded attention IS the repo's correctness-reference
    attention (models.common.dense_causal_attention — GQA expansion,
    f32 softmax, causal mask, output back in q.dtype), so the math can
    never drift from the oracle; activations cross the wire in their
    raw dtype (the upcast happens inside the attention, after the
    collective)."""
    from tpu_inference.models.common import dense_causal_attention

    n = compat.axis_size(axis_name)
    hq, hkv = q.shape[2], k.shape[2]
    if n == 1:
        return dense_causal_attention(q, k, v, sliding_window=sliding_window)
    assert hq % n == 0 and hkv % n == 0, (
        f"ulysses needs head counts divisible by the sp axis: "
        f"Hq={hq}, Hkv={hkv}, sp={n}")
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            tiled=True)
    # seq-sharded -> head-sharded: full sequence, H/n local heads.
    qg = a2a(q, split_axis=2, concat_axis=1)
    kg = a2a(k, split_axis=2, concat_axis=1)
    vg = a2a(v, split_axis=2, concat_axis=1)
    out = dense_causal_attention(qg, kg, vg,       # returns q.dtype
                                 sliding_window=sliding_window)
    # head-sharded -> seq-sharded (raw dtype on the wire).
    return a2a(out, split_axis=1, concat_axis=2)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "axis_name", "sliding_window"))
def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      mesh: Mesh, axis_name: str = "sp",
                      sliding_window: int = 0) -> jax.Array:
    """Full-sequence causal attention, sequence-sharded over
    ``axis_name`` (same call surface as kernels.ring_attention)."""
    from tpu_inference.kernels.ring_attention import seq_sharded_call

    return seq_sharded_call(ulysses_attention_local, q, k, v, mesh,
                            axis_name, sliding_window)
