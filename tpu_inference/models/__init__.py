"""Pure-function JAX model definitions over parameter pytrees.

Each family module exposes:
  init_params(cfg, key)            -> params pytree (random init)
  forward(params, cfg, tokens, positions, kv, attn) -> (logits, kv)

where ``attn`` is an AttentionFn injected by the caller (engine supplies the
paged-cache implementation; tests supply dense causal attention). This keeps
model math independent of KV-cache policy, sharding, and batching strategy.
"""

from tpu_inference.models import common, gpt2, llama, mixtral  # noqa: F401
from tpu_inference.models.registry import build_model, get_model_fns  # noqa: F401
