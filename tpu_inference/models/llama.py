"""Llama-family decoder (RMSNorm + RoPE + GQA + SwiGLU) as pure JAX.

One module serves the whole dialect family via ModelConfig knobs:
vanilla Llama, Mistral (sliding_window — masked in the attention
backend), Qwen2 (qkv_bias), and Gemma (norm_offset, gelu_tanh gate,
embed_scale, decoupled head_dim). Parity for each dialect is pinned
against its HF implementation in tests/test_model_parity.py.

TPU-first design notes:
- Per-layer weights are **stacked along a leading layer axis** and the block
  stack runs under ``jax.lax.scan`` — one traced layer body regardless of
  depth, so Llama-70B (80 layers) compiles as fast as the tiny test model.
- Activations are cfg.dtype (bf16 in production) feeding the MXU; norms and
  softmax accumulate f32 (see models/common.py).
- Attention is injected (AttentionFn), so the same forward serves full-context
  parity tests, paged-KV decode, and Pallas kernels.

Functional parity target: the reference repo has no model code (SURVEY.md §0);
this implements the server-side model the reference delegates to an external
Ollama endpoint (reference: traffic_generator/main.py:306). Correctness is
pinned against HuggingFace ``LlamaForCausalLM`` in tests/test_llama_parity.py.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from tpu_inference.config import ModelConfig
from tpu_inference.models.common import (
    AttentionFn,
    apply_rope,
    rms_norm,
    swiglu,
)
from tpu_inference.models.quant import qdot


def init_params(cfg: ModelConfig, key: jax.Array) -> dict:
    """Random init (normal, 0.02 std) with stacked layer weights."""
    cfg.validate()
    d, f = cfg.d_model, cfg.d_ff
    hd = cfg.head_dim
    keys = jax.random.split(key, 8)

    def norm(k, shape):
        return (0.02 * jax.random.normal(k, shape, jnp.float32)).astype(cfg.dtype)

    L = cfg.n_layers
    params = {
        "embed": norm(keys[0], (cfg.vocab_size, d)),
        "blocks": {
            "attn_norm": jnp.ones((L, d), cfg.dtype),
            "wq": norm(keys[1], (L, d, cfg.n_heads * hd)),
            "wk": norm(keys[2], (L, d, cfg.n_kv_heads * hd)),
            "wv": norm(keys[3], (L, d, cfg.n_kv_heads * hd)),
            "wo": norm(keys[4], (L, cfg.n_heads * hd, d)),
            "ffn_norm": jnp.ones((L, d), cfg.dtype),
            "w_gate": norm(keys[5], (L, d, f)),
            "w_up": norm(keys[6], (L, d, f)),
            "w_down": norm(keys[7], (L, f, d)),
        },
        "final_norm": jnp.ones((d,), cfg.dtype),
    }
    if cfg.qkv_bias:
        params["blocks"]["bq"] = jnp.zeros((L, cfg.n_heads * hd), cfg.dtype)
        params["blocks"]["bk"] = jnp.zeros((L, cfg.n_kv_heads * hd), cfg.dtype)
        params["blocks"]["bv"] = jnp.zeros((L, cfg.n_kv_heads * hd), cfg.dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(jax.random.split(keys[0])[0],
                                 (d, cfg.vocab_size))
    return params


def decoder_block(cfg: ModelConfig, layer_idx: jax.Array, lp: dict,
                  x: jax.Array, positions: jax.Array, kv: Any,
                  attn: AttentionFn):
    """One transformer block. x: [B, S, D]. Public: parallel/pipeline.py
    runs per-stage layer slabs through it."""
    b, s, d = x.shape
    hd = cfg.head_dim

    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps, cfg.norm_offset)
    q, k, v = qdot(h, lp["wq"]), qdot(h, lp["wk"]), qdot(h, lp["wv"])
    if cfg.qkv_bias:
        q = q + lp["bq"].astype(jnp.float32)
        k = k + lp["bk"].astype(jnp.float32)
        v = v + lp["bv"].astype(jnp.float32)
    q, k, v = (t.astype(x.dtype) for t in (q, k, v))
    q = q.reshape(b, s, cfg.n_heads, hd)
    k = k.reshape(b, s, cfg.n_kv_heads, hd)
    v = v.reshape(b, s, cfg.n_kv_heads, hd)
    q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
    k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)

    attn_out, kv = attn(layer_idx, q, k, v, kv)
    attn_out = attn_out.reshape(b, s, cfg.n_heads * hd)
    x = x + qdot(attn_out, lp["wo"]).astype(x.dtype)

    h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps, cfg.norm_offset)
    x = x + swiglu(h, lp["w_gate"], lp["w_up"], lp["w_down"],
                   act=cfg.hidden_act)
    return x, kv


def embed_tokens(params: dict, cfg: ModelConfig,
                 tokens: jax.Array) -> jax.Array:
    """Token ids -> input embeddings (shared with parallel/pipeline.py)."""
    x = params["embed"][tokens].astype(cfg.dtype)
    if cfg.embed_scale:
        # Gemma: HF casts the sqrt(d) normalizer to the activation dtype
        # before multiplying; match that rounding for parity.
        x = x * jnp.asarray(cfg.d_model ** 0.5, dtype=cfg.dtype)
    return x


def forward_hidden(params: dict, cfg: ModelConfig, tokens: jax.Array,
                   positions: jax.Array, kv: Any,
                   attn: AttentionFn) -> Tuple[jax.Array, Any]:
    """Token ids -> final hidden states. tokens, positions: [B, S]."""
    x = embed_tokens(params, cfg, tokens)

    def body(carry, scanned):
        x, kv = carry
        layer_idx, lp = scanned
        x, kv = decoder_block(cfg, layer_idx, lp, x, positions, kv, attn)
        return (x, kv), None

    layer_ids = jnp.arange(cfg.n_layers)
    (x, kv), _ = jax.lax.scan(body, (x, kv), (layer_ids, params["blocks"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps, cfg.norm_offset)
    return x, kv


def unembed(params: dict, cfg: ModelConfig, hidden: jax.Array) -> jax.Array:
    """Hidden states -> f32 logits."""
    if cfg.tie_embeddings:
        return jnp.dot(hidden, params["embed"].T,
                       preferred_element_type=jnp.float32)
    return qdot(hidden, params["lm_head"])


def forward(params: dict, cfg: ModelConfig, tokens: jax.Array,
            positions: jax.Array, kv: Any,
            attn: AttentionFn) -> Tuple[jax.Array, Any]:
    """Convenience: full-sequence logits (tests / tiny models)."""
    hidden, kv = forward_hidden(params, cfg, tokens, positions, kv, attn)
    return unembed(params, cfg, hidden), kv
