"""GPT-2 family (LayerNorm + learned positions + GELU, fused QKV) in pure JAX.

Serves the harness-parity config 0 ("CPU gpt2 HTTP stub", BASELINE.json
configs[0]). Same stacked-layer ``lax.scan`` structure and injected-attention
design as models/llama.py. Parity is pinned against HF ``GPT2LMHeadModel`` in
tests/test_gpt2_parity.py.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from tpu_inference.config import ModelConfig
from tpu_inference.models.common import AttentionFn, layer_norm, linear


def init_params(cfg: ModelConfig, key: jax.Array) -> dict:
    cfg.validate()
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    keys = jax.random.split(key, 6)

    def norm(k, shape):
        return (0.02 * jax.random.normal(k, shape, jnp.float32)).astype(cfg.dtype)

    return {
        "embed": norm(keys[0], (cfg.vocab_size, d)),
        "pos_embed": norm(keys[1], (cfg.max_seq_len, d)),
        "blocks": {
            "ln1_w": jnp.ones((L, d), cfg.dtype),
            "ln1_b": jnp.zeros((L, d), cfg.dtype),
            "w_qkv": norm(keys[2], (L, d, 3 * d)),
            "b_qkv": jnp.zeros((L, 3 * d), cfg.dtype),
            "w_proj": norm(keys[3], (L, d, d)),
            "b_proj": jnp.zeros((L, d), cfg.dtype),
            "ln2_w": jnp.ones((L, d), cfg.dtype),
            "ln2_b": jnp.zeros((L, d), cfg.dtype),
            "w_fc": norm(keys[4], (L, d, f)),
            "b_fc": jnp.zeros((L, f), cfg.dtype),
            "w_out": norm(keys[5], (L, f, d)),
            "b_out": jnp.zeros((L, d), cfg.dtype),
        },
        "ln_f_w": jnp.ones((d,), cfg.dtype),
        "ln_f_b": jnp.zeros((d,), cfg.dtype),
    }


def _block(cfg: ModelConfig, layer_idx: jax.Array, lp: dict, x: jax.Array,
           positions: jax.Array, kv: Any, attn: AttentionFn):
    b, s, d = x.shape
    hd = cfg.head_dim

    h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], cfg.norm_eps)
    qkv = linear(h, lp["w_qkv"], lp["b_qkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, cfg.n_heads, hd)
    k = k.reshape(b, s, cfg.n_kv_heads, hd)
    v = v.reshape(b, s, cfg.n_kv_heads, hd)

    attn_out, kv = attn(layer_idx, q, k, v, kv)
    attn_out = attn_out.reshape(b, s, d)
    x = x + linear(attn_out, lp["w_proj"], lp["b_proj"])

    h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], cfg.norm_eps)
    h = jax.nn.gelu(linear(h, lp["w_fc"], lp["b_fc"]), approximate=True)
    x = x + linear(h, lp["w_out"], lp["b_out"])
    return x, kv


def forward_hidden(params: dict, cfg: ModelConfig, tokens: jax.Array,
                   positions: jax.Array, kv: Any,
                   attn: AttentionFn) -> Tuple[jax.Array, Any]:
    x = (params["embed"][tokens] + params["pos_embed"][positions]).astype(cfg.dtype)

    def body(carry, scanned):
        x, kv = carry
        layer_idx, lp = scanned
        x, kv = _block(cfg, layer_idx, lp, x, positions, kv, attn)
        return (x, kv), None

    layer_ids = jnp.arange(cfg.n_layers)
    (x, kv), _ = jax.lax.scan(body, (x, kv), (layer_ids, params["blocks"]))
    x = layer_norm(x, params["ln_f_w"], params["ln_f_b"], cfg.norm_eps)
    return x, kv


def unembed(params: dict, cfg: ModelConfig, hidden: jax.Array) -> jax.Array:
    return jnp.dot(hidden, params["embed"].T,
                   preferred_element_type=jnp.float32)


def forward(params: dict, cfg: ModelConfig, tokens: jax.Array,
            positions: jax.Array, kv: Any,
            attn: AttentionFn) -> Tuple[jax.Array, Any]:
    hidden, kv = forward_hidden(params, cfg, tokens, positions, kv, attn)
    return unembed(params, cfg, hidden), kv
