"""Shared transformer building blocks (TPU-idiomatic JAX).

Conventions:
- Activations flow in ``cfg.dtype`` (bfloat16 in production) so matmuls hit
  the MXU at full rate; normalization statistics and attention softmax
  accumulate in float32.
- All functions are pure and shape-static, safe under ``jax.jit``.
- Attention is *injected*: model forward passes take an ``AttentionFn``
  ``attn(layer_idx, q, k, v, kv) -> (out, kv)`` with q [B,S,Hq,D] and
  k/v [B,S,Hkv,D]; the engine's paged-cache attention, the dense causal
  test path, and the Pallas kernels all fit this signature.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from tpu_inference.models.quant import qdot

# attn(layer_idx, q, k, v, kv_state) -> (attn_out, kv_state)
AttentionFn = Callable[[int, jax.Array, jax.Array, jax.Array, Any],
                       Tuple[jax.Array, Any]]

# Gated-FFN activations; a KeyError here fails loudly on an unknown or
# unmapped hidden_act instead of silently running the wrong function.
_GATE_ACTS = {
    "silu": jax.nn.silu,
    "gelu_tanh": partial(jax.nn.gelu, approximate=True),
}


def rms_norm(x: jax.Array, weight: jax.Array, eps: float,
             offset: float = 0.0) -> jax.Array:
    """RMSNorm with float32 statistics, output in x.dtype.

    ``offset`` supports Gemma's stored-as-delta weights (y = normed *
    (1 + w)); adding in float32 avoids the precision loss of
    pre-materializing 1 + w in bf16.
    """
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * (weight.astype(jnp.float32) + offset)).astype(x.dtype)


def layer_norm(x: jax.Array, weight: jax.Array, bias: jax.Array,
               eps: float) -> jax.Array:
    """LayerNorm (GPT-2 family) with float32 statistics."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mean) * jax.lax.rsqrt(var + eps)
    out = normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def rope_frequencies(head_dim: int, theta: float,
                     scaling=None) -> jax.Array:
    """Inverse frequencies for rotary embeddings, [head_dim // 2] f32.

    ``scaling`` (config.RopeScaling) applies the Llama-3.1 "llama3"
    per-channel rescale, matching HF's _compute_llama3_parameters:
    channels with wavelength above original_max_len/low_freq_factor run
    ``factor``× slower, those below original_max_len/high_freq_factor are
    untouched, and the band between interpolates by how far the original
    context fits into the wavelength.
    """
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta ** exponent)
    if scaling is not None:
        wavelen = 2.0 * jnp.pi / inv_freq
        smooth = ((scaling.original_max_len / wavelen
                   - scaling.low_freq_factor)
                  / (scaling.high_freq_factor - scaling.low_freq_factor))
        interp = ((1.0 - smooth) * inv_freq / scaling.factor
                  + smooth * inv_freq)
        inv_freq = jnp.where(
            wavelen > scaling.original_max_len / scaling.low_freq_factor,
            inv_freq / scaling.factor,
            jnp.where(
                wavelen < scaling.original_max_len / scaling.high_freq_factor,
                inv_freq, interp))
    return inv_freq


def apply_rope(x: jax.Array, positions: jax.Array, theta: float,
               scaling=None) -> jax.Array:
    """Rotary position embedding.

    x: [B, S, H, D]; positions: [B, S] int32. Uses the half-split pairing
    (first half with second half), matching HF Llama's rotate_half.
    ``scaling`` forwards to rope_frequencies (Llama-3.1 rescale).
    """
    half = x.shape[-1] // 2
    inv_freq = rope_frequencies(x.shape[-1], theta, scaling)  # [half]
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]                      # [B,S,1,half]
    sin = jnp.sin(angles)[:, :, None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """Expand KV heads for GQA: [B, S, Hkv, D] -> [B, S, Hkv*n_rep, D]."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d)


def dense_causal_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           q_offset: jax.Array | int = 0,
                           kv_len: jax.Array | None = None,
                           sliding_window: int = 0) -> jax.Array:
    """Dense causal attention; the correctness reference for all kernels.

    q: [B, Sq, Hq, D]; k, v: [B, Skv, Hkv, D] (GQA expanded internally).
    ``q_offset`` (scalar or [B]) is the absolute position of q's first token
    within the KV sequence (for chunked prefill / decode against a cache).
    ``kv_len`` (scalar or [B]) masks out cache slots beyond the valid length.
    ``sliding_window`` > 0 additionally masks keys more than window-1
    positions behind the query (Mistral-style SWA: each token attends to
    itself and the window-1 tokens before it). Softmax in float32.
    """
    b, sq, hq, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    k = repeat_kv(k, hq // hkv)
    v = repeat_kv(v, hq // hkv)
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    # [B, H, Sq, Skv]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    offs = jnp.broadcast_to(jnp.asarray(q_offset), (b,))        # [B]
    q_pos = offs[:, None] + jnp.arange(sq)[None, :]             # [B, Sq]
    k_pos = jnp.arange(skv)                                     # [Skv]
    mask = k_pos[None, None, :] <= q_pos[:, :, None]            # [B, Sq, Skv]
    if sliding_window:
        mask = jnp.logical_and(
            mask, k_pos[None, None, :] > q_pos[:, :, None] - sliding_window)
    if kv_len is not None:
        lens = jnp.broadcast_to(jnp.asarray(kv_len), (b,))
        mask = jnp.logical_and(mask, k_pos[None, None, :] < lens[:, None, None])
    scores = jnp.where(mask[:, None, :, :], scores, jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def make_dense_attn(sliding_window: int = 0) -> AttentionFn:
    """AttentionFn for cache-free full-sequence forward (tests, parity).
    ``sliding_window`` mirrors ModelConfig.sliding_window for SWA models
    (Mistral)."""

    def attn(layer_idx: int, q, k, v, kv):
        del layer_idx
        return dense_causal_attention(q, k, v,
                                      sliding_window=sliding_window), kv

    return attn


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array,
           w_down: jax.Array, act: str = "silu") -> jax.Array:
    """Gated FFN: down( act(x @ gate) * (x @ up) ).

    ``act``: "silu" (SwiGLU — Llama/Qwen/Mistral) or "gelu_tanh" (GeGLU
    with the tanh approximation — Gemma). Weights may be int8/int4
    ``QuantizedArray``s (models/quant.py) — ``qdot`` handles both
    representations.
    """
    fn = _GATE_ACTS[act]
    gate = fn(qdot(x, w_gate))
    up = qdot(x, w_up)
    return qdot((gate * up).astype(x.dtype), w_down).astype(x.dtype)


def linear(x: jax.Array, w: jax.Array, b: jax.Array | None = None) -> jax.Array:
    out = qdot(x, w)
    if b is not None:
        out = out + b.astype(jnp.float32)
    return out.astype(x.dtype)
