"""Mixtral family: Llama-style attention + sparse MoE FFN, in pure JAX.

TPU-first MoE formulation: token-choice top-k routing expressed as **static
dispatch/combine einsums** (Switch-Transformer / Mesh-TF style) instead of
ragged gather/scatter — every shape is static so XLA tiles the expert matmuls
on the MXU, and the expert axis shards cleanly for expert parallelism (each
chip computes its local experts; the dispatch/combine einsums become
all-to-alls over ICI under a NamedSharding on the expert dim — see
parallel/shardings.py).

Capacity model: each expert processes at most C = ceil(k*T/E * factor) tokens
per call; overflow tokens lose that expert's contribution (standard capacity
dropping). Tests pin routing math against HF ``MixtralForCausalLM``.
"""

from __future__ import annotations

import math
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from tpu_inference.config import ModelConfig
from tpu_inference.models.common import AttentionFn, apply_rope, rms_norm
from tpu_inference.models.quant import qdot, qeinsum


def init_params(cfg: ModelConfig, key: jax.Array) -> dict:
    cfg.validate()
    d, f, L, E = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.n_experts
    hd = cfg.head_dim
    keys = jax.random.split(key, 10)

    def norm(k, shape):
        return (0.02 * jax.random.normal(k, shape, jnp.float32)).astype(cfg.dtype)

    params = {
        "embed": norm(keys[0], (cfg.vocab_size, d)),
        "blocks": {
            "attn_norm": jnp.ones((L, d), cfg.dtype),
            "wq": norm(keys[1], (L, d, cfg.n_heads * hd)),
            "wk": norm(keys[2], (L, d, cfg.n_kv_heads * hd)),
            "wv": norm(keys[3], (L, d, cfg.n_kv_heads * hd)),
            "wo": norm(keys[4], (L, cfg.n_heads * hd, d)),
            "ffn_norm": jnp.ones((L, d), cfg.dtype),
            "w_router": norm(keys[5], (L, d, E)),
            "w_gate": norm(keys[6], (L, E, d, f)),
            "w_up": norm(keys[7], (L, E, d, f)),
            "w_down": norm(keys[8], (L, E, f, d)),
        },
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": norm(keys[9], (d, cfg.vocab_size)),
    }
    return params


def expert_capacity(cfg: ModelConfig, n_tokens: int) -> int:
    """Static per-expert capacity for a call processing n_tokens tokens."""
    c = math.ceil(cfg.n_experts_per_tok * n_tokens / cfg.n_experts
                  * cfg.expert_capacity_factor)
    return max(c, cfg.n_experts_per_tok)


def moe_ffn(cfg: ModelConfig, lp: dict, x: jax.Array) -> jax.Array:
    """Sparse MoE FFN. x: [B, S, D] -> [B, S, D].

    Dispatch/combine are dense one-hot einsums with static shapes:
      dispatch [T, E, C] maps tokens into per-expert buffers,
      expert_in = einsum('tec,td->ecd'), experts run as one batched matmul
      over the leading E axis, combine applies routing weights on the way out.
    """
    b, s, d = x.shape
    t = b * s
    e, k = cfg.n_experts, cfg.n_experts_per_tok
    cap = expert_capacity(cfg, t)
    x2 = x.reshape(t, d)

    router_logits = jnp.dot(x2, lp["w_router"],
                            preferred_element_type=jnp.float32)  # [T, E]
    top_vals, top_idx = jax.lax.top_k(router_logits, k)          # [T, k]
    # Mixtral normalizes softmax over the selected k logits only.
    top_w = jax.nn.softmax(top_vals, axis=-1)                    # [T, k] f32
    choice_oh = jax.nn.one_hot(top_idx, e, dtype=jnp.int32)      # [T, k, E]
    mask = jnp.sum(choice_oh, axis=1)                            # [T, E] {0,1}
    combine_w = jnp.einsum("tk,tke->te", top_w,
                           choice_oh.astype(jnp.float32))        # [T, E]

    # Position of each token within its expert's buffer; one_hot maps
    # out-of-range (dropped / unrouted) positions to all-zero rows.
    pos = jnp.cumsum(mask, axis=0) * mask - 1                    # [T, E]
    dispatch = jax.nn.one_hot(pos, cap, dtype=cfg.dtype)         # [T, E, C]
    dispatch = dispatch * mask[..., None].astype(cfg.dtype)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, x2,
                           preferred_element_type=jnp.float32).astype(cfg.dtype)
    gate = jax.nn.silu(qeinsum("ecd,edf->ecf", expert_in, lp["w_gate"]))
    up = qeinsum("ecd,edf->ecf", expert_in, lp["w_up"])
    expert_out = qeinsum("ecf,efd->ecd", (gate * up).astype(cfg.dtype),
                         lp["w_down"])                           # [E, C, D] f32

    combine = dispatch.astype(jnp.float32) * combine_w[..., None]
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out.astype(x.dtype).reshape(b, s, d)


def _block(cfg: ModelConfig, layer_idx: jax.Array, lp: dict, x: jax.Array,
           positions: jax.Array, kv: Any, attn: AttentionFn):
    b, s, d = x.shape
    hd = cfg.head_dim

    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = qdot(h, lp["wq"]).astype(x.dtype)
    k = qdot(h, lp["wk"]).astype(x.dtype)
    v = qdot(h, lp["wv"]).astype(x.dtype)
    q = apply_rope(q.reshape(b, s, cfg.n_heads, hd), positions,
                   cfg.rope_theta, cfg.rope_scaling)
    k = apply_rope(k.reshape(b, s, cfg.n_kv_heads, hd), positions,
                   cfg.rope_theta, cfg.rope_scaling)
    v = v.reshape(b, s, cfg.n_kv_heads, hd)

    attn_out, kv = attn(layer_idx, q, k, v, kv)
    attn_out = attn_out.reshape(b, s, cfg.n_heads * hd)
    x = x + qdot(attn_out, lp["wo"]).astype(x.dtype)

    h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
    x = x + moe_ffn(cfg, lp, h)
    return x, kv


def forward_hidden(params: dict, cfg: ModelConfig, tokens: jax.Array,
                   positions: jax.Array, kv: Any,
                   attn: AttentionFn) -> Tuple[jax.Array, Any]:
    x = params["embed"][tokens].astype(cfg.dtype)

    def body(carry, scanned):
        x, kv = carry
        layer_idx, lp = scanned
        x, kv = _block(cfg, layer_idx, lp, x, positions, kv, attn)
        return (x, kv), None

    layer_ids = jnp.arange(cfg.n_layers)
    (x, kv), _ = jax.lax.scan(body, (x, kv), (layer_ids, params["blocks"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return x, kv


def unembed(params: dict, cfg: ModelConfig, hidden: jax.Array) -> jax.Array:
    return qdot(hidden, params["lm_head"])


def forward(params: dict, cfg: ModelConfig, tokens: jax.Array,
            positions: jax.Array, kv: Any,
            attn: AttentionFn) -> Tuple[jax.Array, Any]:
    hidden, kv = forward_hidden(params, cfg, tokens, positions, kv, attn)
    return unembed(params, cfg, hidden), kv
