"""Model family registry: maps ModelConfig.family -> module of pure fns."""

from __future__ import annotations

import types
from typing import Tuple

import jax

from tpu_inference.config import ModelConfig


def get_model_fns(cfg: ModelConfig) -> types.ModuleType:
    from tpu_inference.models import gpt2, llama, mixtral

    return {"llama": llama, "mixtral": mixtral, "gpt2": gpt2}[cfg.family]


def build_model(cfg: ModelConfig, seed: int = 0) -> Tuple[dict, types.ModuleType]:
    """Random-init params + family module."""
    mod = get_model_fns(cfg)
    params = mod.init_params(cfg, jax.random.PRNGKey(seed))
    return params, mod
