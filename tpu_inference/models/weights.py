"""HF checkpoint -> jax parameter pytree conversion.

Two entry points:
- ``convert_state_dict(cfg, state_dict)``: torch/numpy state dict (HF naming)
  -> this framework's stacked-layer pytree. Used by parity tests (random HF
  model in-process) and by the safetensors loader.
- ``load_checkpoint(cfg, path, shardings=None)``: read a HF safetensors
  directory and place arrays directly onto devices, optionally with
  ``NamedSharding`` per leaf so a 70B model streams straight into its TP
  layout without materializing on one host (SURVEY.md §5 "Checkpoint/resume").

HF linear weights are [out, in]; this framework stores [in, out] so forward
passes are plain ``x @ w`` row-major matmuls. GPT-2's Conv1D is already
[in, out] and is not transposed.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_inference.config import ModelConfig


def _np(x: Any) -> np.ndarray:
    """torch tensor | np array -> np array (no torch import required here)."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def _stack(state: Dict[str, Any], fmt: str, n_layers: int,
           transpose: bool = False) -> np.ndarray:
    mats = [_np(state[fmt.format(i)]) for i in range(n_layers)]
    if transpose:
        mats = [m.T for m in mats]
    return np.stack(mats)


def convert_llama(cfg: ModelConfig, sd: Dict[str, Any]) -> dict:
    L = cfg.n_layers
    p = "model.layers.{}."
    params = {
        "embed": _np(sd["model.embed_tokens.weight"]),
        "blocks": {
            "attn_norm": _stack(sd, p + "input_layernorm.weight", L),
            "wq": _stack(sd, p + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, p + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, p + "self_attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, p + "self_attn.o_proj.weight", L, transpose=True),
            "ffn_norm": _stack(sd, p + "post_attention_layernorm.weight", L),
            "w_gate": _stack(sd, p + "mlp.gate_proj.weight", L, transpose=True),
            "w_up": _stack(sd, p + "mlp.up_proj.weight", L, transpose=True),
            "w_down": _stack(sd, p + "mlp.down_proj.weight", L, transpose=True),
        },
        "final_norm": _np(sd["model.norm.weight"]),
    }
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight", sd["model.embed_tokens.weight"])
        params["lm_head"] = _np(head).T
    return params


def convert_gpt2(cfg: ModelConfig, sd: Dict[str, Any]) -> dict:
    L = cfg.n_layers
    # HF prefixes keys with "transformer." on GPT2LMHeadModel state dicts.
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    p = pre + "h.{}."
    return {
        "embed": _np(sd[pre + "wte.weight"]),
        "pos_embed": _np(sd[pre + "wpe.weight"]),
        "blocks": {
            "ln1_w": _stack(sd, p + "ln_1.weight", L),
            "ln1_b": _stack(sd, p + "ln_1.bias", L),
            "w_qkv": _stack(sd, p + "attn.c_attn.weight", L),   # Conv1D: [in,out]
            "b_qkv": _stack(sd, p + "attn.c_attn.bias", L),
            "w_proj": _stack(sd, p + "attn.c_proj.weight", L),
            "b_proj": _stack(sd, p + "attn.c_proj.bias", L),
            "ln2_w": _stack(sd, p + "ln_2.weight", L),
            "ln2_b": _stack(sd, p + "ln_2.bias", L),
            "w_fc": _stack(sd, p + "mlp.c_fc.weight", L),
            "b_fc": _stack(sd, p + "mlp.c_fc.bias", L),
            "w_out": _stack(sd, p + "mlp.c_proj.weight", L),
            "b_out": _stack(sd, p + "mlp.c_proj.bias", L),
        },
        "ln_f_w": _np(sd[pre + "ln_f.weight"]),
        "ln_f_b": _np(sd[pre + "ln_f.bias"]),
    }


def convert_mixtral(cfg: ModelConfig, sd: Dict[str, Any]) -> dict:
    L, E = cfg.n_layers, cfg.n_experts
    p = "model.layers.{}."

    def stack_experts(w_name: str, transpose: bool) -> np.ndarray:
        layers = []
        for i in range(L):
            mats = [_np(sd[f"model.layers.{i}.block_sparse_moe.experts.{e}.{w_name}.weight"])
                    for e in range(E)]
            if transpose:
                mats = [m.T for m in mats]
            layers.append(np.stack(mats))
        return np.stack(layers)  # [L, E, ...]

    return {
        "embed": _np(sd["model.embed_tokens.weight"]),
        "blocks": {
            "attn_norm": _stack(sd, p + "input_layernorm.weight", L),
            "wq": _stack(sd, p + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, p + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, p + "self_attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, p + "self_attn.o_proj.weight", L, transpose=True),
            "ffn_norm": _stack(sd, p + "post_attention_layernorm.weight", L),
            "w_router": _stack(sd, p + "block_sparse_moe.gate.weight", L,
                               transpose=True),
            # HF Mixtral: w1 = gate, w2 = down, w3 = up.
            "w_gate": stack_experts("w1", transpose=True),
            "w_up": stack_experts("w3", transpose=True),
            "w_down": stack_experts("w2", transpose=True),
        },
        "final_norm": _np(sd["model.norm.weight"]),
        "lm_head": _np(sd["lm_head.weight"]).T,
    }


_CONVERTERS = {"llama": convert_llama, "gpt2": convert_gpt2,
               "mixtral": convert_mixtral}


def convert_state_dict(cfg: ModelConfig, sd: Dict[str, Any]) -> dict:
    """HF state dict -> params pytree (np arrays cast to cfg.dtype)."""
    params = _CONVERTERS[cfg.family](cfg, sd)
    return jax.tree.map(lambda a: jnp.asarray(a, dtype=cfg.dtype), params)


def save_native(params: dict, path: str) -> None:
    """Serialize a params pytree with Orbax (sharded-aware, resumable).

    The TPU-native checkpoint tier (SURVEY.md §5 checkpoint/resume):
    HF safetensors are the interchange format; Orbax is the fast path
    for restart-after-failure, writing each shard from its owning host.
    """
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), params, force=True)


def load_native(path: str, template: dict) -> dict:
    """Restore an Orbax checkpoint. ``template`` is a pytree of arrays or
    jax.ShapeDtypeStruct (optionally with shardings) giving the target
    structure/placement — pass sharded abstract leaves to stream a 70B
    checkpoint straight into its TP layout."""
    import orbax.checkpoint as ocp

    abstract = jax.tree.map(
        lambda x: x if isinstance(x, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(x.shape, x.dtype,
                                  sharding=getattr(x, "sharding", None)),
        template)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(os.path.abspath(path), abstract)


def load_checkpoint(cfg: ModelConfig, path: str,
                    shardings: Optional[dict] = None) -> dict:
    """Load a HF safetensors directory into a (optionally sharded) pytree.

    ``shardings``: pytree matching the params structure with
    ``jax.sharding.Sharding`` leaves; arrays are device_put per-leaf so large
    checkpoints stream to their final layout shard by shard.
    """
    from safetensors import safe_open  # deferred: optional dependency

    index_path = os.path.join(path, "model.safetensors.index.json")
    sd: Dict[str, np.ndarray] = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            weight_map = json.load(f)["weight_map"]
        shards = sorted(set(weight_map.values()))
    else:
        shards = [f for f in os.listdir(path) if f.endswith(".safetensors")]
    for shard in shards:
        with safe_open(os.path.join(path, shard), framework="np") as f:
            for key in f.keys():
                sd[key] = f.get_tensor(key)
    params = convert_state_dict(cfg, sd)
    if shardings is not None:
        params = jax.tree.map(jax.device_put, params, shardings)
    return params
