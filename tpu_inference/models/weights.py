"""HF checkpoint -> jax parameter pytree conversion.

Two entry points:
- ``convert_state_dict(cfg, state_dict)``: torch/numpy state dict (HF naming)
  -> this framework's stacked-layer pytree. Used by parity tests (random HF
  model in-process) and by the safetensors loader.
- ``load_checkpoint(cfg, path, shardings=None)``: read a HF safetensors
  directory and place arrays directly onto devices, optionally with
  ``NamedSharding`` per leaf so a 70B model streams straight into its TP
  layout without materializing on one host (SURVEY.md §5 "Checkpoint/resume").

HF linear weights are [out, in]; this framework stores [in, out] so forward
passes are plain ``x @ w`` row-major matmuls. GPT-2's Conv1D is already
[in, out] and is not transposed.
"""

from __future__ import annotations

import json
import os
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_inference.config import ModelConfig


def _np(x: Any) -> np.ndarray:
    """torch tensor | np array -> np array (no torch import required here)."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def _stack(state: Dict[str, Any], fmt: str, n_layers: int,
           transpose: bool = False) -> np.ndarray:
    mats = [_np(state[fmt.format(i)]) for i in range(n_layers)]
    if transpose:
        mats = [m.T for m in mats]
    return np.stack(mats)


# Phi-3 checkpoints store q/k/v (and gate/up) fused along the out dim;
# the serving layout keeps them split so the TP sharding and quantization
# paths are identical across the llama family. One span definition feeds
# both the eager converter and the streaming planner — they must split at
# identical row offsets.
_FUSED_QKV_KEY = "self_attn.qkv_proj.weight"
_FUSED_GATE_UP_KEY = "mlp.gate_up_proj.weight"


def _fused_qkv_spans(cfg: ModelConfig) -> tuple:
    """(q_end, k_end, v_end) row offsets inside the fused qkv tensor."""
    q_end = cfg.n_heads * cfg.head_dim
    k_end = q_end + cfg.n_kv_heads * cfg.head_dim
    return q_end, k_end, k_end + cfg.n_kv_heads * cfg.head_dim


def convert_llama(cfg: ModelConfig, sd: Dict[str, Any]) -> dict:
    L = cfg.n_layers
    p = "model.layers.{}."
    fused = p.format(0) + _FUSED_QKV_KEY in sd
    if fused:
        f = cfg.d_ff
        q_end, k_end, _ = _fused_qkv_spans(cfg)
        qkv = _stack(sd, p + _FUSED_QKV_KEY, L, transpose=True)
        gu = _stack(sd, p + _FUSED_GATE_UP_KEY, L, transpose=True)
        attn_ffn = {
            "wq": qkv[..., :q_end], "wk": qkv[..., q_end:k_end],
            "wv": qkv[..., k_end:],
            "w_gate": gu[..., :f], "w_up": gu[..., f:],
        }
    else:
        attn_ffn = {
            "wq": _stack(sd, p + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, p + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, p + "self_attn.v_proj.weight", L, transpose=True),
            "w_gate": _stack(sd, p + "mlp.gate_proj.weight", L, transpose=True),
            "w_up": _stack(sd, p + "mlp.up_proj.weight", L, transpose=True),
        }
    params = {
        "embed": _np(sd["model.embed_tokens.weight"]),
        "blocks": {
            "attn_norm": _stack(sd, p + "input_layernorm.weight", L),
            "wo": _stack(sd, p + "self_attn.o_proj.weight", L, transpose=True),
            "ffn_norm": _stack(sd, p + "post_attention_layernorm.weight", L),
            "w_down": _stack(sd, p + "mlp.down_proj.weight", L, transpose=True),
            **attn_ffn,
        },
        "final_norm": _np(sd["model.norm.weight"]),
    }
    if cfg.qkv_bias:
        params["blocks"]["bq"] = _stack(sd, p + "self_attn.q_proj.bias", L)
        params["blocks"]["bk"] = _stack(sd, p + "self_attn.k_proj.bias", L)
        params["blocks"]["bv"] = _stack(sd, p + "self_attn.v_proj.bias", L)
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight", sd["model.embed_tokens.weight"])
        params["lm_head"] = _np(head).T
    return params


def convert_gpt2(cfg: ModelConfig, sd: Dict[str, Any]) -> dict:
    L = cfg.n_layers
    # HF prefixes keys with "transformer." on GPT2LMHeadModel state dicts.
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    p = pre + "h.{}."
    return {
        "embed": _np(sd[pre + "wte.weight"]),
        "pos_embed": _np(sd[pre + "wpe.weight"]),
        "blocks": {
            "ln1_w": _stack(sd, p + "ln_1.weight", L),
            "ln1_b": _stack(sd, p + "ln_1.bias", L),
            "w_qkv": _stack(sd, p + "attn.c_attn.weight", L),   # Conv1D: [in,out]
            "b_qkv": _stack(sd, p + "attn.c_attn.bias", L),
            "w_proj": _stack(sd, p + "attn.c_proj.weight", L),
            "b_proj": _stack(sd, p + "attn.c_proj.bias", L),
            "ln2_w": _stack(sd, p + "ln_2.weight", L),
            "ln2_b": _stack(sd, p + "ln_2.bias", L),
            "w_fc": _stack(sd, p + "mlp.c_fc.weight", L),
            "b_fc": _stack(sd, p + "mlp.c_fc.bias", L),
            "w_out": _stack(sd, p + "mlp.c_proj.weight", L),
            "b_out": _stack(sd, p + "mlp.c_proj.bias", L),
        },
        "ln_f_w": _np(sd[pre + "ln_f.weight"]),
        "ln_f_b": _np(sd[pre + "ln_f.bias"]),
    }


def convert_mixtral(cfg: ModelConfig, sd: Dict[str, Any]) -> dict:
    L, E = cfg.n_layers, cfg.n_experts
    p = "model.layers.{}."

    def stack_experts(w_name: str, transpose: bool) -> np.ndarray:
        layers = []
        for i in range(L):
            mats = [_np(sd[f"model.layers.{i}.block_sparse_moe.experts.{e}.{w_name}.weight"])
                    for e in range(E)]
            if transpose:
                mats = [m.T for m in mats]
            layers.append(np.stack(mats))
        return np.stack(layers)  # [L, E, ...]

    return {
        "embed": _np(sd["model.embed_tokens.weight"]),
        "blocks": {
            "attn_norm": _stack(sd, p + "input_layernorm.weight", L),
            "wq": _stack(sd, p + "self_attn.q_proj.weight", L, transpose=True),
            "wk": _stack(sd, p + "self_attn.k_proj.weight", L, transpose=True),
            "wv": _stack(sd, p + "self_attn.v_proj.weight", L, transpose=True),
            "wo": _stack(sd, p + "self_attn.o_proj.weight", L, transpose=True),
            "ffn_norm": _stack(sd, p + "post_attention_layernorm.weight", L),
            "w_router": _stack(sd, p + "block_sparse_moe.gate.weight", L,
                               transpose=True),
            # HF Mixtral: w1 = gate, w2 = down, w3 = up.
            "w_gate": stack_experts("w1", transpose=True),
            "w_up": stack_experts("w3", transpose=True),
            "w_down": stack_experts("w2", transpose=True),
        },
        "final_norm": _np(sd["model.norm.weight"]),
        "lm_head": _np(sd["lm_head.weight"]).T,
    }


_CONVERTERS = {"llama": convert_llama, "gpt2": convert_gpt2,
               "mixtral": convert_mixtral}


def convert_state_dict(cfg: ModelConfig, sd: Dict[str, Any]) -> dict:
    """HF state dict -> params pytree (np arrays cast to cfg.dtype)."""
    params = _CONVERTERS[cfg.family](cfg, sd)
    return jax.tree.map(lambda a: jnp.asarray(a, dtype=cfg.dtype), params)


def save_native(params: dict, path: str) -> None:
    """Serialize a params pytree with Orbax (sharded-aware, resumable).

    The TPU-native checkpoint tier (SURVEY.md §5 checkpoint/resume):
    HF safetensors are the interchange format; Orbax is the fast path
    for restart-after-failure, writing each shard from its owning host.
    """
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), params, force=True)


def load_native(path: str, template: dict) -> dict:
    """Restore an Orbax checkpoint. ``template`` is a pytree of arrays or
    jax.ShapeDtypeStruct (optionally with shardings) giving the target
    structure/placement — pass sharded abstract leaves to stream a 70B
    checkpoint straight into its TP layout."""
    import orbax.checkpoint as ocp

    abstract = jax.tree.map(
        lambda x: x if isinstance(x, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(x.shape, x.dtype,
                                  sharding=getattr(x, "sharding", None)),
        template)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(os.path.abspath(path), abstract)


def config_from_hf(path: str) -> ModelConfig:
    """Build a ModelConfig from a HF checkpoint directory's config.json.

    The reference's workflow is "point the server at a model and serve it"
    (Ollama pulls by name); the equivalent here is pointing at a local HF
    directory — architecture hyperparameters come from the checkpoint, not
    from a hand-maintained preset. Supports llama, mistral, qwen2, gemma,
    phi3 (all served by the llama module), mixtral and gpt2.
    """
    import jax.numpy as jnp

    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    model_type = hf.get("model_type", "llama")
    name = os.path.basename(os.path.normpath(path))
    torch_dtype = hf.get("torch_dtype", "bfloat16")
    dtype = {"bfloat16": jnp.bfloat16, "float16": jnp.bfloat16,
             "float32": jnp.float32}.get(torch_dtype, jnp.bfloat16)
    if torch_dtype not in ("bfloat16", "float32"):
        import sys
        print(f"[config_from_hf] {name}: torch_dtype={torch_dtype!r} served "
              "as bfloat16 (TPU-native; fp16 loses 2 mantissa bits — pass "
              "an explicit ModelConfig with dtype=float32 for a lossless "
              "load)", file=sys.stderr)
    if model_type == "gpt2":
        d = hf["n_embd"]
        return ModelConfig(
            name=name, family="gpt2", vocab_size=hf["vocab_size"],
            d_model=d, n_layers=hf["n_layer"], n_heads=hf["n_head"],
            n_kv_heads=hf["n_head"], d_ff=hf.get("n_inner") or 4 * d,
            max_seq_len=hf.get("n_positions", 1024),
            norm_eps=hf.get("layer_norm_epsilon", 1e-5),
            use_learned_pos=True, use_bias=True, tie_embeddings=True,
            dtype=dtype)
    if model_type not in ("llama", "mixtral", "mistral", "qwen2", "gemma",
                          "phi3"):
        raise ValueError(f"unsupported model_type {model_type!r} in "
                         f"{path}/config.json")
    if model_type == "phi3" and hf.get("rope_scaling"):
        # Phi-3 long-context variants (128k) use LongRoPE: two rescaled
        # rope frequency tables switched on context length — unsupported.
        # The 4k checkpoints carry rope_scaling: null and serve natively.
        raise ValueError(
            f"phi3 checkpoint {name!r} uses rope_scaling="
            f"{hf['rope_scaling'].get('type', hf['rope_scaling'])!r} "
            "(LongRoPE); only rope_scaling: null Phi-3 checkpoints (4k "
            "context) are supported")
    heads = hf["num_attention_heads"]
    gemma = model_type == "gemma"
    # Llama-3.1+ rescale rope frequencies per channel (rope_type
    # "llama3"); serving such a checkpoint without the rescale is a
    # different model, so it is parsed (not ignored) and unsupported
    # schemes (yarn, linear, dynamic) fail loudly.
    rope_scaling = None
    rs = hf.get("rope_scaling")
    if rs:
        from tpu_inference.config import RopeScaling
        kind = rs.get("rope_type", rs.get("type", "default"))
        if kind == "llama3":
            rope_scaling = RopeScaling(
                factor=float(rs["factor"]),
                low_freq_factor=float(rs["low_freq_factor"]),
                high_freq_factor=float(rs["high_freq_factor"]),
                original_max_len=int(rs["original_max_position_embeddings"]))
        elif kind != "default":
            raise ValueError(
                f"checkpoint {name!r} uses rope_scaling type {kind!r}; "
                "only 'llama3' (and null/'default') are supported")
    # Gemma checkpoints ("gelu"/"gelu_pytorch_tanh", both the tanh
    # approximation in practice) vs the SiLU dialects.
    act = "gelu_tanh" if gemma else "silu"
    # Qwen2 configs carry sliding_window but gate it behind
    # use_sliding_window (default false); Mistral windows unconditionally.
    if model_type in ("mistral", "phi3"):
        window = int(hf.get("sliding_window") or 0)
    elif model_type == "qwen2" and hf.get("use_sliding_window"):
        window = int(hf.get("sliding_window") or 0)
        # HF Qwen2 windows only layers >= max_window_layers (the first
        # max_window_layers layers keep full attention); the HF default
        # for an absent key is 28, NOT 0. The engine's window is global,
        # so only the all-or-nothing cases map:
        mwl = hf.get("max_window_layers")
        mwl = 28 if mwl is None else int(mwl)
        if mwl >= int(hf["num_hidden_layers"]):
            window = 0           # every layer is below the cutoff: full attn
        elif mwl != 0 and window:
            raise ValueError(
                f"qwen2 checkpoint {name!r} uses per-layer sliding window "
                f"(max_window_layers={mwl} of {hf['num_hidden_layers']}); "
                "mixed full/SWA layers are unsupported — set "
                "use_sliding_window=false to serve with full attention")
    else:
        window = 0
    return ModelConfig(
        name=name, family="mixtral" if model_type == "mixtral" else "llama",
        vocab_size=hf["vocab_size"], d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"], n_heads=heads,
        n_kv_heads=hf.get("num_key_value_heads", heads),
        d_ff=hf["intermediate_size"],
        max_seq_len=hf.get("max_position_embeddings", 8192),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rope_scaling=rope_scaling,
        norm_eps=hf.get("rms_norm_eps", 1e-5),
        tie_embeddings=bool(hf.get("tie_word_embeddings", gemma)),
        n_experts=hf.get("num_local_experts", 0),
        n_experts_per_tok=hf.get("num_experts_per_tok", 2),
        sliding_window=window,
        qkv_bias=model_type == "qwen2",
        norm_offset=1.0 if gemma else 0.0,
        hidden_act=act,
        embed_scale=gemma,
        # Honored whenever the checkpoint carries it (a no-op when it
        # equals d_model // n_heads): Gemma-7B and e.g. Mistral-Nemo
        # decouple head_dim from the hidden size.
        head_dim_override=int(hf.get("head_dim") or 0),
        dtype=dtype)


# ---------------------------------------------------------------------------
# Streaming safetensors loader.
#
# The naive path (read every shard into one host dict, convert, device_put)
# peaks at ~2x model size in host RAM and, with shardings, additionally
# materializes every leaf unsharded on device 0 before GSPMD resharding —
# a host-OOM at 70B. Instead each param leaf is described by a *plan*
# (which HF tensors it stacks, whether they transpose) and assembled
# through ``jax.make_array_from_callback``: JAX asks for exactly the
# index slab each local device owns, and the callback reads only that
# slab from the memory-mapped safetensors files. Host transient memory
# = one device's shard of one leaf; nothing unsharded ever materializes.
# ---------------------------------------------------------------------------


class _CheckpointFiles:
    """Key -> memory-mapped safetensors file mapping over a HF dir."""

    def __init__(self, path: str):
        from safetensors import safe_open  # deferred: optional dependency

        self._safe_open = safe_open
        self.path = path
        self._handles: Dict[str, Any] = {}
        self.key_to_file: Dict[str, str] = {}
        index_path = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index_path):
            with open(index_path) as f:
                self.key_to_file = json.load(f)["weight_map"]
        else:
            for fname in sorted(os.listdir(path)):
                if fname.endswith(".safetensors"):
                    with safe_open(os.path.join(path, fname),
                                   framework="np") as f:
                        for k in f.keys():
                            self.key_to_file[k] = fname

    def keys(self):
        return self.key_to_file.keys()

    def get_slice(self, key: str):
        fname = self.key_to_file[key]
        h = self._handles.get(fname)
        if h is None:
            h = self._safe_open(os.path.join(self.path, fname),
                                framework="np")
            self._handles[fname] = h
        return h.get_slice(key)


# A leaf plan is (keys, transpose[, rows]): ``keys`` is one HF tensor name,
# or a (nested) list of names stacked along leading axes (layers, then
# experts); ``transpose`` swaps the trailing 2 dims (HF Linear [out,in] ->
# [in,out]); optional ``rows = (start, stop)`` restricts the leaf to a row
# range of the HF tensor's out dim (dim 0 pre-transpose) — how Phi-3's
# fused qkv_proj / gate_up_proj split into separate param leaves without
# ever materializing the fused tensor.
_Plan = tuple


def _plan_llama(cfg: ModelConfig, have) -> dict:
    L = cfg.n_layers
    p = "model.layers.{}."

    def lk(s):
        return [p.format(i) + s for i in range(L)]

    if p.format(0) + _FUSED_QKV_KEY in have:
        # Phi-3 fused layout: each split leaf reads a row range of the
        # fused HF tensor (rows = out dim pre-transpose), so streaming
        # still touches only the bytes each device shard needs.
        f = cfg.d_ff
        q_end, k_end, v_end = _fused_qkv_spans(cfg)
        qkv, gu = lk(_FUSED_QKV_KEY), lk(_FUSED_GATE_UP_KEY)
        attn_ffn = {
            "wq": (qkv, True, (0, q_end)),
            "wk": (qkv, True, (q_end, k_end)),
            "wv": (qkv, True, (k_end, v_end)),
            "w_gate": (gu, True, (0, f)),
            "w_up": (gu, True, (f, 2 * f)),
        }
    else:
        attn_ffn = {
            "wq": (lk("self_attn.q_proj.weight"), True),
            "wk": (lk("self_attn.k_proj.weight"), True),
            "wv": (lk("self_attn.v_proj.weight"), True),
            "w_gate": (lk("mlp.gate_proj.weight"), True),
            "w_up": (lk("mlp.up_proj.weight"), True),
        }
    plan = {
        "embed": ("model.embed_tokens.weight", False),
        "blocks": {
            "attn_norm": (lk("input_layernorm.weight"), False),
            "wo": (lk("self_attn.o_proj.weight"), True),
            "ffn_norm": (lk("post_attention_layernorm.weight"), False),
            "w_down": (lk("mlp.down_proj.weight"), True),
            **attn_ffn,
        },
        "final_norm": ("model.norm.weight", False),
    }
    if cfg.qkv_bias:
        plan["blocks"]["bq"] = (lk("self_attn.q_proj.bias"), False)
        plan["blocks"]["bk"] = (lk("self_attn.k_proj.bias"), False)
        plan["blocks"]["bv"] = (lk("self_attn.v_proj.bias"), False)
    if not cfg.tie_embeddings:
        head = ("lm_head.weight" if "lm_head.weight" in have
                else "model.embed_tokens.weight")
        plan["lm_head"] = (head, True)
    return plan


def _plan_gpt2(cfg: ModelConfig, have) -> dict:
    L = cfg.n_layers
    pre = "transformer." if any(k.startswith("transformer.") for k in have) \
        else ""
    p = pre + "h.{}."

    def lk(s):
        return [p.format(i) + s for i in range(L)]

    return {
        "embed": (pre + "wte.weight", False),
        "pos_embed": (pre + "wpe.weight", False),
        "blocks": {
            "ln1_w": (lk("ln_1.weight"), False),
            "ln1_b": (lk("ln_1.bias"), False),
            "w_qkv": (lk("attn.c_attn.weight"), False),  # Conv1D: [in,out]
            "b_qkv": (lk("attn.c_attn.bias"), False),
            "w_proj": (lk("attn.c_proj.weight"), False),
            "b_proj": (lk("attn.c_proj.bias"), False),
            "ln2_w": (lk("ln_2.weight"), False),
            "ln2_b": (lk("ln_2.bias"), False),
            "w_fc": (lk("mlp.c_fc.weight"), False),
            "b_fc": (lk("mlp.c_fc.bias"), False),
            "w_out": (lk("mlp.c_proj.weight"), False),
            "b_out": (lk("mlp.c_proj.bias"), False),
        },
        "ln_f_w": (pre + "ln_f.weight", False),
        "ln_f_b": (pre + "ln_f.bias", False),
    }


def _plan_mixtral(cfg: ModelConfig, have) -> dict:
    L, E = cfg.n_layers, cfg.n_experts
    p = "model.layers.{}."

    def lk(s):
        return [p.format(i) + s for i in range(L)]

    def ek(w):
        # HF Mixtral: w1 = gate, w2 = down, w3 = up.
        return [[f"model.layers.{i}.block_sparse_moe.experts.{e}.{w}.weight"
                 for e in range(E)] for i in range(L)]

    return {
        "embed": ("model.embed_tokens.weight", False),
        "blocks": {
            "attn_norm": (lk("input_layernorm.weight"), False),
            "wq": (lk("self_attn.q_proj.weight"), True),
            "wk": (lk("self_attn.k_proj.weight"), True),
            "wv": (lk("self_attn.v_proj.weight"), True),
            "wo": (lk("self_attn.o_proj.weight"), True),
            "ffn_norm": (lk("post_attention_layernorm.weight"), False),
            "w_router": (lk("block_sparse_moe.gate.weight"), True),
            "w_gate": (ek("w1"), True),
            "w_up": (ek("w3"), True),
            "w_down": (ek("w2"), True),
        },
        "final_norm": ("model.norm.weight", False),
        "lm_head": ("lm_head.weight", True),
    }


_PLANNERS = {"llama": _plan_llama, "gpt2": _plan_gpt2,
             "mixtral": _plan_mixtral}


def _base_shape(files: _CheckpointFiles, keys, transpose: bool,
                rows=None) -> tuple:
    """Global shape of a leaf: stacked leading axes + (transposed) base."""
    stack = []
    while isinstance(keys, list):
        stack.append(len(keys))
        keys = keys[0]
    base = tuple(files.get_slice(keys).get_shape())
    if rows is not None:
        base = (rows[1] - rows[0],) + base[1:]
    if transpose:
        base = base[:-2] + (base[-1], base[-2])
    return tuple(stack) + base


def _read_slab(files: _CheckpointFiles, keys, transpose: bool,
               index: tuple, rows=None) -> np.ndarray:
    """Read the sub-array ``leaf[index]`` touching only the needed bytes."""
    if isinstance(keys, list):
        rng = range(len(keys))[index[0]]
        parts = [_read_slab(files, keys[i], transpose, index[1:], rows)
                 for i in rng]
        return np.stack(parts)
    sl = files.get_slice(keys)
    if transpose:
        index = index[:-2] + (index[-1], index[-2])
    if rows is not None:
        # index is in HF-tensor coordinates here (post transpose-swap);
        # shift its dim-0 slice into the fused tensor's row range.
        d0 = index[0]
        index = (slice(d0.start + rows[0], d0.stop + rows[0]),) + index[1:]
    out = np.asarray(sl[index])
    return out.swapaxes(-1, -2) if transpose else out


def load_checkpoint(cfg: ModelConfig, path: str,
                    shardings: Optional[dict] = None,
                    quant: str = "none") -> dict:
    """Load a HF safetensors directory into a (optionally sharded) pytree.

    ``shardings``: pytree matching the params structure with
    ``jax.sharding.Sharding`` leaves. Each leaf streams straight from the
    memory-mapped files into its device layout: with shardings, every chip
    reads only its own slab and no unsharded copy ever exists on host or
    device (the ADVICE r1 70B-host-OOM fix).

    ``quant="int8"`` quantizes each eligible matmul weight (QUANT_KEYS)
    on device immediately after it lands, before the next leaf streams
    in — peak device memory is the int8 model plus ONE full-precision
    leaf, so a model that only fits quantized can actually be loaded
    (quantizing after a full bf16 load would peak at bf16 + int8).
    """
    from tpu_inference.models.quant import QUANT_KEYS, quantize_array

    files = _CheckpointFiles(path)
    plan = _PLANNERS[cfg.family](cfg, set(files.keys()))
    dtype = cfg.dtype

    def build(tree_path, leaf_plan: _Plan, sharding=None):
        keys, transpose, *rest = leaf_plan
        rows = rest[0] if rest else None
        shape = _base_shape(files, keys, transpose, rows)
        full = tuple(slice(0, s) for s in shape)

        def read(index=full):
            index = tuple(slice(*i.indices(s)) for i, s in zip(index, shape))
            return _read_slab(files, keys, transpose, index,
                              rows).astype(dtype)

        if sharding is None:
            arr = jnp.asarray(read())
        else:
            arr = jax.make_array_from_callback(shape, sharding, read)
        name = tree_path[-1].key if tree_path else ""
        if quant != "none" and name in QUANT_KEYS:
            # The bf16 leaf becomes garbage as soon as this returns; its
            # device buffer frees before the next leaf materializes.
            return jax.jit(partial(quantize_array, mode=quant))(arr)
        return arr

    is_plan_leaf = lambda x: isinstance(x, tuple)  # noqa: E731
    if shardings is None:
        return jax.tree_util.tree_map_with_path(build, plan,
                                                is_leaf=is_plan_leaf)
    return jax.tree_util.tree_map_with_path(build, plan, shardings,
                                            is_leaf=is_plan_leaf)
