"""Weight-only int8/int4 quantization for the HBM-bound decode path.

TPU decode at serving batch sizes is bandwidth-bound: every step re-reads
the full weight set from HBM (BASELINE.md roofline), so storing matmul
weights as int8 with a per-output-channel scale halves weight traffic —
and int4 with group-wise scales halves it again. XLA folds the int->bf16
convert into the matmul fusion, so HBM sees one narrow read and the MXU
still runs a bf16 contraction against full-precision activations.

Design:
- ``QuantizedArray`` is a registered pytree dataclass ``{q, scale}``.
  int8: per-*output*-channel scale — the contraction dim (axis -2 of
  every weight in this codebase's [in, out] convention) is reduced to 1
  in ``scale``. int4: the contraction dim is split into groups of
  ``GROUP_SIZE`` and the scale is per (group, output channel) — 4-bit
  cells are too coarse for one whole-column scale (the GPTQ/AWQ
  group-quant recipe). Registered as a pytree node it survives
  ``lax.scan`` over stacked layer weights and tree-mapped sharding.
- int4 codes are stored PACKED, two per int8 byte along the contraction
  dim (rows 2i, 2i+1 -> low, high nibble). Sub-byte (S4) arrays never
  persist across a jit boundary: the axon TPU runtime's device_put
  re-layout of persistent S4 arrays recurses into jit (round-5 bench
  failure), and a packed byte array is the portable representation
  anyway. The arithmetic-shift unpack is elementwise and fuses into the
  matmul read; HBM still sees half of int8's weight bytes. Invariant:
  a grouped scale (G > 1) always pairs with packed codes.
- ``qdot`` / ``qeinsum`` are drop-in contraction helpers the model
  forwards call for every weight matmul; they accept plain arrays too, so
  quantization stays a load-time decision (EngineConfig.quant) rather
  than a model-code fork. The two paths are discriminated by the scale's
  group count alone: G == 1 scales the contraction *output* (exact
  because the scale is constant along the contracted axis), G > 1 runs a
  grouped contraction and folds the per-group partial sums.
- Under tensor parallelism GSPMD shards the grouped partials like any
  einsum; for G == 1 it may place the all-reduce before or after the
  scale — both are exact.

The reference has no quantization tier (it has no model code at all,
SURVEY.md §0); this implements the serving-side capability its external
Ollama endpoint provided (Ollama serves quantized GGUF models — the
reference's `mistral` was a 4-bit variant by default, which is exactly
the int4 tier here).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

QUANT_MODES = ("none", "int8", "int4")

# int4 group size along the contraction dim (GPTQ/AWQ-style). Contraction
# dims not divisible by it fall back to one group per column (exact for
# the tiny test models whose dims are below the group size anyway).
GROUP_SIZE = 128

# Params-tree leaf names eligible for quantization: the large matmul
# weights. Norm scales, biases, embeddings (gather tables), positional
# tables, and the MoE router (tiny, routing-precision-sensitive) stay in
# the model dtype.
QUANT_KEYS = frozenset({
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
    "w_qkv", "w_proj", "w_fc", "w_out",
})


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantizedArray:
    """Narrow-int weight + f32 scale.

    int8: scale [..., 1, out] (axis -2 reduced). int4: scale
    [..., G, out] with G groups along the contraction dim.
    """

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def size(self):
        return self.q.size

    @property
    def ndim(self):
        return self.q.ndim


def _contract_dtype(act_dtype):
    """Contraction dtype for the grouped (int4) paths. bf16 on TPU (MXU
    native); f32 elsewhere — XLA:CPU's batched-dot thunk cannot execute
    bf16 x bf16 -> f32 (the backend is static at trace time, so this is
    a compile-time constant, not a traced branch)."""
    if act_dtype == jnp.bfloat16 and jax.default_backend() != "tpu":
        return jnp.float32
    return act_dtype


def _groups_for(in_dim: int, mode: str) -> int:
    """Scale groups along the contraction dim for a quant mode."""
    if mode == "int8" or in_dim % GROUP_SIZE:
        return 1
    return in_dim // GROUP_SIZE


def pack_int4(codes: jax.Array) -> jax.Array:
    """int8 codes [..., in, out] (values in [-7, 7]) -> packed int8
    [..., in // 2, out]: row 2i in the low nibble, row 2i+1 in the high."""
    *lead, in_dim, out = codes.shape
    pairs = codes.reshape(*lead, in_dim // 2, 2, out)
    lo, hi = pairs[..., 0, :], pairs[..., 1, :]
    return (lo & jnp.int8(0x0F)) | (hi << 4)


def unpack_int4(packed: jax.Array) -> jax.Array:
    """Packed int8 [..., in // 2, out] -> sign-extended int8 codes
    [..., in, out]. Two arithmetic shifts per nibble — elementwise, so
    XLA fuses the unpack into the consuming matmul's operand read."""
    *lead, half, out = packed.shape
    lo = (packed << 4) >> 4                      # sign-extend low nibble
    hi = packed >> 4                             # arithmetic: sign-extends
    return jnp.stack([lo, hi], axis=-2).reshape(*lead, 2 * half, out)


def quantize_array(w: jax.Array, mode: str = "int8") -> QuantizedArray:
    """Symmetric narrow-int quantization along the contraction dim
    (axis -2): int8 per output channel, int4 per (group, channel).

    int4 with grouped scales returns PACKED codes (see module docstring);
    the no-group fallback (contraction dim not divisible by GROUP_SIZE —
    tiny test models) keeps one code per byte with a per-column scale,
    which the G == 1 contraction path handles exactly."""
    wf = w.astype(jnp.float32)
    if mode == "int4":
        in_dim, out = w.shape[-2], w.shape[-1]
        ngrp = _groups_for(in_dim, mode)
        wg = wf.reshape(w.shape[:-2] + (ngrp, in_dim // ngrp, out))
        amax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)
        scale = jnp.maximum(amax, 1e-8) / 7.0
        q = jnp.clip(jnp.round(wg / scale), -7, 7).astype(jnp.int8)
        q = q.reshape(w.shape)
        if ngrp > 1:
            q = pack_int4(q)
        return QuantizedArray(q=q, scale=scale[..., 0, :])  # [..., G, out]
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantizedArray(q=q, scale=scale)


def dequantize(w: QuantizedArray, dtype=jnp.float32) -> jax.Array:
    ngrp = w.scale.shape[-2]
    if ngrp == 1:
        return (w.q.astype(jnp.float32) * w.scale).astype(dtype)
    codes = unpack_int4(w.q)
    in_dim, out = codes.shape[-2], codes.shape[-1]
    wg = codes.reshape(codes.shape[:-2] + (ngrp, in_dim // ngrp, out))
    full = wg.astype(jnp.float32) * w.scale[..., :, None, :]
    return full.reshape(codes.shape).astype(dtype)


def qdot(x: jax.Array, w: Any) -> jax.Array:
    """``x @ w`` with f32 accumulation; w may be a QuantizedArray.

    x: [..., in]; w: [in, out] (or quantized). Returns f32 [..., out].
    """
    if isinstance(w, QuantizedArray):
        ngrp = w.scale.shape[-2]
        if ngrp == 1:
            y = jnp.dot(x, w.q.astype(x.dtype),
                        preferred_element_type=jnp.float32)
            return y * w.scale[..., 0, :]
        # Grouped (int4): unpack the nibble-packed codes (fuses into the
        # operand read), contract each group separately, fold the
        # per-group partials with their own scales. HBM still reads only
        # the packed 4-bit codes + the small scale table.
        codes = unpack_int4(w.q)
        gsz = codes.shape[-2] // ngrp
        ct = _contract_dtype(x.dtype)
        xg = x.reshape(x.shape[:-1] + (ngrp, gsz)).astype(ct)
        qg = codes.reshape(ngrp, gsz, codes.shape[-1]).astype(ct)
        y = jnp.einsum("...gi,gio->...go", xg, qg,
                       preferred_element_type=jnp.float32)
        return jnp.sum(y * w.scale, axis=-2)
    return jnp.dot(x, w, preferred_element_type=jnp.float32)


def qeinsum(eq: str, a: jax.Array, w: Any) -> jax.Array:
    """``einsum(eq, a, w)`` where w may be quantized.

    Valid for contractions whose output ends with w's output (last) axis
    and preserves w's leading batch axes (the MoE expert einsums
    'ecd,edf->ecf' and 'ecf,efd->ecd'): the [..., 1, out] scale then
    broadcasts against the result directly; grouped (int4) scales fold
    per-group partial contractions of the same two patterns.
    """
    if isinstance(w, QuantizedArray):
        ngrp = w.scale.shape[-2]
        if ngrp == 1:
            y = jnp.einsum(eq, a, w.q.astype(a.dtype),
                           preferred_element_type=jnp.float32)
            return y * w.scale
        assert eq in ("ecd,edf->ecf", "ecf,efd->ecd"), (
            f"grouped qeinsum supports the MoE expert contractions, "
            f"got {eq!r}")
        codes = unpack_int4(w.q)
        gsz = codes.shape[-2] // ngrp
        ct = _contract_dtype(a.dtype)
        a4 = a.reshape(a.shape[:-1] + (ngrp, gsz)).astype(ct)  # [E,C,G,g]
        q4 = codes.reshape(codes.shape[0], ngrp, gsz,
                           codes.shape[-1]).astype(ct)    # [E, G, g, out]
        y = jnp.einsum("ecgi,egio->egco", a4, q4,
                       preferred_element_type=jnp.float32)
        return jnp.sum(y * w.scale[:, :, None, :], axis=1)
    return jnp.einsum(eq, a, w, preferred_element_type=jnp.float32)


def quantize_params(params: dict, mode: str = "int8") -> dict:
    """Quantize the matmul weights of a params pytree (QUANT_KEYS leaves).

    Runs on device (jitted per distinct leaf shape); sharded inputs
    produce q/scale with layouts GSPMD derives from the input sharding —
    re-apply ``parallel.shardings.shard_params`` afterwards for the
    canonical placement.
    """
    if mode == "none":
        return params
    if mode not in QUANT_MODES:
        raise ValueError(f"unknown quant mode {mode!r}; one of {QUANT_MODES}")
    import functools
    quant_jit = jax.jit(functools.partial(quantize_array, mode=mode))

    def maybe_quant(path, leaf):
        last = path[-1]
        name = last.key if hasattr(last, "key") else str(last)
        if name in QUANT_KEYS:
            return quant_jit(leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(maybe_quant, params)


def init_quantized_params(model_cfg, seed: int = 0,
                          mode: str = "int8") -> dict:
    """Random init + quantize ONE LEAF AT A TIME.

    ``build_model`` then ``quantize_params`` peaks at the full
    model-dtype tree plus the quantized copy — an 8B-dims engine would
    OOM a 16 GB chip it comfortably serves int8. Here each QUANT_KEYS
    leaf is initialized and quantized inside a single jit (XLA frees the
    full-precision intermediate on exit), so peak device memory is
    ~quantized-model-sized plus one full-precision leaf.

    Leaf VALUES differ from build_model's (independent per-leaf keys);
    random-init weights carry no meaning, so only shapes, dtypes, and
    determinism-per-seed matter. Norm-scale leaves are ones (as in every
    family's init_params); everything else draws the same 0.02-std
    normal.
    """
    if mode == "none":
        raise ValueError("init_quantized_params needs a quant mode; use "
                         "build_model for full-precision init")
    if mode not in QUANT_MODES:
        raise ValueError(f"unknown quant mode {mode!r}; one of {QUANT_MODES}")
    from tpu_inference.models.registry import get_model_fns

    mod = get_model_fns(model_cfg)
    shapes = jax.eval_shape(
        lambda k: mod.init_params(model_cfg, k), jax.random.PRNGKey(0))
    leaves, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    key = jax.random.PRNGKey(seed)

    def name_of(path):
        last = path[-1]
        return last.key if hasattr(last, "key") else str(last)

    out = []
    for path, sds in leaves:
        name = name_of(path)
        key, sub = jax.random.split(key)
        if name in QUANT_KEYS:
            out.append(jax.jit(
                lambda k, s=sds: quantize_array(
                    (0.02 * jax.random.normal(k, s.shape, jnp.float32)
                     ).astype(s.dtype), mode))(sub))
        elif "norm" in name:
            out.append(jnp.ones(sds.shape, sds.dtype))
        else:
            out.append(jax.jit(
                lambda k, s=sds: (0.02 * jax.random.normal(
                    k, s.shape, jnp.float32)).astype(s.dtype))(sub))
    return jax.tree_util.tree_unflatten(treedef, out)
