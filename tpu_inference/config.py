"""Typed configuration for models, engine, parallelism, and server.

The reference framework configures itself with a module-level dict literal
(reference: traffic_generator/main.py:302-313) and three module constants
(main.py:298-300). Here configuration is typed dataclasses; the harness-facing
dict keys (`url`, `model`, `temperature`, `max_tokens`, `trace_path`,
`data_path`, `max_trace`, `log_path`) are preserved by the client harness in
`traffic_generator/` so existing configs keep working.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Llama-3.1 "llama3" rope frequency rescale (static, per-channel).

    Long-wavelength channels (wavelen > original_max_len /
    low_freq_factor) divide their frequency by ``factor``; short ones
    keep it; the band between interpolates smoothly. Position-independent,
    so it folds into the inverse-frequency table
    (models/common.py rope_frequencies).
    """

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_len: int = 8192


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters for a decoder-only transformer.

    Covers Llama-style (RMSNorm/RoPE/GQA/SwiGLU), Mixtral (adds MoE fields)
    and GPT-2 (LayerNorm/learned-positional/GELU) families.
    """

    name: str = "llama"
    family: str = "llama"  # "llama" | "mixtral" | "gpt2"
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    # Llama-3.1+ checkpoints rescale rope frequencies (rope_type
    # "llama3" in HF config.json); None = vanilla rope.
    rope_scaling: Optional[RopeScaling] = None
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    # MoE (Mixtral family); n_experts == 0 means dense FFN.
    n_experts: int = 0
    n_experts_per_tok: int = 2
    # Static per-expert token capacity = ceil(k*T/E * factor); overflow drops.
    expert_capacity_factor: float = 2.0
    # Sliding-window attention (Mistral): each token attends to itself
    # and the window-1 tokens before it. 0 = full causal attention.
    # Decode runs on the window-aware Pallas kernel (O(window) page
    # reads); prefill uses the window-masked dense path. sp>1 prefill
    # doesn't window yet (engine.__init__ guards).
    sliding_window: int = 0
    # GPT-2 family uses learned positional embeddings + LayerNorm with bias.
    use_learned_pos: bool = False
    use_bias: bool = False
    # Llama-family dialect knobs (all default to vanilla Llama):
    # Qwen2 puts bias terms on the q/k/v projections only.
    qkv_bias: bool = False
    # Gemma stores RMSNorm weights as offsets from 1: y = normed * (o + w).
    # Applied in float32 inside the norm so 1+w never rounds through bf16.
    norm_offset: float = 0.0
    # FFN gate activation: "silu" (Llama/Qwen) | "gelu_tanh" (Gemma).
    hidden_act: str = "silu"
    # Gemma scales token embeddings by sqrt(d_model) (cast to cfg.dtype,
    # matching HF's rounded normalizer) before the first block.
    embed_scale: bool = False
    # Gemma-7B decouples head_dim from d_model/n_heads (3072/16 heads but
    # head_dim 256). 0 = derive from d_model // n_heads.
    head_dim_override: int = 0
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.d_model // self.n_heads

    @property
    def n_rep(self) -> int:
        """Query heads per KV head (GQA group size)."""
        return self.n_heads // self.n_kv_heads

    def validate(self) -> None:
        if not self.head_dim_override:
            assert self.d_model % self.n_heads == 0
        assert self.n_heads % self.n_kv_heads == 0
        if self.n_experts:
            assert self.n_experts_per_tok <= self.n_experts


# ---------------------------------------------------------------------------
# Presets. Tiny variants are for tests (random init, CPU-mesh friendly).
# ---------------------------------------------------------------------------

def llama3_8b() -> ModelConfig:
    return ModelConfig(
        name="llama-3-8b", family="llama", vocab_size=128256, d_model=4096,
        n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336,
        max_seq_len=8192, rope_theta=500000.0,
    )


def llama3_70b() -> ModelConfig:
    return ModelConfig(
        name="llama-3-70b", family="llama", vocab_size=128256, d_model=8192,
        n_layers=80, n_heads=64, n_kv_heads=8, d_ff=28672,
        max_seq_len=8192, rope_theta=500000.0,
    )


def llama31_8b() -> ModelConfig:
    """Llama-3.1-8B: 3.0 dims + the "llama3" rope rescale that extends
    context to 128k (rope_scaling in HF config.json, parsed by
    weights.config_from_hf)."""
    return ModelConfig(
        name="llama-3.1-8b", family="llama", vocab_size=128256, d_model=4096,
        n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336,
        max_seq_len=131072, rope_theta=500000.0, rope_scaling=RopeScaling(),
    )


def mixtral_8x7b() -> ModelConfig:
    return ModelConfig(
        name="mixtral-8x7b", family="mixtral", vocab_size=32000, d_model=4096,
        n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336,
        max_seq_len=8192, rope_theta=1000000.0, n_experts=8,
        n_experts_per_tok=2,
    )


def mistral_7b() -> ModelConfig:
    """Mistral-7B-v0.1 — the model the reference's Ollama endpoint
    actually served (reference: traffic_generator/main.py:308 config
    'model': 'mistral'). Its signature sliding window flows through the
    window-aware serving path (dense mask + windowed Pallas kernels +
    behind-window page eviction)."""
    return ModelConfig(
        name="mistral-7b", family="llama", vocab_size=32000, d_model=4096,
        n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336,
        max_seq_len=8192, rope_theta=10000.0, sliding_window=4096,
    )


def qwen2_7b() -> ModelConfig:
    """Qwen2-7B: Llama-shaped with bias on the q/k/v projections and a
    1M rope base. Loads from HF ``model_type: qwen2`` checkpoints
    (weights.config_from_hf)."""
    return ModelConfig(
        name="qwen2-7b", family="llama", vocab_size=152064, d_model=3584,
        n_layers=28, n_heads=28, n_kv_heads=4, d_ff=18944,
        max_seq_len=8192, rope_theta=1000000.0, norm_eps=1e-6,
        qkv_bias=True,
    )


def phi3_mini() -> ModelConfig:
    """Phi-3-mini-4k: Llama-shaped MHA (32 heads, no GQA) with a
    2047-token sliding window. HF checkpoints store fused qkv_proj /
    gate_up_proj tensors; the loader splits them at read time
    (models/weights.py fused-plan branch) so TP sharding and quantization
    see the standard llama layout."""
    return ModelConfig(
        name="phi-3-mini", family="llama", vocab_size=32064, d_model=3072,
        n_layers=32, n_heads=32, n_kv_heads=32, d_ff=8192,
        max_seq_len=4096, rope_theta=10000.0, sliding_window=2047,
    )


def gemma_7b() -> ModelConfig:
    """Gemma-7B: RMSNorm offset (+1), GeGLU FFN, sqrt(d)-scaled embeddings,
    tied unembedding, and head_dim 256 decoupled from d_model/n_heads."""
    return ModelConfig(
        name="gemma-7b", family="llama", vocab_size=256000, d_model=3072,
        n_layers=28, n_heads=16, n_kv_heads=16, d_ff=24576,
        max_seq_len=8192, rope_theta=10000.0, norm_eps=1e-6,
        tie_embeddings=True, norm_offset=1.0, hidden_act="gelu_tanh",
        embed_scale=True, head_dim_override=256,
    )


def gpt2_small() -> ModelConfig:
    return ModelConfig(
        name="gpt2", family="gpt2", vocab_size=50257, d_model=768,
        n_layers=12, n_heads=12, n_kv_heads=12, d_ff=3072,
        max_seq_len=1024, norm_eps=1e-5, use_learned_pos=True, use_bias=True,
        tie_embeddings=True,
    )


def tiny_llama(vocab_size: int = 512) -> ModelConfig:
    """Small Llama for unit tests; dims chosen TPU-tile friendly."""
    return ModelConfig(
        name="tiny-llama", family="llama", vocab_size=vocab_size, d_model=128,
        n_layers=2, n_heads=4, n_kv_heads=2, d_ff=256, max_seq_len=1024,
        rope_theta=10000.0, dtype=jnp.float32,
    )


def tiny_llama_fatkv(vocab_size: int = 512) -> ModelConfig:
    """tiny_llama with a production-shaped KV:compute ratio. The stock
    tiny models carry ~1 KiB of KV per token — two orders of magnitude
    leaner than a real 8B (32 layers x 8 KV heads x 128 dims), which
    makes any KV *data-plane* measurement on them fixed-cost bound.
    Four MHA layers at head_dim 64 put 16 KiB of f32 KV behind every
    token, so handoff/migration payloads reach realistic MiB scale at
    prompt lengths a CPU lane can still prefill in well under a
    second. Unit-scale weights otherwise (d_model 128, d_ff 256)."""
    return ModelConfig(
        name="tiny-llama-fatkv", family="llama", vocab_size=vocab_size,
        d_model=128, n_layers=4, n_heads=8, n_kv_heads=8, d_ff=256,
        max_seq_len=1024, rope_theta=10000.0, head_dim_override=64,
        dtype=jnp.float32,
    )


def tiny_mixtral(vocab_size: int = 512) -> ModelConfig:
    return ModelConfig(
        name="tiny-mixtral", family="mixtral", vocab_size=vocab_size,
        d_model=128, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=256,
        max_seq_len=1024, rope_theta=10000.0, n_experts=4,
        n_experts_per_tok=2, dtype=jnp.float32,
    )


def tiny_mistral(vocab_size: int = 512) -> ModelConfig:
    """Small Mistral-style model (tiny_llama + sliding window): exercises
    the full SWA serving path — windowed masks/kernels, behind-window
    eviction, SWA x sp composition — without a checkpoint."""
    return dataclasses.replace(tiny_llama(vocab_size), name="tiny-mistral",
                               sliding_window=64)


def tiny_qwen2(vocab_size: int = 512) -> ModelConfig:
    """tiny_llama + qkv bias (the Qwen2 dialect) for unit tests."""
    return dataclasses.replace(tiny_llama(vocab_size), name="tiny-qwen2",
                               qkv_bias=True)


def tiny_gemma(vocab_size: int = 512) -> ModelConfig:
    """Small Gemma exercising every dialect knob, including a head_dim
    (48) decoupled from d_model/n_heads (128/4 = 32)."""
    return ModelConfig(
        name="tiny-gemma", family="llama", vocab_size=vocab_size, d_model=128,
        n_layers=2, n_heads=4, n_kv_heads=2, d_ff=256, max_seq_len=1024,
        rope_theta=10000.0, norm_eps=1e-6, tie_embeddings=True,
        norm_offset=1.0, hidden_act="gelu_tanh", embed_scale=True,
        head_dim_override=48, dtype=jnp.float32,
    )


def tiny_phi3(vocab_size: int = 512) -> ModelConfig:
    """tiny_llama + a binding sliding window; loads from fused-projection
    (phi3-style) checkpoints via the fused-plan branch in weights.py."""
    return dataclasses.replace(tiny_llama(vocab_size), name="tiny-phi3",
                               sliding_window=8)


def tiny_gpt2(vocab_size: int = 512) -> ModelConfig:
    return ModelConfig(
        name="tiny-gpt2", family="gpt2", vocab_size=vocab_size, d_model=128,
        n_layers=2, n_heads=4, n_kv_heads=4, d_ff=256, max_seq_len=512,
        use_learned_pos=True, use_bias=True, tie_embeddings=True,
        dtype=jnp.float32,
    )


PRESETS = {
    "llama-3-8b": llama3_8b,
    "llama-3.1-8b": llama31_8b,
    "llama-3-70b": llama3_70b,
    "mixtral-8x7b": mixtral_8x7b,
    "mistral-7b": mistral_7b,
    "qwen2-7b": qwen2_7b,
    "gemma-7b": gemma_7b,
    "phi-3-mini": phi3_mini,
    "gpt2": gpt2_small,
    "tiny-llama": tiny_llama,
    "tiny-llama-fatkv": tiny_llama_fatkv,
    "tiny-qwen2": tiny_qwen2,
    "tiny-gemma": tiny_gemma,
    "tiny-mixtral": tiny_mixtral,
    "tiny-mistral": tiny_mistral,
    "tiny-phi3": tiny_phi3,
    "tiny-gpt2": tiny_gpt2,
}


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Device-mesh axes. Axis size 1 disables that axis.

    The mesh is (dp, tp, sp). TP shards attention heads and FFN hidden dim
    with XLA all-reduce over ICI; EP (Mixtral) reuses the tp axis for experts
    (parallel/shardings.py). SP shards the sequence dim for ring-attention
    prefill. The server builds a mesh from this config when n_devices > 1
    (server/http.py InferenceServer.__init__). dp > 1 is replica-per-group
    serving: each replica owns a tp*sp submesh, KV pool and scheduler,
    behind either fleet backend (ServerConfig.fleet) — "in-process"
    threads in one process (server/replicas.py EngineGroup) or
    "subprocess" engine-worker OS processes supervised by a router
    (server/fleet.py ProcessEngineGroup).
    """

    dp: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.tp * self.sp


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Serving-engine knobs: paging, batching, bucketing."""

    # Paged KV cache.
    page_size: int = 16               # tokens per KV page
    num_pages: int = 512              # pool size (per chip, per model)
    max_pages_per_seq: int = 64       # => max context = page_size * this
    # Continuous batching.
    max_batch_size: int = 8           # decode slots in the batched graph
    # Compiled decode-graph ladder (README "Batch ladder"): batch sizes
    # the decode graphs are compiled at, strictly increasing and ending
    # at max_batch_size. The engine dispatches at the smallest rung that
    # covers the occupied slots and moves between rungs as occupancy
    # changes, so a near-empty batch never pays the top rung's per-step
    # latency while a full one uses every HBM-budgeted lane. () = the
    # single legacy rung (max_batch_size,). The CLI's --max-batch-size
    # auto derives both the top rung (from the chip's HBM budget,
    # engine/autosize.py) and the ladder below it.
    decode_ladder: tuple[int, ...] = ()
    max_queue_len: int = 512
    # Prefill bucketing: prompt is right-padded up to the nearest bucket so
    # XLA compiles a bounded number of prefill graphs.
    prefill_buckets: tuple[int, ...] = (64, 128, 256, 512, 1024)
    chunked_prefill_size: int = 0     # 0 = whole-prompt prefill
    # Same-bucket single-chunk prefills batched into one [P, S] dispatch
    # (burst arrivals stop paying one serial forward each). Graphs are
    # compiled for P in {1, this}.
    max_prefill_batch: int = 4
    # Decode attention backend: "auto" picks the Pallas paged-attention
    # kernel (kernels/paged_attention.py) on real TPU and the dense
    # gather path elsewhere; "pallas"/"dense" force one.
    attn_backend: str = "auto"
    # Weight quantization: "int8" stores matmul weights as int8 with
    # per-output-channel scales (models/quant.py), halving the per-step
    # HBM weight traffic that bounds decode throughput. "none" = serve
    # in the model dtype.
    quant: str = "none"
    # KV-cache quantization: "int8" stores pool pages as int8 codes with
    # per-(token, kv-head) f32 scales (engine/kv_cache.py quantize_kv) —
    # halves KV HBM traffic AND doubles the context that fits in a pool
    # of the same byte size. "int4" nibble-packs codes (uint8 pool,
    # trailing dim D/2) for quarter traffic / 4x context at lower
    # fidelity (7 levels per half-range; int8 is the accuracy-safe
    # tier). Dequant is in-kernel (Pallas) or at gather (dense path).
    kv_quant: str = "none"
    # Sequence-parallel prefill algorithm on an sp>1 mesh: "ring"
    # (ppermute K/V rotation, O((S/n)^2) memory — the long-context
    # default) or "ulysses" (two all-to-alls, full-sequence attention
    # per head group — fewer collective hops, balanced causal load;
    # needs head counts divisible by sp after tp sharding).
    sp_attn: str = "ring"
    # Device-side decode steps fused per host call (lax.scan): each host
    # round trip costs ~dispatch latency, so K steps per call multiply
    # steady-state decode throughput by up to K. Streamed tokens are
    # flushed every K steps (latency cost: K * per-step time).
    decode_steps_per_call: int = 8
    # Latency mode: when at most this many sequences are decoding (and
    # nothing is queued or in flight), the scheduler switches to the
    # single-step decode graph so every token streams out as it is
    # sampled — a lone interactive chat gets per-token streaming while
    # loaded batches keep the fused-K throughput path. 0 disables.
    latency_decode_threshold: int = 1
    # Decode dispatch pipeline depth: >1 keeps that many fused-decode
    # calls in flight (later calls consume earlier calls' device-resident
    # carry tokens), hiding host round-trip/dispatch latency behind
    # device compute. Costs up to (depth-1)*K extra speculative steps
    # for lanes that stop mid-flight (their tokens are discarded) and
    # adds (depth-1)*K steps of streaming latency. 1 = fully synchronous.
    decode_pipeline_depth: int = 1
    # Hybrid prefill-decode steps (Sarathi-Serve-style chunked-prefill
    # piggybacking): while a multi-chunk prompt prefills, each chunk is
    # FUSED into the same device dispatch as the batch's K decode steps,
    # so running lanes keep producing tokens instead of stalling a full
    # chunk wall per chunk. Safe because the chunk and the decode lanes
    # touch disjoint KV pages (each sequence reads/writes only its own
    # block table). Off by default; no effect on single-chunk prompts
    # (they still batch-admit through prefill_many) or under speculative
    # decoding (the spec round has its own fused graph).
    hybrid_prefill: bool = False
    # Per-hybrid-step token budget: chunk tokens are capped at
    # step_token_budget minus the decode tokens granted for that
    # dispatch (floored at page_size so the prefill always advances),
    # bounding how much prefill compute any one fused step adds on top
    # of the decode work —
    # the knob that trades TTFT of the long prompt against inter-token
    # latency of everyone else. 0 = uncapped (chunked_prefill_size /
    # largest bucket governs, as in serial chunking).
    step_token_budget: int = 0
    # Sampling defaults (overridable per request).
    temperature: float = 0.0          # 0 => greedy
    top_k: int = 0                    # 0 => disabled
    top_p: float = 1.0
    max_new_tokens: int = 1024
    # Speculative decoding (0 = off). γ = drafted tokens per round; each
    # verified round emits 1..γ+1 tokens from ONE target forward.
    num_speculative_tokens: int = 0
    # Proposal source (README "Speculative decoding"):
    # - "draft": a separate draft model scans γ steps then the target
    #   verifies (needs a trained draft + its own KV pool; the classic
    #   Leviathan et al. 2023 arrangement).
    # - "ngram": draft-free self-drafting (prompt lookup, Saxena 2023) —
    #   the host matches the sequence's last tokens against its own
    #   prompt+generated history and proposes the continuation of the
    #   most recent match as one-hot drafts; the verify-only round keeps
    #   exact greedy argmax-match acceptance and distribution-exact
    #   sampled acceptance. No draft model, no draft KV, no extra HBM —
    #   so the decode ladder, host KV tier, SWA eviction and the
    #   repetition penalty all stay active (unlike "draft" mode).
    spec_mode: str = "draft"
    # ngram mode: longest suffix n-gram matched against the history
    # (matching tries window..1 and takes the most recent match).
    ngram_window: int = 3
    # ngram mode: per-sequence acceptance-rate EWMA update weight (a
    # fresh echo-free stream throttles after ~2 rejected rounds; an
    # echoic one un-throttles after ~1-2 accepted probe rounds).
    spec_ewma_alpha: float = 0.4
    # ngram mode: a sequence whose acceptance EWMA falls below this is
    # throttled to γ=0 (no proposals; rounds where NO slot proposes run
    # the plain fused-K decode graph instead) so speculation can never
    # lose on echo-free streams. At the defaults a fresh stream
    # throttles after ONE fully-rejected round (0.5 -> 0.3) while an
    # established echoic stream (EWMA near 1) tolerates transient
    # misses; un-throttling needs one clean probe. 0 disables.
    spec_throttle_below: float = 0.35
    # ngram mode: a throttled sequence re-probes (one narrow γ=1 verify
    # round) after this many rounds, so a stream that turns echoic
    # mid-generation can re-earn its γ. Consecutive failed probes back
    # off (doubling, capped at 8x) and the engine aligns every
    # throttled lane onto the same probe round, so echo-free streams
    # spend a vanishing fraction of rounds probing.
    spec_probe_every: int = 48
    # Prefix caching: finished sequences publish their full KV pages for
    # reuse by later requests sharing the prefix (multi-turn chats).
    enable_prefix_cache: bool = True
    # Host-RAM KV tier (README "Tiered KV cache"): evicted prefix-cache
    # pages demote to host memory (up to this many pages) instead of
    # being dropped, and promote back into freshly allocated device
    # pages when a returning prompt — or a preempted sequence's
    # swap-in-resume — needs them. 0 disables the tier (classic
    # free-on-evict). The CLI accepts ``--host-cache-pages auto`` to
    # size from the machine's available RAM (engine/autosize.py).
    host_cache_pages: int = 0
    # --- Admission control (README "Admission & preemption") ---
    # "reserve": a request is admitted only when the pool can hold its
    # prompt plus its FULL max_new_tokens budget — OOM-free by
    # construction, but BurstGPT-style traffic (generations finishing
    # far short of their budget) strands a large fraction of the pool
    # and sheds load while pages are actually free.
    # "optimistic": admit against the prompt footprint plus a small
    # decode headroom; KV exhaustion is handled by preempting the most
    # recently admitted sequence(s) and recompute-resuming them
    # (re-prefill over prompt+generated, token-identical under greedy
    # decoding) instead of rejecting or failing.
    admission: str = "reserve"
    # Optimistic mode: decode-headroom pages charged per request at
    # admission on top of its prompt pages.
    optimistic_headroom_pages: int = 2
    # Low watermark on free+evictable pages: when a decode grant comes
    # up short AND the pool is below this, the engine preempts victims
    # (most recently admitted first) instead of degrading to a stall.
    preempt_watermark_pages: int = 4
    # Starvation guard: after this many preemptions a request is
    # re-admitted under the full worst-case reservation (and is never
    # chosen as a victim again), so it provably finishes.
    preempt_max_per_request: int = 3
    # Fault injection: hold this many real pages out of the pool at
    # engine boot (runtime-adjustable via engine.set_page_pressure /
    # POST /debug/chaos {"page_pressure": n}) so pool-exhaustion paths
    # run deterministically on CPU. Off in production.
    chaos_page_pressure: int = 0
    # Engine-level fault injection (the engine-side counterpart of
    # ServerConfig.chaos_*): every prefill/decode dispatch raises with
    # this probability, exercising the scheduler error paths and the
    # replica health machine deterministically on CPU. Works under both
    # fleet backends (per-worker via the chaos RPC in "subprocess" mode;
    # kill -9 / SIGTERM-drain chaos for real process faults lives in the
    # fleet layer — POST /debug/chaos {"kill": ...}). Off in production.
    chaos_step_failure_rate: float = 0.0
    # Each dispatch sleeps this long first, simulating the documented TPU
    # wedge failure mode (the step watchdog detects it in either fleet
    # backend; with --fleet subprocess the wedge is confined to one
    # worker process instead of sharing the router's GIL).
    chaos_step_wedge_s: float = 0.0
    # Reuse the decode-step host staging arrays (block tables, sampling
    # params) across dispatches, refreshing only the rows whose occupant
    # or pages changed, instead of rebuilding every array per dispatch —
    # shrinks the host-side bubble between decode calls. False = legacy
    # rebuild-per-dispatch (the bubble comparison arm of the ladder
    # artifact). Output-invariant either way.
    stage_host_reuse: bool = True
    # Batch-ladder admission headroom: once the bound lanes would exceed
    # the ladder's BASE rung, a further admission must leave this many
    # reclaimable (free + evictable) pages spare — growing the batch
    # toward the top rung must not drain the pool to the preemption
    # watermark or force decode grants to churn the whole hot set
    # (with a host tier the churn demotes instead of destroying; the
    # headroom keeps it off the steady-state path either way). 0 = off
    # (legacy admission gate only).
    ladder_admit_headroom_pages: int = 0
    # Rolling SLO targets (README "Observability": SLO gauges; CLI
    # --slo-ttft-ms / --slo-tpot-ms). Each finished request's TTFT and
    # TPOT feed exact windowed quantile gauges
    # (tpu_inf_slo_ttft_seconds{q=...} / tpu_inf_slo_tpot_seconds{q=...})
    # regardless; with a non-zero target, requests past it additionally
    # count into tpu_inf_slo_breaches_total{slo=...} — the signal an
    # SLO-driven autoscaler scales on. 0 = no target.
    slo_ttft_ms: float = 0.0
    slo_tpot_ms: float = 0.0
    # Step ledger depth (README "Performance attribution"): how many
    # per-dispatch records the roofline-attribution ring retains. Each
    # record is one small tuple, so deeper rings cost only memory; 60 s
    # of bs=8 decode at ~10 ms/dispatch is ~6000 records.
    step_ledger_depth: int = 256
    # Worker phase role (README "P/D disaggregation"): "mixed" runs both
    # phases (the compatibility default — every pre-P/D topology);
    # "prefill" serves prompt prefills only and HANDS each settled
    # prefill off (KV pages incl. the partial final page + stream state)
    # to a decode worker, so warmup compiles only the prefill buckets;
    # "decode" resumes handed-off sequences and decodes at high
    # occupancy with zero prefill interference, so warmup compiles only
    # the decode ladder (and spec-verify) graphs. The role specializes
    # WARMUP and scheduling intent, not capability — a degraded fleet
    # can still run the other phase (lazy compile) so failover never
    # strands a request. Per-worker roles come from
    # ServerConfig.worker_roles; this field is what one engine sees.
    role: str = "mixed"

    @property
    def max_context(self) -> int:
        return self.page_size * self.max_pages_per_seq

    @property
    def ladder_rungs(self) -> tuple:
        """The decode-graph ladder actually in effect: ``decode_ladder``
        or the single legacy rung. Validated by the engine at boot."""
        return tuple(self.decode_ladder) or (self.max_batch_size,)

    @property
    def chunk_tokens_cap(self) -> int:
        """Effective chunk length for multi-chunk prefills:
        ``chunked_prefill_size`` clamped to the largest compiled bucket —
        a larger value would slice chunks no prefill graph can hold
        (the [1, bucket] token buffer raises on assignment). 0 means the
        largest bucket governs."""
        cap = self.chunked_prefill_size or self.prefill_buckets[-1]
        return min(cap, self.prefill_buckets[-1])

    def bucket_for(self, length: int) -> int:
        for b in self.prefill_buckets:
            if length <= b:
                return b
        return self.prefill_buckets[-1]


def validate_spec_config(spec_mode: str, num_speculative_tokens: int,
                         ngram_window: int,
                         has_draft_model: bool) -> None:
    """Speculative-decoding knob validation shared by the engine and the
    CLIs (server + replay), so a bad combination fails as a usage error
    before any weights load.

    Raises ValueError; messages mention the flag spelling so argparse
    surfaces actionable errors."""
    if spec_mode not in ("draft", "ngram"):
        raise ValueError(f"--spec-mode {spec_mode!r}: one of "
                         "('draft', 'ngram')")
    if spec_mode == "ngram" and has_draft_model:
        raise ValueError(
            "--spec-mode ngram does not take --draft-model: n-gram "
            "self-drafting proposes from the sequence's own history "
            "(drop the draft model, or use --spec-mode draft)")
    if num_speculative_tokens > 0 or spec_mode == "ngram":
        if not (1 <= num_speculative_tokens <= 16):
            raise ValueError(
                f"--num-speculative-tokens {num_speculative_tokens}: "
                "must be in [1, 16] when speculative decoding is on "
                "(γ drafts verify in one γ+1-position forward; huge γ "
                "only compiles wider graphs to reject more)")
    if spec_mode == "ngram" and not (1 <= ngram_window <= 8):
        raise ValueError(
            f"--ngram-window {ngram_window}: must be in [1, 8] "
            "(longest suffix n-gram matched against the history)")


# Worker phase roles (README "P/D disaggregation").
WORKER_ROLES = ("prefill", "decode", "mixed")

# Request priority classes (README "Elastic fleet"), best-first. Admission
# and scheduling order by rank; preemption steals from the worst rank up.
PRIORITY_CLASSES = ("interactive", "batch", "background")


def class_rank(priority_class: str) -> int:
    """Scheduling rank of a class (0 = most latency-sensitive). Unknown
    names rank as interactive so a typo'd header can never starve a
    request — validation with a 400 belongs at the HTTP edge."""
    try:
        return PRIORITY_CLASSES.index(priority_class)
    except ValueError:
        return 0


def resolve_worker_roles(dp: int, worker_roles, default_role: str = "mixed"
                         ) -> tuple:
    """THE role-resolution rule, shared by the fleet router and the CLIs
    so they cannot drift: expand ``worker_roles`` (one entry per dp
    replica, or () = ``default_role`` everywhere) into a validated
    dp-length tuple. Raises ValueError with a flag-spelling message on a
    bad role name or a length mismatch; warns (returns anyway) are the
    caller's business — a fleet of only-decode workers still serves,
    it just prefills lazily."""
    roles = tuple(worker_roles or ())
    if not roles:
        roles = (default_role,) * max(1, dp)
    if len(roles) != max(1, dp):
        raise ValueError(
            f"--roles needs exactly one role per dp replica: got "
            f"{len(roles)} for dp={dp}")
    for r in roles:
        if r not in WORKER_ROLES:
            raise ValueError(f"unknown worker role {r!r}: one of "
                             f"{WORKER_ROLES}")
    return roles


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """HTTP server config (Ollama-protocol endpoint, SURVEY.md §2c)."""

    host: str = "127.0.0.1"
    port: int = 11434
    model_name: str = "tiny-llama"    # name echoed in NDJSON records
    tokenizer: str = "byte"           # "byte" | path to HF tokenizer
    request_timeout_s: float = 600.0
    # Compile all engine graphs before accepting traffic (keeps XLA compile
    # out of the first requests' TTFT).
    warmup: bool = True
    # Hold HTTP headers until the first token is ready so client-side TTFT
    # (first streamed chunk) matches header-arrival time (SURVEY.md §2c).
    defer_headers_until_first_token: bool = True
    # Debug/observability endpoints (/debug/requests, /debug/profile) are
    # unauthenticated introspection; off unless explicitly enabled
    # (CLI --debug). The profiler writes only under profile_dir — the
    # client never chooses the path.
    enable_debug: bool = False
    profile_dir: str = "/tmp/jax-trace"
    # Crash flight recorder (README "Performance attribution"): bounded
    # per-replica capture dir for step records + spans + config + stats
    # on watchdog trip / step_error / SIGTERM / atexit. Same security
    # stance as profile_dir: the OPERATOR configures the path (CLI
    # --blackbox-dir), never a client. "" disables the recorder — the
    # library default, so embedded/test engine groups do no disk I/O
    # unless a path is set; the CLI serves with /tmp/tpu-inf-blackbox.
    blackbox_dir: str = ""
    # Captures retained per replica before the oldest is pruned.
    blackbox_retain: int = 8
    # Fault injection (SURVEY.md §5 failure detection: "HTTP-stub chaos
    # mode"): randomly reject this fraction of generate/chat/embed
    # requests with 503 and/or delay them, to test client resilience.
    # Off in production.
    chaos_failure_rate: float = 0.0
    chaos_delay_s: float = 0.0
    # --- Replica supervision (server/replicas.py health state machine) ---
    # A replica whose decode/prefill dispatch stays in flight longer than
    # this is wedged (the round-5 TPU failure mode): it is quarantined and
    # its in-flight requests fail over. 0 disables the watchdog — the
    # first dispatch after a cold boot without warmup includes XLA
    # compile, which can legitimately take minutes at 70B scale, so the
    # deadline is opt-in (the CLI enables it with --step-watchdog-s).
    step_watchdog_s: float = 0.0
    # Consecutive step failures before healthy -> degraded -> quarantined
    # (the first failure degrades; this many quarantine).
    quarantine_after_failures: int = 3
    # A quarantined replica waits this long, then re-enters as
    # "recovered" (probation): one clean step re-promotes it to healthy,
    # one failure re-quarantines it immediately.
    quarantine_cooldown_s: float = 30.0
    # Failover budget: a request failed/stranded by a sick replica with
    # NO tokens delivered yet is resubmitted from its prompt to a healthy
    # replica at most this many times. Requests that already streamed
    # tokens fail cleanly instead of being silently re-generated.
    failover_max_retries: int = 1
    # Admission control: reject (HTTP 429 + Retry-After) when the least
    # loaded routable replica already has this many requests queued or
    # running. 0 = unlimited (legacy behavior: queue until
    # request_timeout_s).
    admission_queue_depth: int = 0
    # Retry-After hint (seconds) sent with 429/503 shed responses.
    retry_after_s: float = 1.0
    # --- Replica routing (server/replicas.py EngineGroup) ---
    # "prefix_affinity" (default): score every routable replica by the
    # KV prefill work routing there would cost —
    #   prompt_pages - route_hit_weight * peeked_hit_pages
    #     + route_load_pages * load  (+ a pressure penalty)
    # — and route to the cheapest, so a returning conversation lands on
    # the replica that already holds its history's pages instead of
    # re-prefilling it cold (dp-1)/dp of the time. Cold prompts (no
    # replica holds anything) degrade to least-loaded. "least_loaded":
    # the legacy load-only policy (the benchmark comparison arm).
    routing: str = "prefix_affinity"
    # Pages of prefill compute one peeked cache-hit page is worth in the
    # routing score. 1.0 = at cost (a hit page saves exactly one page of
    # prefill). Raising it makes warmth beat load/pressure harder: past
    # ~1 + (prompt_pages+1)/hit_pages a fully-warm replica under
    # preemption pressure outbids a cold idle one; at the default a
    # pressured warm replica loses to a cold idle sibling.
    route_hit_weight: float = 1.0
    # Pages of prefill compute one HOST-tier hit page is worth in the
    # routing score (three temperatures: HBM-warm > host-warm > cold).
    # A host hit saves the prefill compute but still pays a host->device
    # swap-in, so it scores below an HBM hit; 0 makes the router ignore
    # host warmth entirely.
    route_host_hit_weight: float = 0.5
    # Page-equivalents of routing cost charged per queued-or-running
    # request on a replica — blends queue depth into the affinity score
    # so warmth cannot herd every conversation onto one overloaded
    # replica. Not a CLI flag; tune in config when page_size is unusual.
    route_load_pages: float = 1.0
    # --- Fleet KV fabric (README "KV fabric") ---
    # Router-side digest-keyed LRU pool of serialized KV prefix pages
    # shared across EVERY replica: a prefix prefilled on any replica
    # warms all of them (pages pull into a replica's host tier before
    # its prefill). Capacity in pages; 0 = fabric off. CLI:
    # --fabric-cache-pages.
    fabric_cache_pages: int = 0
    # Minimum contiguous settled prefix pages a sequence must hold
    # before its engine publishes them to the fabric — keeps one-page
    # scraps from churning the pool. CLI: --fabric-publish-min-pages.
    fabric_publish_min_pages: int = 1
    # Pages of the fabric's hot (MRU) set pushed into an autoscale/
    # rollout worker via import-kv before it enters the routable pool,
    # so scaled-up capacity serves its first request warm. 0 = boot
    # cold. CLI: --fabric-warmboot-pages.
    fabric_warmboot_pages: int = 64
    # Pages of prefill compute one FABRIC-covered page is worth in the
    # routing score — the fourth cache temperature, between host-warm
    # (route_host_hit_weight) and cold (0): a fabric page saves the
    # prefill compute but pays a pool pull + host->device swap-in.
    # Only pages beyond a candidate's own warm depth earn it. CLI:
    # --route-fabric-hit-weight.
    route_fabric_hit_weight: float = 0.25
    # --- Process fleet (README "Process fleet") ---
    # Fleet backend: "in-process" = dp EngineSchedulers as threads of the
    # server process (server/replicas.py EngineGroup — one process, one
    # GIL, one failure domain); "subprocess" = a router plus one
    # engine-worker OS process per replica, speaking a length-prefixed
    # JSON RPC over a local unix socket (server/worker.py +
    # server/fleet.py ProcessEngineGroup). Same facade either way.
    fleet: str = "in-process"
    # --- Zero-copy KV data plane (README "KV data plane") ---
    # "relay" = KV blobs (handoff/migrate/fabric/warmboot) traverse the
    # RPC sockets through the router — the universal path. "shm" =
    # subprocess-fleet workers write each blob ONCE into a shared-
    # memory page arena and frames carry {seg, off, len, crc32c}
    # descriptors instead; adopting workers read straight from the
    # arena. Silently degrades to relay for --fleet in-process, on
    # non-Linux hosts, or when the arena cannot be created; every
    # arena read re-verifies crc32c and falls back to relay/recompute
    # on any stale or corrupt slab. CLI: --kv-plane.
    kv_plane: str = "relay"
    # Total bytes of the shared-memory arena (split into equal
    # per-worker regions). A blob that does not fit a region's free
    # space relays through the router instead. CLI: --shm-arena-bytes.
    shm_arena_bytes: int = 256 * 1024 * 1024
    # Subprocess fleet: restarts allowed per worker (with doubling
    # backoff from worker_restart_backoff_s) before it is left down and
    # the fleet serves degraded on the survivors.
    worker_restart_max: int = 3
    worker_restart_backoff_s: float = 0.5
    # Subprocess fleet: a SIGTERM'd (or drain-RPC'd) worker gets this
    # long to settle in-flight dispatches and export its sequences' KV
    # pages before exiting.
    drain_timeout_s: float = 10.0
    # Drain-time KV page migration: a draining worker exports in-flight
    # sequences' KV pages (the PR-6 host serialization layout) over the
    # RPC channel and the router imports them into the destination
    # worker's host tier, so resubmission becomes a swap-in-resume.
    # False = the resubmission-only comparison arm (full re-prefill).
    fleet_migrate: bool = True
    # --- P/D disaggregation (README "P/D disaggregation") ---
    # Per-worker phase roles for the subprocess fleet, one entry per dp
    # replica ("prefill" | "decode" | "mixed"). () = every worker runs
    # EngineConfig.role (default "mixed" — the dp fallback with
    # unchanged behavior). With phase-specialized roles the router
    # admits new prompts to prefill-capable workers only and moves each
    # settled prefill to a decode worker as a live KV handoff (no
    # re-prefill, byte-identical under greedy). CLI: --role / --roles /
    # --pd-ratio.
    worker_roles: tuple[str, ...] = ()
    # Fan-out deadline for the router's per-candidate peek RPCs: peeks
    # are issued concurrently and any candidate that hasn't answered by
    # this deadline scores with a cold fallback instead of adding its
    # round-trip to the admission path.
    route_peek_timeout_s: float = 2.0
    # Decode-phase routing (handoffs + mid-stream resumes): page-
    # equivalents of routing cost a FULLY-occupied decode ladder adds to
    # a candidate's score — decode picks by ladder occupancy + load,
    # minus host-warm pages (the least-loaded decode worker wins when
    # occupancies tie).
    route_occupancy_pages: float = 8.0
    # os.nice() increment applied to prefill-ROLE worker processes at
    # boot (0 = off). On a real TPU fleet the P/D isolation is physical
    # (phases sit on different chips); on a shared-CPU host the worker
    # processes still contend for cores, and deprioritizing the prefill
    # tier keeps decode cadence flat under prefill bursts — the mixed/
    # hybrid topologies CANNOT buy this with any priority, because
    # their interference is in-engine dispatch serialization, not CPU
    # share. Used by the --compare-pd replay lane; irrelevant (but
    # harmless) when each worker owns its accelerator.
    pd_prefill_nice: int = 0
    # --- Elastic fleet (README "Elastic fleet") ---
    # SLO-driven autoscaler on the subprocess fleet: the router watches
    # the fleet-pooled TTFT/TPOT quantile windows (the PR-12 SLO sensor)
    # and spawns an extra worker when p95 breaches the configured
    # slo_ttft_ms/slo_tpot_ms target for autoscale_breach_window_s
    # straight, or drain-and-migrates the coldest replica away (lossless
    # scale-down: KV pages migrate, streams keep going) when pooled
    # ladder occupancy stays under autoscale_low_watermark for
    # autoscale_idle_window_s. Hysteresis comes from the two distinct
    # windows plus autoscale_cooldown_s between ANY two scale decisions,
    # and the autoscaler never acts while a worker is booting or
    # restarting — so a chaos-killed worker's restart can never race a
    # scale-up into a double spawn. False = fixed fleet (legacy).
    autoscale: bool = False
    # Replica-count bounds for the autoscaler. max 0 = dp + 2.
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: int = 0
    # Sustained-breach window before a scale-up (seconds of continuous
    # p95-over-target on the pooled windows).
    autoscale_breach_window_s: float = 3.0
    # Minimum seconds between any two scale decisions.
    autoscale_cooldown_s: float = 10.0
    # Scale-down trigger: pooled decode-ladder occupancy (0..1) must stay
    # under this for autoscale_idle_window_s straight.
    autoscale_low_watermark: float = 0.25
    autoscale_idle_window_s: float = 5.0
    # Role spawned by a scale-up: "decode" on a P/D-split fleet (decode
    # capacity is what TPOT breaches starve for); "" = "decode" when P/D
    # roles are in play, else "mixed" (a mixed fleet needs prefill
    # capacity too for TTFT relief).
    autoscale_role: str = ""
    # --- Priority classes (README "Elastic fleet": class semantics) ---
    # Class assumed for requests without an X-Priority header:
    # "interactive" | "batch" | "background".
    default_class: str = "interactive"
    # Per-class router-side deferral queues: when the fleet is at the
    # admission cap, batch/background requests park in a bounded
    # deferral queue (drained as load drops) instead of shedding 429,
    # and an interactive arrival preempts a running batch-lane request
    # (recompute-resume, byte-identical under greedy) to make room.
    # 0 = classes ride the legacy single global cap.
    class_queue_depth: int = 0
    # --- Byzantine transport (README "Failure model") ---
    # Per-verb RPC deadline classes replacing the historical blanket
    # 60 s waits: "fast" covers control-plane verbs that answer from
    # memory (peek/cancel/healthz/stats/metrics/...), "slow" covers
    # verbs that touch the engine loop or move KV bytes
    # (submit/import-kv/drain). Boot handshake, shutdown, embed and
    # profiler captures keep their own explicit budgets.
    rpc_deadline_fast_s: float = 10.0
    rpc_deadline_slow_s: float = 60.0
    # Poison-request quarantine: a request whose attempts have crashed
    # or wedged this many DISTINCT workers is failed terminally with a
    # structured 500 (and a router-side blackbox capture) instead of
    # marching through the fleet via failover. 0 disables the gate.
    poison_max_workers: int = 3
    # Transport fault injection (--chaos-rpc-*): seeded chaos shim
    # around the frame codec on both sides of every worker connection.
    # Rates are per-frame probabilities; faults are drawn from a
    # private RNG keyed only by (seed, frame index) so a pinned seed
    # reproduces the exact fault schedule. All off by default.
    chaos_rpc_seed: int = 0
    chaos_rpc_corrupt_rate: float = 0.0   # flip a byte (CRC catches it)
    chaos_rpc_drop_rate: float = 0.0      # drop = connection reset
    chaos_rpc_delay_rate: float = 0.0     # hold the frame delay_s
    chaos_rpc_delay_s: float = 0.02
    chaos_rpc_truncate_rate: float = 0.0  # torn write: prefix + reset
    # Wedge one router->worker connection (socket open, writes stop
    # landing) after this many frames; one-shot — the replacement
    # connection after the deadline-driven recycle serves clean.
    # 0 = no wedge. chaos_rpc_wedge_replica picks the victim.
    chaos_rpc_wedge_after: int = 0
    chaos_rpc_wedge_replica: int = 0
    # Frame eligibility filters: verbs (empty = all; matched against
    # the RPC verb / reply verb / event name) and direction
    # ("send" = router->worker, "recv" = worker->router, "both").
    chaos_rpc_verbs: tuple[str, ...] = ()
    chaos_rpc_direction: str = "both"


@dataclasses.dataclass
class FrameworkConfig:
    """Top-level bundle used by the CLI and server entry point."""

    model: ModelConfig = dataclasses.field(default_factory=tiny_llama)
    engine: EngineConfig = dataclasses.field(default_factory=EngineConfig)
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    server: ServerConfig = dataclasses.field(default_factory=ServerConfig)
    checkpoint_path: Optional[str] = None  # HF safetensors dir; None = random init
    seed: int = 0


# ---------------------------------------------------------------------------
# JSON config transport (subprocess fleet): the router serializes one
# FrameworkConfig and ships it to each engine-worker process over stdin,
# so router and workers can never drift on engine geometry (page_size /
# ladder / prefix digests all depend on it). Only the non-JSON-native
# leaves need special casing: the model dtype (by numpy name) and the
# tuple-valued EngineConfig fields.
# ---------------------------------------------------------------------------

_TUPLE_FIELDS = ("decode_ladder", "prefill_buckets")


def model_config_to_dict(m: ModelConfig) -> dict:
    import numpy as np

    d = dataclasses.asdict(m)
    d["dtype"] = np.dtype(m.dtype).name
    return d


def model_config_from_dict(d: dict) -> ModelConfig:
    d = dict(d)
    dtype = d.get("dtype")
    if isinstance(dtype, str):
        # jnp exposes bfloat16/float16/float32 as attributes; np.dtype
        # round-trips them by name once jax (ml_dtypes) is imported.
        d["dtype"] = getattr(jnp, dtype)
    rs = d.get("rope_scaling")
    if isinstance(rs, dict):
        d["rope_scaling"] = RopeScaling(**rs)
    return ModelConfig(**d)


def framework_config_to_dict(cfg: FrameworkConfig) -> dict:
    return {
        "model": model_config_to_dict(cfg.model),
        "engine": dataclasses.asdict(cfg.engine),
        "parallel": dataclasses.asdict(cfg.parallel),
        "server": dataclasses.asdict(cfg.server),
        "checkpoint_path": cfg.checkpoint_path,
        "seed": cfg.seed,
    }


def framework_config_from_dict(d: dict) -> FrameworkConfig:
    eng = dict(d.get("engine") or {})
    for k in _TUPLE_FIELDS:
        if k in eng and eng[k] is not None:
            eng[k] = tuple(eng[k])
    srv = dict(d.get("server") or {})
    for k in ("worker_roles", "chaos_rpc_verbs"):
        if srv.get(k) is not None:
            srv[k] = tuple(srv[k])
    return FrameworkConfig(
        model=model_config_from_dict(d["model"]),
        engine=EngineConfig(**eng),
        parallel=ParallelConfig(**(d.get("parallel") or {})),
        server=ServerConfig(**srv),
        checkpoint_path=d.get("checkpoint_path"),
        seed=d.get("seed", 0),
    )
