"""Payload integrity primitives shared by the engine and the fleet.

Stdlib-only on purpose: the frame codec (`server/transport.py`) and the
KV wire format (`engine/kv_cache.py`) both checksum their payloads, and
neither layer may drag the other's dependencies in. CRC32C (Castagnoli)
is the polynomial used by iSCSI/ext4/gRPC for exactly this job —
detecting wire and memory corruption — and unlike `zlib.crc32` it is
the checksum hardware (SSE4.2, ARMv8) accelerates, so a future C fast
path slots in without changing any stored artifact.

The pure-Python table walk below is slow in absolute terms (~5 MB/s)
but the frames it guards are KBs: JSON control messages, token events,
and tiny-model KV pages. Measured cost per frame is microseconds.
"""

from __future__ import annotations

_POLY = 0x82F63B78  # CRC-32C (Castagnoli), reflected


def _build_table() -> tuple:
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        table.append(c)
    return tuple(table)


_TABLE = _build_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC-32C of ``data``; pass a previous result as ``crc`` to chain
    incremental updates over multiple buffers."""
    crc ^= 0xFFFFFFFF
    table = _TABLE
    for b in data:
        crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


class KVIntegrityError(ValueError):
    """A serialized KV blob failed its embedded digest (or is otherwise
    structurally unsound in a way only corruption explains). Raised by
    `kv_cache.deserialize_host_pages`; every adopt/import path catches
    it, *rejects* the blob, counts the rejection, and falls back to
    recompute — a corrupt page must never be adopted silently."""
