"""Payload integrity primitives shared by the engine and the fleet.

Stdlib-first on purpose: the frame codec (`server/transport.py`) and
the KV wire format (`engine/kv_cache.py`) both checksum their payloads,
and neither layer may drag the other's dependencies in. CRC32C
(Castagnoli) is the polynomial used by iSCSI/ext4/gRPC for exactly
this job — detecting wire and memory corruption — and unlike
`zlib.crc32` it is the checksum hardware (SSE4.2, ARMv8) accelerates.

When the optional ``google_crc32c`` C extension is importable it is
used verbatim (same polynomial, same chaining semantics — pinned
against the pure table walk by tests/test_transport.py), turning the
~5 MB/s Python loop into multi-GB/s hardware CRC. That matters on the
KV data plane: a 1 MiB handoff blob is checksummed at frame-encode,
frame-decode, and page-verify time, and ~300 ms/MiB of pure-Python CRC
would dwarf every copy the zero-copy plane removes. Absent the
extension, the table walk below still guards the KB-sized control
frames at microseconds each.
"""

from __future__ import annotations

_POLY = 0x82F63B78  # CRC-32C (Castagnoli), reflected


def _build_table() -> tuple:
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        table.append(c)
    return tuple(table)


_TABLE = _build_table()


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    """CRC-32C of ``data``; pass a previous result as ``crc`` to chain
    incremental updates over multiple buffers."""
    crc ^= 0xFFFFFFFF
    table = _TABLE
    for b in data:
        crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


try:
    from google_crc32c import extend as _crc32c_ext

    def crc32c(data: bytes, crc: int = 0) -> int:
        """CRC-32C of ``data``; pass a previous result as ``crc`` to
        chain incremental updates over multiple buffers (hardware-
        accelerated; bit-identical to the pure-Python fallback)."""
        return _crc32c_ext(crc, data)
except ImportError:                                  # pragma: no cover
    crc32c = _crc32c_py


class KVIntegrityError(ValueError):
    """A serialized KV blob failed its embedded digest (or is otherwise
    structurally unsound in a way only corruption explains). Raised by
    `kv_cache.deserialize_host_pages`; every adopt/import path catches
    it, *rejects* the blob, counts the rejection, and falls back to
    recompute — a corrupt page must never be adopted silently."""
