"""HTTP serving layer: Ollama-protocol endpoint + tokenizers + metrics."""
