"""Framed unix-socket transport shared by the router and the workers.

One implementation of the wire format for both sides (`fleet.py`
imports the router half, `worker.py` the worker half), stdlib-only so
worker subprocesses can bootstrap it before heavyweight imports.

Frame layout (v2, checksummed)::

    [u32 magic "TPF1"][u32 json_len][u32 blob_len][u32 crc32c]
    [json bytes][blob bytes]

The CRC-32C covers ``pack(">II", json_len, blob_len) + json + blob`` —
lengths included so a corrupted length field that still lands inside
bounds cannot reframe the stream undetected. The magic word is the
desync detector: after a torn write the next read lands mid-payload,
and the odds of four aligned bytes spelling the magic are ~2^-32 —
the reader fails fast with a typed `FrameError` instead of
misinterpreting payload bytes as a length and hanging.

All read-side failures raise `FrameError` (a `ConnectionError`
subclass, so every existing "peer died" handler already routes it to
connection recycling). `reason` is a short machine-readable code:
``eof`` / ``magic`` / ``oversized`` / ``crc`` / ``json``.

`ChaosTransport` is the fault-injection shim: given a seeded policy it
perturbs sends — corrupt a byte, delay, tear the write, drop (modelled
as a connection reset: a SOCK_STREAM socket cannot silently lose bytes
mid-stream, so "the frame vanished" only happens as "the connection
broke"), or wedge (socket stays open, writes stop landing — the
failure only deadlines catch). Faults are drawn from a private
`random.Random(seed)` keyed only by the frame sequence, so the same
seed over the same traffic yields the same fault schedule — replay
lanes and tests pin scenarios exactly.
"""

from __future__ import annotations

import json
import random
import socket
import struct
import time
from typing import Optional, Tuple

from tpu_inference.integrity import crc32c

MAX_FRAME = 1 << 31   # blob bound (KV exports are legitimately large)
MAX_JSON = 1 << 24    # control-plane JSON is small; 16 MB is already absurd
_MAGIC = 0x54504631   # "TPF1"
_HEADER = struct.Struct(">IIII")  # magic, json_len, blob_len, crc32c


class FrameError(ConnectionError):
    """The byte stream is not a valid frame (desync, truncation,
    checksum mismatch, bad JSON). Subclasses ConnectionError because
    the only safe recovery is the same: recycle the connection."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        self.reason = reason


def _read_exact(rfile, n: int) -> bytes:
    """Exact-size read without quadratic concat: one allocation,
    ``readinto`` a sliding memoryview. A 1 MB KV blob arriving in 64 KB
    socket chunks used to pay ~16 progressively larger copies; now it
    pays one. Returns immutable bytes — deserialize_host_pages builds
    numpy views over the result, so handing out a reusable buffer
    would alias pages across frames."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    reader = getattr(rfile, "readinto", None)
    while got < n:
        if reader is not None:
            k = reader(view[got:])
            if not k:
                raise FrameError("eof", "peer closed mid-frame")
            got += k
        else:
            chunk = rfile.read(n - got)
            if not chunk:
                raise FrameError("eof", "peer closed mid-frame")
            view[got:got + len(chunk)] = chunk
            got += len(chunk)
    return bytes(buf)


def recv_frame(rfile) -> Tuple[dict, bytes]:
    """Read one frame. Raises ConnectionError("peer closed") on clean
    EOF at a frame boundary, FrameError on anything malformed. Length
    bounds are enforced BEFORE allocation, so a garbage header cannot
    trigger a multi-GB read buffer."""
    hdr = rfile.read(_HEADER.size)
    if not hdr:
        raise ConnectionError("peer closed")
    if len(hdr) < _HEADER.size:
        hdr += _read_exact(rfile, _HEADER.size - len(hdr))
    magic, jlen, blen, want = _HEADER.unpack(hdr)
    if magic != _MAGIC:
        raise FrameError("magic", f"bad frame magic 0x{magic:08x} "
                                  "(stream desync)")
    if jlen > MAX_JSON or blen > MAX_FRAME:
        raise FrameError("oversized",
                         f"frame too large (json={jlen} blob={blen})")
    payload = _read_exact(rfile, jlen)
    blob = _read_exact(rfile, blen) if blen else b""
    got = crc32c(blob, crc32c(payload, crc32c(hdr[4:12])))
    if got != want:
        raise FrameError("crc", "frame checksum mismatch "
                                f"(want 0x{want:08x} got 0x{got:08x})")
    try:
        obj = json.loads(payload.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError("json", f"bad frame json: {e}") from None
    return obj, blob


def _frame_head(obj: dict, blob: bytes) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    lens = struct.pack(">II", len(payload), len(blob))
    crc = crc32c(blob, crc32c(payload, crc32c(lens)))
    return _HEADER.pack(_MAGIC, len(payload), len(blob), crc) + payload


def encode_frame(obj: dict, blob: bytes = b"") -> bytes:
    return _frame_head(obj, blob) + blob


def _sendmsg_all(sock: socket.socket, head: bytes, blob: bytes) -> None:
    """Vectored send: header+json and the blob go out as one gather
    write, so the blob is never copied into a header+blob bytes object
    first (encode_frame's concat doubled the transient footprint of
    every KV transfer). Loops on partial sends — sendmsg may land any
    prefix of the iovec."""
    bufs = [memoryview(head), memoryview(blob)]
    while bufs:
        sent = sock.sendmsg(bufs)
        while bufs and sent >= len(bufs[0]):
            sent -= len(bufs[0])
            bufs.pop(0)
        if bufs and sent:
            bufs[0] = bufs[0][sent:]


def send_frame(sock: socket.socket, obj: dict, blob: bytes = b"", *,
               chaos: "Optional[ChaosTransport]" = None,
               verb: str = "", direction: str = "send") -> None:
    """Encode and write one frame, routing through the chaos shim when
    one is armed. Chaos faults surface as ConnectionError (drop/tear)
    or silently swallowed writes (wedge) — exactly the failure shapes a
    real broken transport produces."""
    if chaos is not None:
        # Chaos needs the full contiguous frame (corrupt/truncate act
        # on absolute byte offsets); it is a test-only shim, so the
        # concat copy is acceptable there.
        chaos.send(sock, encode_frame(obj, blob), verb, direction)
        return
    head = _frame_head(obj, blob)
    if blob and hasattr(sock, "sendmsg"):
        _sendmsg_all(sock, head, blob)
    else:
        sock.sendall(head + blob)


class ChaosPolicy:
    """Fault-injection knobs for one endpoint. Plain data; the
    stateful draw lives in ChaosTransport. ``verbs`` filters which
    frames are eligible (empty = all; matched against the RPC verb on
    the router side and the reply-verb/event name on the worker side);
    ``direction`` gates which side injects ("send" = router->worker,
    "recv" = worker->router, "both"). ``wedge_after`` > 0 arms a
    one-shot wedge: after that many eligible frames the connection goes
    silent (open but mute) until recycled; ``wedge_spent`` makes the
    replacement connection serve clean so liveness is preserved."""

    def __init__(self, *, seed: int = 0, corrupt_rate: float = 0.0,
                 drop_rate: float = 0.0, delay_rate: float = 0.0,
                 delay_s: float = 0.02, truncate_rate: float = 0.0,
                 wedge_after: int = 0, verbs: tuple = (),
                 direction: str = "both"):
        self.seed = int(seed)
        self.corrupt_rate = float(corrupt_rate)
        self.drop_rate = float(drop_rate)
        self.delay_rate = float(delay_rate)
        self.delay_s = float(delay_s)
        self.truncate_rate = float(truncate_rate)
        self.wedge_after = int(wedge_after)
        self.verbs = tuple(verbs or ())
        self.direction = str(direction or "both")
        self.wedge_spent = False

    @property
    def active(self) -> bool:
        return (self.corrupt_rate > 0 or self.drop_rate > 0
                or self.delay_rate > 0 or self.truncate_rate > 0
                or self.wedge_after > 0)

    def snapshot(self) -> dict:
        return {"seed": self.seed, "corrupt_rate": self.corrupt_rate,
                "drop_rate": self.drop_rate,
                "delay_rate": self.delay_rate, "delay_s": self.delay_s,
                "truncate_rate": self.truncate_rate,
                "wedge_after": self.wedge_after,
                "wedge_spent": self.wedge_spent,
                "verbs": list(self.verbs), "direction": self.direction}


class ChaosTransport:
    """Per-connection fault injector. Deterministic: the action for
    frame N is a pure function of (policy.seed, N), independent of
    wall clock or payload bytes, so pinned seeds reproduce schedules.

    Byte corruption only touches offset >= 12 (the CRC field or the
    payload), never the length words: flipping a length could make the
    reader block for bytes that are never coming, which is the *wedge*
    fault, injected explicitly — corruption should exercise the
    checksum path. Garbage-length handling is covered by the codec
    fuzz tests against the reader directly."""

    def __init__(self, policy: ChaosPolicy):
        self.policy = policy
        self.rng = random.Random(policy.seed)
        self.frames = 0
        self.wedged = False

    def _matches(self, verb: str, direction: str) -> bool:
        p = self.policy
        if p.direction not in ("both", direction):
            return False
        return not p.verbs or verb in p.verbs

    def decide(self, verb: str, direction: str) -> str:
        """Fault action for the next frame: "pass" | "delay" |
        "corrupt" | "truncate" | "drop" | "wedge"."""
        if self.wedged:
            # A wedged connection is mute for ALL traffic, filters or
            # not — that is what "wedged" means.
            return "wedge"
        if not self._matches(verb, direction):
            return "pass"
        p = self.policy
        self.frames += 1
        if p.wedge_after > 0 and not p.wedge_spent \
                and self.frames > p.wedge_after:
            self.wedged = True
            p.wedge_spent = True  # replacement connection serves clean
            return "wedge"
        u = self.rng.random()
        if u < p.drop_rate:
            return "drop"
        u -= p.drop_rate
        if u < p.truncate_rate:
            return "truncate"
        u -= p.truncate_rate
        if u < p.corrupt_rate:
            return "corrupt"
        u -= p.corrupt_rate
        if u < p.delay_rate:
            return "delay"
        return "pass"

    def send(self, sock: socket.socket, data: bytes, verb: str,
             direction: str) -> None:
        action = self.decide(verb, direction)
        if action == "pass":
            sock.sendall(data)
        elif action == "delay":
            time.sleep(self.policy.delay_s)
            sock.sendall(data)
        elif action == "corrupt":
            # Flip one byte in the CRC field or payload; the peer's
            # checksum rejects the frame and recycles the connection.
            buf = bytearray(data)
            off = 12 + self.rng.randrange(len(buf) - 12)
            buf[off] ^= 0xFF
            sock.sendall(bytes(buf))
        elif action == "truncate":
            # Torn write: a prefix lands, then the connection dies.
            n = 1 + self.rng.randrange(max(1, len(data) - 1))
            try:
                sock.sendall(data[:n])
            except OSError:
                pass
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            raise ConnectionError("chaos: torn write")
        elif action == "drop":
            # See module docstring: stream sockets cannot lose bytes
            # silently, so a dropped frame IS a connection reset.
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            raise ConnectionError("chaos: frame dropped "
                                  "(connection reset)")
        else:  # wedge: swallow the write, keep the socket open.
            pass
