"""Tokenizer adapters.

Two implementations behind one tiny interface:
- ``ByteTokenizer``: hermetic UTF-8 byte-level tokenizer (vocab 256 bytes +
  BOS/EOS). No files, no network — used by tests, the CPU stub config, and
  any tiny random-init model. Incremental decoding buffers split UTF-8
  sequences so streamed chunks are always valid text.
- ``HFTokenizer``: wraps a local HuggingFace tokenizer directory (Llama,
  Mixtral, GPT-2 vocabularies) via ``transformers.AutoTokenizer``.

The reference repo never tokenizes (prompt lengths come pre-counted in its
corpus; SURVEY.md §2a #3) — tokenization there happens inside the external
Ollama server. This module is that missing server half.
"""

from __future__ import annotations

from typing import List, Optional, Protocol


class Tokenizer(Protocol):
    vocab_size: int
    bos_token_id: Optional[int]
    eos_token_id: Optional[int]

    def encode(self, text: str, add_bos: bool = True) -> List[int]: ...
    def decode(self, ids: List[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes as tokens; ids 0-255 = bytes, 256 = BOS, 257 = EOS."""

    def __init__(self, vocab_size: int = 512):
        assert vocab_size >= 258
        self.vocab_size = vocab_size
        self.bos_token_id = 256
        self.eos_token_id = 257

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_token_id] + ids) if add_bos else ids

    def decode(self, ids: List[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Local HuggingFace tokenizer directory (no network)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)
        self.bos_token_id = self._tok.bos_token_id
        self.eos_token_id = self._tok.eos_token_id

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_token_id is not None:
            ids = [self.bos_token_id] + ids
        return ids

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: List[dict]) -> Optional[str]:
        """Render /api/chat messages with the checkpoint's own chat
        template (tokenizer_config.json), or None when it has none —
        instruct-tuned models only behave when prompted in their trained
        format, not a generic role-prefix transcript."""
        if not getattr(self._tok, "chat_template", None):
            return None
        try:
            rendered = self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True)
            # Templates usually bake in the BOS text; encode() prepends
            # the BOS id itself, so strip it here or it doubles.
            bos = self._tok.bos_token
            if bos and rendered.startswith(bos):
                rendered = rendered[len(bos):]
            return rendered
        # Broad by intent: template rendering raises jinja2.TemplateError
        # subclasses (e.g. Llama-2's raise_exception on non-alternating
        # roles) besides the std ones — ANY render failure falls back to
        # the transcript format rather than 500ing the chat request.
        except Exception as e:  # noqa: BLE001
            import sys
            print(f"[tokenizer] chat template failed ({e!r}); falling "
                  "back to role-prefix transcript", file=sys.stderr)
            return None


class IncrementalDecoder:
    """Streams token ids -> text chunks. One instance per request.

    Decoding each token independently is wrong for non-concatenative
    tokenizers (SentencePiece/Metaspace pieces like "▁the" decode to
    "the" alone but " the" in context), so this keeps a sliding window:
    re-decode from the previous emit point and yield only the text
    delta (the vLLM detokenizer offset scheme). The window resets on
    every emit, so per-token cost stays O(tokens since last emit).
    A trailing replacement char means an incomplete UTF-8/byte-fallback
    sequence — hold until a later token completes it.

    ``prompt_tail``: the last few prompt ids, seeding the window so the
    first generated piece keeps its inter-word spacing after the prompt.
    """

    def __init__(self, tokenizer: Tokenizer, prompt_tail: List[int] = ()):
        self._tok = tokenizer
        self._ids: List[int] = list(prompt_tail)
        self._prefix = 0                   # window start
        self._read = len(self._ids)        # already-emitted boundary

    def push(self, token_id: int) -> str:
        self._ids.append(token_id)
        prefix_text = self._tok.decode(self._ids[self._prefix:self._read])
        full_text = self._tok.decode(self._ids[self._prefix:])
        if full_text.endswith("�") or len(full_text) <= len(prefix_text):
            return ""
        self._prefix = self._read
        self._read = len(self._ids)
        return full_text[len(prefix_text):]

    def flush(self) -> str:
        prefix_text = self._tok.decode(self._ids[self._prefix:self._read])
        full_text = self._tok.decode(self._ids[self._prefix:])
        self._read = len(self._ids)
        return full_text[len(prefix_text):]


class StopMatcher:
    """Scans a text stream for stop sequences spanning chunk boundaries.

    ``push(chunk)`` returns (text safe to emit, stopped). Text that could
    be the prefix of a stop string is held back until disambiguated, so a
    stop sequence split across streamed tokens is still caught and the
    stop string itself is never emitted (Ollama ``options.stop``).
    """

    def __init__(self, stops: List[str]):
        self.stops = [s for s in stops if s]
        self._buf = ""

    def push(self, text: str) -> tuple:
        if not self.stops:
            return text, False
        self._buf += text
        cut = min((i for i in (self._buf.find(s) for s in self.stops)
                   if i >= 0), default=-1)
        if cut >= 0:
            out, self._buf = self._buf[:cut], ""
            return out, True
        hold = 0
        for s in self.stops:
            for n in range(min(len(s) - 1, len(self._buf)), hold, -1):
                if self._buf.endswith(s[:n]):
                    hold = n
                    break
        out = self._buf[:len(self._buf) - hold]
        self._buf = self._buf[len(self._buf) - hold:]
        return out, False

    def flush(self) -> str:
        out, self._buf = self._buf, ""
        return out


def build_tokenizer(spec: str, vocab_size: int = 512) -> Tokenizer:
    """'byte' -> ByteTokenizer; anything else is a local HF tokenizer path."""
    if spec == "byte":
        return ByteTokenizer(vocab_size=max(vocab_size, 258))
    return HFTokenizer(spec)
