"""Data-parallel replica serving: dp independent engines behind one facade.

``ParallelConfig.dp`` used to replicate params inside ONE engine (useful
for the sharding dry-run, useless for throughput: one scheduler, one
decode batch). True dp serving is replica-per-group — each replica owns a
``tp*sp``-device submesh, its own KV pool, and its own continuous-batching
scheduler. The reference's analogue is the load balancer in front of its
external endpoint (implicit, out of repo — SURVEY.md §0); here it comes
in TWO backends behind one facade (``ServerConfig.fleet``, README
"Process fleet"): this module's ``EngineGroup`` runs every replica as a
thread of the server process (simple, but one Python process, one GIL,
one failure domain), while ``server/fleet.py``'s ``ProcessEngineGroup``
runs each replica as its own engine-worker OS process behind a router,
with supervised restarts, kill -9 failover, and drain-time KV page
migration. The routing/failover/admission semantics below are the
contract both backends implement.

Supervision (README "Failure handling & degraded operation"): each
replica carries a health state machine

    healthy -> degraded -> quarantined -> recovered -> healthy

driven by consecutive step failures (engine exceptions surfaced through
the scheduler hooks) and a step watchdog that detects wedged dispatches
(the round-5 TPU failure mode: a decode call that never returns).
Quarantined replicas receive no traffic; their failed or stranded
requests fail over — resubmitted from the prompt to a healthy replica
when no tokens were delivered yet, failed cleanly otherwise. Admission
control sheds load (FleetSaturated/FleetUnavailable -> HTTP 429/503 with
Retry-After) instead of queueing to the request timeout.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from tpu_inference import telemetry
from tpu_inference.config import ServerConfig
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.engine.prefix_cache import _chain_hashes
from tpu_inference.engine.scheduler import EngineScheduler
from tpu_inference.server import kv_fabric


class AdmissionError(RuntimeError):
    """Request rejected before submission; carries the Retry-After hint."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class FleetSaturated(AdmissionError):
    """Every routable replica is at the admission queue cap (HTTP 429)."""


class FleetUnavailable(AdmissionError):
    """No routable replica at all — fleet fully quarantined (HTTP 503)."""


HEALTHY = "healthy"
DEGRADED = "degraded"
QUARANTINED = "quarantined"
RECOVERED = "recovered"


class ReplicaHealth:
    """Per-replica health state machine (thread-safe; hooks fire on the
    replica's engine thread, the watchdog on the monitor thread, and
    snapshots on HTTP handler threads)."""

    def __init__(self, cfg: ServerConfig):
        self.cfg = cfg
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.wedges = 0                 # watchdog firings
        self.quarantines = 0            # entries into QUARANTINED
        self.since = time.monotonic()   # last state change
        self._lock = threading.Lock()

    def _transition(self, state: str) -> None:
        if state == QUARANTINED and self.state != QUARANTINED:
            self.quarantines += 1
        if state != self.state:
            self.state = state
            self.since = time.monotonic()

    def on_ok(self) -> None:
        # Hot path: one clean step per decode call — skip the lock when
        # there is provably nothing to do.
        if self.state == HEALTHY and self.consecutive_failures == 0:
            return
        with self._lock:
            self.consecutive_failures = 0
            if self.state in (DEGRADED, RECOVERED):
                # RECOVERED -> HEALTHY is the probation pass.
                self._transition(HEALTHY)
            # QUARANTINED stays: a late success from a previously wedged
            # call does not beat the cooldown (the fault may recur).

    def on_error(self) -> None:
        with self._lock:
            self.consecutive_failures += 1
            if self.state == RECOVERED:
                # Probation failure: straight back to quarantine.
                self._transition(QUARANTINED)
            elif self.consecutive_failures >= self.cfg.quarantine_after_failures:
                self._transition(QUARANTINED)
            elif self.state == HEALTHY:
                self._transition(DEGRADED)

    def mark_wedged(self) -> bool:
        """Watchdog deadline exceeded. True only on the transition, so
        the caller fails over stranded requests exactly once."""
        with self._lock:
            if self.state == QUARANTINED:
                return False
            self.wedges += 1
            self._transition(QUARANTINED)
            return True

    def maybe_recover(self) -> None:
        """QUARANTINED -> RECOVERED after the cooldown. The caller must
        not invoke this while the replica's dispatch is still wedged."""
        with self._lock:
            if (self.state == QUARANTINED
                    and time.monotonic() - self.since
                    >= self.cfg.quarantine_cooldown_s):
                self._transition(RECOVERED)

    @property
    def routable(self) -> bool:
        return self.state != QUARANTINED

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "wedges": self.wedges,
                "quarantines": self.quarantines,
                "state_age_s": round(time.monotonic() - self.since, 3),
            }


def _clone_request(seq: Sequence) -> Sequence:
    """A pristine copy of the client-supplied request fields — engine-
    filled state (slot, pages, generated, timings) starts fresh, so a
    failover attempt replays from the prompt exactly like a new submit."""
    return Sequence(
        request_id=seq.request_id,
        prompt_tokens=list(seq.prompt_tokens),
        max_new_tokens=seq.max_new_tokens,
        temperature=seq.temperature, top_p=seq.top_p, top_k=seq.top_k,
        seed=seq.seed, repeat_penalty=seq.repeat_penalty,
        repeat_last_n=seq.repeat_last_n, eos_token_id=seq.eos_token_id,
        trace_id=seq.trace_id,
        priority_class=seq.priority_class,
        # The prompt's chain hashes are a pure function of the tokens:
        # the replay reuses the original's single hash pass (bytes are
        # immutable — sharing the list is safe).
        prefix_digests=seq.prefix_digests)


# Finish reasons a zero-delivery request may be resubmitted after.
_RETRYABLE = ("error",)


@dataclasses.dataclass
class _Tracked:
    """Group-side state for one in-flight request across attempts."""

    template: Sequence                  # pristine request for resubmission
    on_token: Callable
    on_finish: Callable
    sched: EngineScheduler
    delivered: int = 0                  # tokens forwarded to the caller
    attempts: int = 0                   # failover resubmissions so far
    generation: int = 0                 # bumped to orphan stale callbacks
    t_submit: float = 0.0               # perf_counter at submit (root span)
    # DISTINCT replica indices whose attempt at this request errored or
    # wedged — the poison-quarantine gate's evidence (README "Failure
    # model").
    failed_replicas: set = dataclasses.field(default_factory=set)


class EngineGroup:
    """dp EngineSchedulers with cache-aware routing, health supervision,
    failover, and admission control.

    Routing (ServerConfig.routing): "prefix_affinity" scores every
    routable replica by the prefill work routing there would cost —
    expected re-prefill pages (prompt pages minus a side-effect-free
    prefix-cache peek) blended with queue depth and preemption
    pressure — so a returning conversation lands on the replica that
    already holds its history's KV pages. Cold prompts, single-replica
    fleets, and routing="least_loaded" reduce to the legacy
    (pressure, load) key, now with a deterministic rotating tie-break
    (equal-key replicas used to all herd onto replica 0).

    With one engine this is a transparent pass-through, so the server
    always talks to an EngineGroup.
    """

    def __init__(self, engines: List[InferenceEngine],
                 server_cfg: Optional[ServerConfig] = None):
        assert engines
        self.engines = engines
        self.server_cfg = server_cfg or ServerConfig()
        self.schedulers = [EngineScheduler(e) for e in engines]
        self.health = [ReplicaHealth(self.server_cfg) for _ in engines]
        for sched, health in zip(self.schedulers, self.health):
            sched.on_step_ok = health.on_ok
            sched.on_step_error = lambda exc, h=health: h.on_error()
        # request_id -> tracked entry (ids are globally unique).
        self._tracked: Dict[int, _Tracked] = {}
        self._lock = threading.Lock()
        # Fleet counters (surfaced via stats_snapshot / /healthz).
        self.retries_attempted = 0
        self.retries_succeeded = 0
        self.failovers = 0              # stranded-by-wedge resubmissions
        self.requests_shed = 0          # 429: queue cap
        self.requests_unavailable = 0   # 503: no routable replica
        self.poison_requests = 0        # terminally quarantined (500)
        # Routing accounting. The rotation counter advances once per
        # tie-broken decision; the counters move on every dispatch
        # (initial or failover). Plain ints mutated from HTTP/engine
        # threads: GIL-atomic increments, torn reads tolerated (same
        # stance as telemetry.py).
        self._rr = 0                    # rotating tie-break cursor
        self.route_prefix_hits = 0      # dispatches with peeked hit > 0
        self.route_cold = 0             # dispatches with no cached prefix
        self.route_fabric_hits = 0      # dispatches that pulled fabric pages
        self._route_stats = [{"hits": 0, "cold": 0, "hit_pages": 0,
                              "host_hit_pages": 0, "fabric_hit_pages": 0}
                             for _ in engines]
        # Fleet KV fabric (README "KV fabric"): the router-side
        # digest-keyed pool of serialized prefix pages shared by every
        # replica. In-process, publish is a direct call (each engine's
        # fabric_publish is armed below); pulls land in the target
        # engine's host tier via request_import_host before dispatch.
        self.fabric = kv_fabric.FabricPool(self.server_cfg.fabric_cache_pages)
        for e in engines:
            if self.fabric.capacity > 0 and e.prefix_cache is not None:
                e.fabric_publish = self.fabric.put_pages
                e.fabric_publish_min_pages = \
                    self.server_cfg.fabric_publish_min_pages
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        # Cross-replica trace assembly (README "Observability"): each
        # engine's recorder holds its replica's spans (stamped with the
        # replica index here); the group's own recorder holds the
        # router-side spans (request root, route) — /debug/trace reads
        # them together. Same shape as the subprocess router, minus the
        # transport (everything is in-process).
        self._recorder = telemetry.SpanRecorder(replica=-1)
        for i, e in enumerate(self.engines):
            e.telemetry.recorder.replica = i
        # Fleet-level Prometheus registry: supervision counters (no
        # replica label — they are fleet decisions) + per-replica health
        # gauges. Rendered together with each engine's registry (under
        # replica="i" labels) by prometheus_text().
        self._fleet_registry = telemetry.Registry()
        r = self._fleet_registry
        telemetry.register_span_ring(r, self._recorder)
        r.gauge("tpu_inf_replicas", "Configured dp replicas",
                fn=lambda: len(self.engines))
        r.counter("tpu_inf_retries_attempted_total",
                  "Failover resubmissions attempted",
                  fn=lambda: self.retries_attempted)
        r.counter("tpu_inf_retries_succeeded_total",
                  "Failover resubmissions that finished cleanly",
                  fn=lambda: self.retries_succeeded)
        r.counter("tpu_inf_failovers_total",
                  "Requests stranded by a wedged replica and resubmitted",
                  fn=lambda: self.failovers)
        r.counter("tpu_inf_requests_shed_total",
                  "Requests shed at the admission queue cap (HTTP 429)",
                  fn=lambda: self.requests_shed)
        r.counter("tpu_inf_requests_unavailable_total",
                  "Requests rejected with no routable replica (HTTP 503)",
                  fn=lambda: self.requests_unavailable)
        r.counter("tpu_inf_poison_requests_total",
                  "Requests quarantined after crashing/wedging "
                  "poison_max_workers distinct replicas (HTTP 500)",
                  fn=lambda: self.poison_requests)
        r.counter("tpu_inf_kv_integrity_rejections_total",
                  "KV blobs rejected on a failed end-to-end digest "
                  "check (recompute fallback, never adopted silently)",
                  fn=lambda: sum(e.kv_integrity_rejections
                                 for e in self.engines)
                  + self.fabric.kv_rejections)
        r.counter("tpu_inf_route_prefix_hits_total",
                  "Dispatches routed with a non-zero prefix-cache peek "
                  "(the request landed on a warm replica)",
                  fn=lambda: self.route_prefix_hits)
        r.counter("tpu_inf_route_cold_total",
                  "Dispatches routed with no cached prefix on any scored "
                  "replica (least-loaded fallback)",
                  fn=lambda: self.route_cold)
        self._route_hit_pages_hist = r.histogram(
            "tpu_inf_route_hit_pages",
            "Peeked prefix-cache hit pages per warm-routed dispatch",
            buckets=telemetry.COUNT_BUCKETS)
        r.counter("tpu_inf_route_fabric_hits_total",
                  "Dispatches that pulled fabric pages into the routed "
                  "replica's host tier (fourth-temperature warmth)",
                  fn=lambda: self.route_fabric_hits)
        self._route_fabric_hit_pages_hist = r.histogram(
            "tpu_inf_route_fabric_hit_pages",
            "Fabric pages pulled per fabric-warm dispatch",
            buckets=telemetry.COUNT_BUCKETS)
        telemetry.register_fabric(r, self.fabric)
        for i, health in enumerate(self.health):
            r.gauge("tpu_inf_replica_routable",
                    "1 when the replica accepts traffic (not quarantined)",
                    fn=lambda h=health: float(h.routable),
                    replica=str(i))
            r.counter("tpu_inf_replica_quarantines_total",
                      "Entries into the quarantined state",
                      fn=lambda h=health: h.quarantines, replica=str(i))
            r.counter("tpu_inf_replica_wedges_total",
                      "Step-watchdog firings (wedged dispatches)",
                      fn=lambda h=health: h.wedges, replica=str(i))
        # Fleet-level rolling SLO gauges: EXACT quantiles pooled across
        # every replica's window (the per-replica series render from
        # each engine's own registry under replica="i" labels).
        telemetry.register_fleet_slo(
            r, self._pooled_slo_quantile,
            lambda k: sum(getattr(e.telemetry.slo, f"{k}_breaches", 0)
                          for e in self.engines
                          if e.telemetry.slo is not None))
        # Dashboard-join info gauge, on the fleet registry AND every
        # replica registry (label values are pure config: identical
        # across replicas and restarts).
        import jax
        ecfg = self.engines[0].engine_cfg
        kw = dict(backend=jax.default_backend(),
                  fleet=self.server_cfg.fleet,
                  kv_quant=ecfg.kv_quant,
                  spec_mode=(self.engines[0].spec_mode
                             if self.engines[0].spec_enabled else "off"),
                  routing=self.server_cfg.routing)
        telemetry.emit_build_info(r, **kw)
        for e in self.engines:
            if e.telemetry.enabled:
                telemetry.emit_build_info(e.telemetry.registry, **kw)
        # Crash flight recorders (one per replica) when the operator
        # configured --blackbox-dir; direct-constructed test groups
        # leave it '' and do no disk I/O.
        if self.server_cfg.blackbox_dir:
            import dataclasses as _dc
            for i, (e, s) in enumerate(zip(self.engines,
                                           self.schedulers)):
                telemetry.attach_flight_recorder(
                    e.telemetry, self.server_cfg.blackbox_dir, i,
                    retain=self.server_cfg.blackbox_retain,
                    config=_dc.asdict(self.server_cfg),
                    stats_fn=lambda s=s, e=e: s.stats.snapshot(e))

    def _pooled_slo_quantile(self, which: str, q: float) -> float:
        windows = []
        for e in self.engines:
            slo = e.telemetry.slo
            if slo is not None:
                ring = slo.ttft if which == "ttft" else slo.tpot
                windows.append(ring.values())
        v = telemetry.pooled_quantile(windows, q)
        return float("nan") if v is None else v

    def _fleet_slo(self) -> dict:
        return telemetry.pooled_slo(
            [e.telemetry.slo.snapshot() for e in self.engines
             if e.telemetry.slo is not None])

    @property
    def engine(self) -> InferenceEngine:
        """Primary replica (single-engine callers, tests)."""
        return self.engines[0]

    def warmup(self) -> float:
        return sum(e.warmup() for e in self.engines)

    def start(self) -> "EngineGroup":
        for s in self.schedulers:
            s.start()
        self._watch_stop.clear()
        self._watch_thread = threading.Thread(
            target=self._watch, name="replica-watchdog", daemon=True)
        self._watch_thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=5.0)
            self._watch_thread = None
        for s in self.schedulers:
            s.stop(drain=drain, timeout=timeout)

    # ------------------------------------------------------- supervision

    def _watch_interval(self) -> float:
        cfg = self.server_cfg
        interval = 0.25
        if cfg.step_watchdog_s > 0:
            interval = min(interval, cfg.step_watchdog_s / 5)
        if cfg.quarantine_cooldown_s > 0:
            interval = min(interval, max(0.05, cfg.quarantine_cooldown_s / 5))
        return max(0.02, interval)

    def _wedged(self, sched: EngineScheduler) -> bool:
        wd = self.server_cfg.step_watchdog_s
        t0 = sched.step_inflight_since
        return wd > 0 and t0 is not None and time.monotonic() - t0 > wd

    def _watch(self) -> None:
        """Monitor thread: watchdog deadlines + quarantine cooldowns."""
        interval = self._watch_interval()
        while not self._watch_stop.wait(interval):
            for sched, health in zip(self.schedulers, self.health):
                if self._wedged(sched):
                    if health.mark_wedged():
                        flight = sched.engine.telemetry.flight
                        if flight is not None:
                            # The wedged dispatch's records are still
                            # the newest in the ring — dump them now.
                            flight.capture("watchdog")
                        self._failover_stranded(sched)
                else:
                    health.maybe_recover()

    def _routable(self) -> List[EngineScheduler]:
        out = []
        for sched, health in zip(self.schedulers, self.health):
            # Lazy cooldown check too, so a fleet whose monitor tick has
            # not fired yet (or tests driving the group directly) still
            # re-admits a cooled-down replica at submit time.
            if not self._wedged(sched):
                health.maybe_recover()
            if health.routable:
                out.append(sched)
        return out

    @staticmethod
    def _route_key(sched: EngineScheduler):
        """Least-loaded routing, preferring replicas whose KV pool is
        not under preemption pressure: a request routed to a pressured
        replica would likely trigger (or suffer) a preemption that a
        sibling with free pages avoids entirely."""
        return kv_fabric.cold_route_key(sched.engine.under_pressure,
                                        sched.load)

    def _rotate(self, ties: list):
        """Deterministic rotating pick among equal-key candidates.
        min() always returned the first — under a burst of equal-load
        (or equally cold) replicas everything herded onto replica 0.
        The cursor is a plain int: racy increments just skew the
        rotation, never the correctness of the pick."""
        if len(ties) == 1:
            return ties[0]
        idx = self._rr % len(ties)
        self._rr += 1
        return ties[idx]

    def _digests_for(self, seq: Sequence) -> Tuple[List[bytes], int]:
        """THE truncation/trim rule for routing-time prefix digests,
        shared by every scoring site so router math can never drift
        from engine lookup: keep the most recent max_context-1 tokens,
        never count the final prompt token (its logits are always
        recomputed). Chain-hashes the prompt ONCE per request — the
        list is cached on the Sequence and reused by admission lookup,
        publish, failover replays, and the admission-cap fallback (all
        replicas serve one EngineConfig, so page_size/max_context
        agree). The cached list may carry one extra final-page digest
        from an engine-side fill; the cap trims it. Returns
        (digests, prompt_pages)."""
        ecfg = self.engines[0].engine_cfg
        prompt_len = min(len(seq.prompt_tokens), ecfg.max_context - 1)
        prompt_pages = kvc.pages_needed(prompt_len, ecfg.page_size)
        cap = (prompt_len - 1) // ecfg.page_size
        if cap <= 0:
            return [], prompt_pages
        if seq.prefix_digests is None:
            tokens = seq.prompt_tokens
            prompt = (tokens[-prompt_len:] if len(tokens) > prompt_len
                      else tokens)
            seq.prefix_digests = _chain_hashes(prompt, ecfg.page_size)
        return seq.prefix_digests[:cap], prompt_pages

    def _pick(self, cands: List[EngineScheduler],
              seq: Optional[Sequence] = None
              ) -> Tuple[EngineScheduler, Tuple[int, int, int]]:
        """Choose a replica for one request; returns (scheduler,
        (hbm_hit_pages, host_hit_pages, fabric_extra_pages) peeked on
        that scheduler).

        prefix_affinity with a token-bearing request scores each
        candidate in KV-page units across FOUR temperatures — HBM-warm
        > host-warm > fabric-warm > cold (README "KV fabric") — via
        kv_fabric.prefill_route_score, THE formula both fleet backends
        share: the prefill work this replica would actually redo (a
        host-tier page saves the prefill compute but still pays a
        host->device swap-in; a fabric page additionally pays the pool
        pull, so it scores below host at the default weights) plus a
        queue-depth blend, plus a pressure penalty sized so that at the
        default hit weight a fully-warm pressured replica still loses
        to a cold idle one. The fabric term counts only the pages the
        pool covers BEYOND the candidate's own warm depth, from the
        router's own local index — no extra RPC. Ties break by the
        legacy (pressure, load) key, then rotate. When NO candidate
        holds any prefix page in either tier and the fabric holds none
        (or routing="least_loaded"), the score reduces to (pressure,
        load) + rotation — plain least-loaded. A single warm candidate
        is still peeked so the routing counters and span report the
        true hit (e.g. the lone survivor of a quarantined fleet must
        not read as a cold dispatch).

        The digest list computed here is cached on the Sequence
        (prefix_digests) so admission and publish reuse the same single
        hash pass over the prompt.
        """
        cfg = self.server_cfg
        if seq is not None and cfg.routing == "prefix_affinity":
            digests, prompt_pages = self._digests_for(seq)
            fdepth = self.fabric.match_depth(digests)
            hits = []
            for sched in cands:
                pc = sched.engine.prefix_cache
                hits.append(pc.peek_digests_tiered(digests)
                            if pc is not None else (0, 0))
            if any(h + w for h, w in hits) or fdepth > 0:
                scored = []
                for sched, (hbm, host) in zip(cands, hits):
                    fx = kv_fabric.fabric_extra_pages(
                        fdepth, hbm + host, prompt_pages)
                    pressured = sched.engine.under_pressure
                    score = kv_fabric.prefill_route_score(
                        cfg, prompt_pages=prompt_pages, hbm=hbm,
                        host=host, fabric=fx, load=sched.load,
                        pressured=pressured)
                    scored.append(((score, pressured, sched.load),
                                   sched, (hbm, host, fx)))
                best = min(key for key, _, _ in scored)
                return self._rotate([(s, h) for key, s, h in scored
                                     if key == best])
            # Cold everywhere: least-loaded fall-through (hit 0 is the
            # truth, not an accounting shortcut).
        keyed = [(self._route_key(sched), sched) for sched in cands]
        best = min(key for key, _ in keyed)
        return self._rotate([(s, (0, 0, 0)) for key, s in keyed
                             if key == best])

    def _peek_replica(self, sched: EngineScheduler,
                      seq: Sequence) -> Tuple[int, int, int]:
        """One replica's peeked (hbm, host, fabric_extra) hit pages for
        a request (accounting on paths that chose by load, e.g. the
        admission-cap fallback). Reuses the digest list _pick just
        cached on the Sequence — the fallback fires on exactly the
        overloaded path where a second full hash pass would hurt most."""
        if self.server_cfg.routing != "prefix_affinity":
            return (0, 0, 0)
        pc = sched.engine.prefix_cache
        if pc is None:
            return (0, 0, 0)
        digests, prompt_pages = self._digests_for(seq)
        hbm, host = pc.peek_digests_tiered(digests)
        fx = kv_fabric.fabric_extra_pages(
            self.fabric.match_depth(digests), hbm + host, prompt_pages)
        return (hbm, host, fx)

    def _least_loaded(self) -> EngineScheduler:
        routable = self._routable()
        if not routable:
            raise FleetUnavailable(
                "all replicas quarantined",
                self._retry_after())
        return self._pick(routable)[0]

    def _retry_after(self) -> float:
        return self.server_cfg.retry_after_s

    def embed_many(self, batch) -> "np.ndarray":  # noqa: F821
        """Embeddings on the least-loaded replica — pinning them to
        replica 0 would interleave dense forwards with its decode loop
        while the other replicas idle."""
        try:
            sched = self._least_loaded()
        except FleetUnavailable:
            # Same counter as submit(): embed 503s must be visible in
            # /healthz and stats, not just generate ones.
            with self._lock:
                self.requests_unavailable += 1
            raise
        return sched.engine.embed_many(batch)

    # -------------------------------------------------------- submission

    def submit(self, seq: Sequence, on_token: Callable,
               on_finish: Callable) -> None:
        """Route to the best healthy replica (prefix affinity blended
        with load/pressure; see _pick).

        Raises FleetUnavailable (no routable replica) or FleetSaturated
        (admission queue cap) instead of queueing — the HTTP layer maps
        these to 503/429 with Retry-After. Scheduler-level rejections
        (queue_full, too_large) still arrive via on_finish.
        """
        # Trace-id propagation: mint when the ingress didn't (direct
        # group submits from benchmarks/tests) so logs and spans are
        # joinable under one id on every path.
        if not seq.trace_id:
            import uuid
            seq.trace_id = uuid.uuid4().hex[:16]
        routable = self._routable()
        if not routable:
            with self._lock:
                self.requests_unavailable += 1
            raise FleetUnavailable(
                "all replicas quarantined", self._retry_after())
        t_route = time.perf_counter()
        sched, hit_pages = self._pick(routable, seq)
        self._recorder.add(
            "route", seq.trace_id, t_route, time.perf_counter(),
            dest=self.schedulers.index(sched),
            hbm_hit=hit_pages[0], host_hit=hit_pages[1],
            fabric_hit=hit_pages[2])
        cap = self.server_cfg.admission_queue_depth
        if cap > 0 and sched.load >= cap:
            # The affinity pick can saturate a warm replica while a cold
            # sibling still has room: fall back to least-loaded before
            # shedding, so 429s only fire when the whole fleet is full —
            # then re-peek the fallback so the span/counters report its
            # real warmth, not a hardcoded cold.
            sched = self._pick(routable)[0]
            hit_pages = self._peek_replica(sched, seq)
            if sched.load >= cap:
                with self._lock:
                    self.requests_shed += 1
                # A shed IS terminal: seal the route span so sustained
                # overload can't fill the recorder's open table and
                # evict a LIVE request's trace.
                self._recorder.seal(seq.trace_id)
                raise FleetSaturated(
                    f"admission queue cap reached ({sched.load} >= {cap} "
                    "on the least-loaded replica)", self._retry_after())
        entry = _Tracked(template=_clone_request(seq), on_token=on_token,
                         on_finish=on_finish, sched=sched,
                         t_submit=time.perf_counter())
        with self._lock:
            self._tracked[seq.request_id] = entry
        self._dispatch(entry, seq, sched, hit_pages)

    def _dispatch(self, entry: _Tracked, seq: Sequence,
                  sched: EngineScheduler,
                  hit_pages: Tuple[int, int, int] = (0, 0, 0)) -> None:
        gen = entry.generation
        entry.sched = sched
        # Mark the span: attempt >= 1 means this is a failover
        # resubmission — the timeline/logs distinguish replays.
        seq.attempt = entry.attempts
        # Routing span + fleet accounting: every dispatch (initial or
        # failover resubmission) is one routing decision. hit_pages is
        # the tiered peek (hbm, host, fabric_extra) the router counted
        # on.
        idx = self.schedulers.index(sched)
        hbm_hit, host_hit, fabric_extra = hit_pages
        # Fabric pull (README "KV fabric"): pages the pool covers
        # beyond this replica's own warm depth land in its host tier
        # via request_import_host BEFORE dispatch — the engine loop
        # applies pending imports ahead of admission, so this request's
        # prefill sees them. crc-verified by get_pages; a corrupt or
        # evicted-since-peek entry just shortens the run.
        fabric_pulled = 0
        if fabric_extra > 0:
            digests = self._digests_for(seq)[0]
            warm = hbm_hit + host_hit
            entries = self.fabric.get_pages(
                digests[warm:warm + fabric_extra])
            if entries:
                sched.engine.request_import_host(entries)
                sched.kick()
                fabric_pulled = len(entries)
        seq.routed_replica = idx
        seq.route_hit_pages = hbm_hit + host_hit + fabric_pulled
        seq.route_host_hit_pages = host_hit
        seq.route_fabric_hit_pages = fabric_pulled
        total_hit = seq.route_hit_pages
        stats = self._route_stats[idx]
        if total_hit > 0:
            self.route_prefix_hits += 1
            stats["hits"] += 1
            stats["hit_pages"] += total_hit
            stats["host_hit_pages"] += host_hit
            self._route_hit_pages_hist.observe(total_hit)
        else:
            self.route_cold += 1
            stats["cold"] += 1
        if fabric_pulled > 0:
            self.route_fabric_hits += 1
            stats["fabric_hit_pages"] += fabric_pulled
            self._route_fabric_hit_pages_hist.observe(fabric_pulled)

        def tok(s: Sequence, t: int) -> None:
            if entry.generation != gen:     # stale attempt (failed over)
                return
            entry.delivered += 1
            entry.on_token(s, t)

        def fin(s: Sequence) -> None:
            self._attempt_finished(entry, s, gen)

        sched.submit(seq, tok, fin)

    def _retry_target(self, failed: EngineScheduler,
                      template: Optional[Sequence] = None
                      ) -> Optional[Tuple[EngineScheduler,
                                          Tuple[int, int, int]]]:
        """Replica for a failover resubmission (and its peeked hit
        pages): affinity composes with failover — the replay prefers a
        sibling already holding the prompt's pages, but never the
        scheduler that just failed when an alternative exists."""
        routable = self._routable()
        others = [s for s in routable if s is not failed]
        pool = others or routable           # degraded-but-routable self ok
        return self._pick(pool, template) if pool else None

    def _attempt_finished(self, entry: _Tracked, seq: Sequence,
                          gen: int) -> None:
        """Terminal or retryable end of one attempt (engine thread).

        The whole decision — is this attempt still current, does it
        retry, which counters move — happens under one lock hold, so it
        cannot interleave with _failover_stranded deciding about the
        same entry from the watchdog thread (whoever bumps generation
        first wins; the loser returns without acting)."""
        rid = entry.template.request_id
        with self._lock:
            if entry.generation != gen:     # stranded failover took over
                return
            if seq.finish_reason in _RETRYABLE:
                entry.failed_replicas.add(
                    self.schedulers.index(entry.sched))
            limit = self.server_cfg.poison_max_workers
            poison = (seq.finish_reason in _RETRYABLE and limit > 0
                      and len(entry.failed_replicas) >= limit)
            retryable = (not poison
                         and seq.finish_reason in _RETRYABLE
                         and entry.delivered == 0
                         and entry.attempts
                         < self.server_cfg.failover_max_retries)
            target = (self._retry_target(entry.sched, entry.template)
                      if retryable else None)
            if target is not None:
                entry.attempts += 1
                entry.generation += 1
                self.retries_attempted += 1
            else:
                self._tracked.pop(rid, None)
                if poison:
                    self.poison_requests += 1
                if entry.attempts and seq.finish_reason in ("stop", "length"):
                    self.retries_succeeded += 1
        if target is not None:
            self._dispatch(entry, _clone_request(entry.template), *target)
            return
        if poison:
            # Every attempt errored a DIFFERENT replica: quarantine the
            # request terminally (structured 500) before it burns the
            # rest of the fleet.
            telemetry.log_event(
                "poison_quarantined", level="error",
                request_id=entry.template.trace_id or str(rid),
                replicas=sorted(entry.failed_replicas),
                attempts=entry.attempts)
            seq.finish_reason = "poison"
        self._finish_trace(entry, seq.finish_reason)
        entry.on_finish(seq)

    def _finish_trace(self, entry: _Tracked, reason: str) -> None:
        """Terminal end of a tracked request: the router-side root span
        (submit -> terminal) + seal, mirroring the subprocess router.
        The engine-side recorders sealed their phase spans at the
        scheduler's finish; /debug/trace joins the two."""
        t = entry.template
        tid = t.trace_id or str(t.request_id)
        self._recorder.add("request", tid, entry.t_submit or
                           time.perf_counter(), time.perf_counter(),
                           parent="", reason=reason,
                           attempts=entry.attempts,
                           output_tokens=entry.delivered)
        self._recorder.seal(tid)

    def _failover_stranded(self, sched: EngineScheduler) -> None:
        """A replica was quarantined by the watchdog mid-dispatch: its
        engine thread may be stuck for minutes (or forever), so its
        requests cannot finish through callbacks. Detach them here and
        resubmit (zero tokens delivered, budget left) or fail them
        cleanly; flag the originals done so the wedged thread, if it ever
        wakes, reaps them instead of streaming into the void."""
        actions = []
        with self._lock:
            # Decide everything inside one lock hold (see
            # _attempt_finished): the generation bump atomically orphans
            # both late wake-up callbacks AND any _attempt_finished
            # racing from the wedged engine thread.
            limit = self.server_cfg.poison_max_workers
            for rid, entry in list(self._tracked.items()):
                if entry.sched is not sched:
                    continue
                entry.generation += 1
                entry.failed_replicas.add(self.schedulers.index(sched))
                poison = (limit > 0
                          and len(entry.failed_replicas) >= limit)
                target = self._retry_target(sched, entry.template)
                can_retry = (not poison
                             and entry.delivered == 0
                             and entry.attempts
                             < self.server_cfg.failover_max_retries
                             and target is not None)
                if can_retry:
                    entry.attempts += 1
                    self.retries_attempted += 1
                    self.failovers += 1
                else:
                    self._tracked.pop(rid, None)
                    if poison:
                        self.poison_requests += 1
                actions.append((rid, entry, can_retry, target, poison))
        for rid, entry, can_retry, target, poison in actions:
            sched.cancel(rid)               # reap-on-wake; frees queue slot
            telemetry.log_event(
                "request_failover", level="warning",
                request_id=entry.template.trace_id or str(rid),
                resubmitted=can_retry, attempts=entry.attempts)
            if can_retry:
                self._dispatch(entry, _clone_request(entry.template), *target)
            else:
                if poison:
                    telemetry.log_event(
                        "poison_quarantined", level="error",
                        request_id=entry.template.trace_id or str(rid),
                        replicas=sorted(entry.failed_replicas),
                        attempts=entry.attempts)
                ghost = _clone_request(entry.template)
                ghost.done = True
                ghost.finish_reason = ("poison" if poison
                                       else "unavailable" if target is None
                                       else "error")
                ghost.finish_time = time.perf_counter()
                self._finish_trace(entry, ghost.finish_reason)
                entry.on_finish(ghost)

    def cancel(self, request_id: int) -> None:
        # Pop (not get): a request cancelled while still QUEUED never
        # reaches _finish/on_finish, so the tracked entry must be released
        # here or it leaks one dict entry per timed-out/disconnected
        # request. Double-pop from a later on_finish is harmless.
        with self._lock:
            entry = self._tracked.pop(request_id, None)
            if entry is not None:
                entry.generation += 1       # silence in-flight callbacks
        if entry is not None:
            entry.sched.cancel(request_id)

    # ----------------------------------------------------- observability

    def health_snapshot(self) -> dict:
        """Operator view served by /healthz: per-replica states + fleet
        status + shed/retry counters."""
        replicas = []
        for i, (h, e) in enumerate(zip(self.health, self.engines)):
            d = h.snapshot()
            # KV-pool pressure view: operators (and load balancers) see
            # which replicas are burning headroom before they quarantine.
            d["pool_pressure"] = round(e.pool_pressure, 4)
            d["under_pressure"] = e.under_pressure
            d["preemptions"] = e.preemptions_total
            # Affinity view: warm/cold dispatches this replica received
            # and the cached pages the router counted on — the numbers
            # that say whether conversations are actually sticking.
            d["routing"] = dict(self._route_stats[i])
            # Rolling SLO view (quantiles + breach counts).
            if e.telemetry.slo is not None:
                d["slo"] = e.telemetry.slo.snapshot(include_window=False)
            # Tiered KV cache view: host-tier residency + swap churn
            # (absent when the tier is disabled on this replica).
            if e.host_pool is not None:
                d["host_cache"] = {
                    "capacity_pages": e.host_pool.capacity,
                    "pages_used": e.host_pool.used,
                    "offloaded": e.host_pool.offloaded_total,
                    "restored": e.host_pool.restored_total,
                    "evicted": e.host_pool.evicted_total,
                    "swap_in_resumes": e.swap_in_resumes,
                }
            replicas.append(d)
        routable = sum(1 for h in self.health if h.routable)
        if routable == 0:
            status = "unavailable"
        elif all(r["state"] == HEALTHY for r in replicas):
            status = "ok"
        else:
            status = "degraded"
        return {
            "status": status,
            "routing": self.server_cfg.routing,
            "replicas": replicas,
            # Fleet-aggregated rolling SLO view (pooled exact
            # quantiles; the autoscaler's input signal).
            "slo": self._fleet_slo(),
            # Fleet KV fabric pool occupancy + churn (README "KV
            # fabric"); same shape under both fleet backends.
            "fabric": self.fabric.snapshot(),
            "supervision": self.supervision_counters(),
        }

    def supervision_counters(self) -> dict:
        with self._lock:
            return {
                "retries_attempted": self.retries_attempted,
                "retries_succeeded": self.retries_succeeded,
                "failovers": self.failovers,
                "requests_shed": self.requests_shed,
                "requests_unavailable": self.requests_unavailable,
                "poison_requests": self.poison_requests,
                "kv_integrity_rejections": sum(
                    e.kv_integrity_rejections for e in self.engines)
                + self.fabric.kv_rejections,
                "route_prefix_hits": self.route_prefix_hits,
                "route_cold": self.route_cold,
                "route_fabric_hits": self.route_fabric_hits,
                "fabric_puts": self.fabric.puts,
                "fabric_hits": self.fabric.hits,
                "preemptions": sum(e.preemptions_total
                                   for e in self.engines),
                "recompute_resumes": sum(e.resumes_total
                                         for e in self.engines),
                "states": [h.state for h in self.health],
            }

    def prometheus_text(self) -> str:
        """Standards-compliant Prometheus text page: every replica's
        engine registry under a ``replica="i"`` label plus the fleet
        registry (supervision counters, replica health gauges)."""
        groups = [({"replica": str(i)}, s.engine.telemetry.registry)
                  for i, s in enumerate(self.schedulers)]
        groups.append(({}, self._fleet_registry))
        return telemetry.render_prometheus(groups)

    def recent_snapshot(self, n: int) -> List[dict]:
        """Most recent n finished-request timelines ACROSS replicas
        (merged by completion time — a plain tail would show only the
        last replica's view)."""
        items: List[dict] = []
        for s in self.schedulers:
            items.extend(s.recent_snapshot(n))
        items.sort(key=lambda t: t.get("finished_unix", 0.0))
        return items[-n:]

    # -------------------------------------------- tracing + profiling

    def _trace_spans(self, trace_id: str) -> List[dict]:
        spans = self._recorder.get_trace(trace_id) or []
        for e in self.engines:
            spans.extend(e.telemetry.recorder.get_trace(trace_id) or ())
        return spans

    def trace_snapshot(self, trace_id: str) -> Optional[dict]:
        """One request's assembled span tree (GET /debug/trace?id=):
        router-side spans + every replica recorder's spans for the
        trace, joined in place (no transport in-process)."""
        spans = self._trace_spans(trace_id)
        if not spans:
            return None
        return telemetry.assemble_trace(trace_id, spans)

    def trace_chrome(self, n: int = 128) -> dict:
        """The recent-request ring as Chrome trace-event JSON (GET
        /debug/trace?format=chrome), one pid per replica + pid 0 for
        the group's routing spans — loadable in Perfetto."""
        traces = {tid: self._trace_spans(tid)
                  for tid in self._recorder.recent_traces(n)}
        maintenance: List[dict] = []
        for e in self.engines:
            maintenance.extend(e.telemetry.recorder.maintenance_spans())
        return telemetry.spans_to_chrome(
            traces,
            {0: "router", **{i + 1: f"replica {i}"
                             for i in range(len(self.engines))}},
            maintenance=maintenance,
            other_data={"fleet": self.server_cfg.fleet,
                        "spans_dropped": self._recorder.spans_dropped})

    def capture_profile(self, replica: int, seconds: float) -> dict:
        """POST /debug/profile {"seconds": N}: run a jax.profiler
        capture in this process (all in-process replicas share one jax
        runtime, so the replica argument only names the trace dir)."""
        return telemetry.capture_jax_profile(
            self.server_cfg.profile_dir, replica, seconds)

    def stats_snapshot(self) -> dict:
        """Aggregate counters + per-replica breakdown."""
        per = [s.stats.snapshot(s.engine) for s in self.schedulers]
        for d, h in zip(per, self.health):
            d["health"] = h.snapshot()
        return aggregate_replica_stats(per, self.supervision_counters())

    def steps_snapshot(self) -> dict:
        """Step-ledger roofline attribution (GET /debug/steps):
        per-replica bottleneck verdicts + the fleet-merged report."""
        reports = {str(i): e.telemetry.steps_report()
                   for i, e in enumerate(self.engines)}
        return {"replicas": reports,
                "fleet": telemetry.merge_steps_reports(
                    list(reports.values()))}

    def blackbox_index(self) -> dict:
        """Flight-recorder capture index (GET /debug/blackbox) — scans
        the operator's blackbox_dir; every replica is in-process here,
        so there is nothing to harvest, only to list."""
        return telemetry.blackbox_index(self.server_cfg.blackbox_dir)

    def apply_chaos(self, body: dict) -> dict:
        """Arm/disarm engine-level fault injection (POST /debug/chaos):
        ``{"replica": i | null, "step_failure_rate": p, "step_wedge_s":
        s, "page_pressure": n}`` — null replica applies to all. The
        subprocess fleet adds process-level verbs ("kill"); here they
        are a usage error (there is no process to kill in-process —
        chaos_step_wedge_s is the in-process simulation). Raises
        ValueError/IndexError/TypeError on bad specs (HTTP 400)."""
        if body.get("kill") is not None:
            raise ValueError(
                "'kill' chaos (kill9/sigterm) needs --fleet subprocess; "
                "the in-process fleet simulates faults via "
                "step_failure_rate / step_wedge_s / page_pressure")
        engines = self.engines
        replica = body.get("replica")
        targets = (engines if replica is None
                   else [engines[int(replica)]])
        rate = body.get("step_failure_rate")
        wedge = body.get("step_wedge_s")
        pressure = body.get("page_pressure")
        for eng in targets:
            if rate is not None:
                eng.chaos_step_failure_rate = float(rate)
            if wedge is not None:
                eng.chaos_step_wedge_s = float(wedge)
            if pressure is not None:
                # Holds real pages out of the KV pool (clamped to
                # what's free) — deterministic exhaustion testing.
                # Applied by the engine loop (the allocator is
                # engine-thread only), usually within milliseconds.
                eng.request_page_pressure(int(pressure))

        def _pp(e):
            t = e._pressure_target
            return e.chaos_page_pressure if t is None else t

        return {"replicas": [
            {"step_failure_rate": e.chaos_step_failure_rate,
             "step_wedge_s": e.chaos_step_wedge_s,
             "page_pressure": _pp(e)} for e in engines]}


# Per-chip gauges / config constants that must not be summed across
# replicas. KV page counts SUM (total and in_use together, so fleet
# utilization = in_use/total stays consistent); depth is config.
_NON_ADDITIVE = ("model_params", "approx_flops_per_token",
                 "mean_batch_occupancy", "decode_pipeline_depth",
                 "pool_pressure",
                 # Batch ladder: rung/occupancy are per-replica
                 # states (summing rungs would fabricate a fleet
                 # batch size); re-aggregated below. rung_switches
                 # stays additive (a fleet churn total).
                 "decode_rung", "rung_peak", "lane_occupancy",
                 "mfu_estimate")


def aggregate_replica_stats(per: List[dict], supervision: dict) -> dict:
    """Fold per-replica scheduler snapshots into the fleet stats dict —
    THE aggregation rule, shared by both fleet backends (EngineGroup
    over live scheduler objects; ProcessEngineGroup over stats dicts
    fetched from worker processes), so /metrics?format=json has one
    shape regardless of --fleet."""
    if len(per) == 1:
        out = dict(per[0])
        if isinstance(out.get("slo"), dict):
            # Same window-stripping as the dp>1 path (a copy — the
            # caller may cache the original, windows included).
            out["slo"] = {k: v for k, v in out["slo"].items()
                          if not k.endswith("_window")}
        out["supervision"] = supervision
        return out
    agg = dict(per[0])
    for d in per[1:]:
        for k, v in d.items():
            if (k in _NON_ADDITIVE or isinstance(v, bool)
                    or not isinstance(v, (int, float))):
                continue
            base = agg.get(k, 0)
            agg[k] = (base if isinstance(base, (int, float))
                      and not isinstance(base, bool) else 0) + v
    # Replica 0's health dict would masquerade as the fleet's;
    # per-replica health lives under "replicas", fleet under
    # "supervision". Same for the phase role — a P/D fleet's replicas
    # differ by design, and supervision carries the full role list.
    agg.pop("health", None)
    agg.pop("role", None)
    # Rolling SLO: fleet quantiles must POOL the replicas' raw windows
    # (summing or averaging per-replica quantiles fabricates numbers).
    # After pooling, the ~512-entry windows are stripped from the
    # per-replica views COPIES (never the caller's dicts — the
    # subprocess router caches them, windows included, for its pooled
    # gauges): they exist for this aggregation, not for every scrape
    # to carry kilobytes of raw floats.
    if any("slo" in d for d in per):
        agg["slo"] = telemetry.pooled_slo([d.get("slo") for d in per])
        per = [({**d, "slo": {k: v for k, v in d["slo"].items()
                              if not k.endswith("_window")}}
                if isinstance(d.get("slo"), dict) else d)
               for d in per]
    # Fleet phase histograms = element-wise bucket merge across
    # replicas (replica 0's copy would otherwise masquerade as the
    # fleet's); per-replica views stay under "replicas".
    phase_keys = sorted(set().union(
        *(d.get("phases", {}).keys() for d in per)))
    agg["phases"] = {
        k: telemetry.merge_phases(
            [d.get("phases", {}).get(k) for d in per])
        for k in phase_keys}
    agg["mean_batch_occupancy"] = (
        sum(d.get("mean_batch_occupancy", 0.0) for d in per) / len(per))
    # Batch ladder fleet view: active/peak rung = the highest any
    # replica runs (replica 0's copy must not masquerade as the
    # fleet's); occupancy/MFU = fleet means; decode_ladder is the
    # one shared EngineConfig's rungs, identical on every replica.
    # Replica detail stays under "replicas".
    agg["decode_rung"] = max(d.get("decode_rung", 0) for d in per)
    agg["rung_peak"] = max(d.get("rung_peak", 0) for d in per)
    agg["lane_occupancy"] = round(
        sum(d.get("lane_occupancy", 0.0) for d in per) / len(per), 4)
    mfus = [d["mfu_estimate"] for d in per
            if d.get("mfu_estimate") is not None]
    agg["mfu_estimate"] = (round(sum(mfus) / len(mfus), 6)
                           if mfus else None)
    if "prefix_cache" in per[0]:
        agg["prefix_cache"] = {
            k: sum(d.get("prefix_cache", {}).get(k, 0) for d in per)
            for k in per[0]["prefix_cache"]}
    # Fleet decode-dispatch latency = element-wise worst replica (an
    # operator alarms on p99; replica 0's copy masquerading as the
    # fleet number would hide a degraded replica).
    rings = [d.get("decode_call_s") for d in per]
    rings = [r for r in rings if r]
    agg["decode_call_s"] = (
        {k: max(r[k] for r in rings if k in r) for k in rings[0]}
        if rings else None)
    if "speculative" in per[0]:
        specs = [d.get("speculative") or {} for d in per]
        drafted = sum(s.get("drafted", 0) for s in specs)
        accepted = sum(s.get("accepted", 0) for s in specs)
        agg["speculative"] = {
            # Mode/γ are one shared EngineConfig, identical on every
            # replica; counters sum across the fleet.
            "mode": specs[0].get("mode"),
            "gamma": specs[0].get("gamma"),
            "drafted": drafted, "accepted": accepted,
            "acceptance_rate": (accepted / drafted) if drafted else 0.0,
            "rounds": sum(s.get("rounds", 0) for s in specs),
            "fallback_rounds": sum(s.get("fallback_rounds", 0)
                                   for s in specs),
            "throttles": sum(s.get("throttles", 0) for s in specs)}
    agg["replicas"] = per
    agg["dp"] = len(per)
    agg["supervision"] = supervision
    return agg
