"""Data-parallel replica serving: dp independent engines behind one facade.

``ParallelConfig.dp`` used to replicate params inside ONE engine (useful
for the sharding dry-run, useless for throughput: one scheduler, one
decode batch). True dp serving is replica-per-group — each replica owns a
``tp*sp``-device submesh, its own KV pool, and its own continuous-batching
scheduler thread; the HTTP layer routes each request to the least-loaded
replica. The reference's analogue is the load balancer in front of its
external endpoint (implicit, out of repo — SURVEY.md §0); here it is
in-process.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.engine.scheduler import EngineScheduler


class EngineGroup:
    """dp EngineSchedulers with least-loaded request routing.

    With one engine this is a transparent pass-through, so the server
    always talks to an EngineGroup.
    """

    def __init__(self, engines: List[InferenceEngine]):
        assert engines
        self.engines = engines
        self.schedulers = [EngineScheduler(e) for e in engines]
        # request_id -> scheduler that owns it (ids are globally unique).
        self._owner = {}

    @property
    def engine(self) -> InferenceEngine:
        """Primary replica (single-engine callers, tests)."""
        return self.engines[0]

    def warmup(self) -> float:
        return sum(e.warmup() for e in self.engines)

    def start(self) -> "EngineGroup":
        for s in self.schedulers:
            s.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        for s in self.schedulers:
            s.stop(drain=drain, timeout=timeout)

    def _least_loaded(self) -> EngineScheduler:
        def load(s: EngineScheduler) -> int:
            return len(s._waiting) + len(s.engine.active_sequences())

        return min(self.schedulers, key=load)

    def embed_many(self, batch) -> "np.ndarray":  # noqa: F821
        """Embeddings on the least-loaded replica — pinning them to
        replica 0 would interleave dense forwards with its decode loop
        while the other replicas idle."""
        return self._least_loaded().engine.embed_many(batch)

    def submit(self, seq: Sequence, on_token: Callable,
               on_finish: Callable) -> None:
        sched = self._least_loaded()
        self._owner[seq.request_id] = sched

        def done(s: Sequence) -> None:
            self._owner.pop(s.request_id, None)
            on_finish(s)

        sched.submit(seq, on_token, done)

    def cancel(self, request_id: int) -> None:
        # Pop (not get): a request cancelled while still QUEUED never
        # reaches _finish/on_finish, so the owner entry must be released
        # here or it leaks one dict entry per timed-out/disconnected
        # request. Double-pop from a later on_finish is harmless.
        sched = self._owner.pop(request_id, None)
        if sched is not None:
            sched.cancel(request_id)

    def recent_snapshot(self, n: int) -> List[dict]:
        """Most recent n finished-request timelines ACROSS replicas
        (merged by completion time — a plain tail would show only the
        last replica's view)."""
        items: List[dict] = []
        for s in self.schedulers:
            items.extend(s.recent_snapshot(n))
        items.sort(key=lambda t: t.get("finished_unix", 0.0))
        return items[-n:]

    # Per-chip gauges / config constants that must not be summed across
    # replicas. KV page counts SUM (total and in_use together, so fleet
    # utilization = in_use/total stays consistent); depth is config.
    _NON_ADDITIVE = ("model_params", "approx_flops_per_token",
                     "mean_batch_occupancy", "decode_pipeline_depth")

    def stats_snapshot(self) -> dict:
        """Aggregate counters + per-replica breakdown."""
        per = [s.stats.snapshot(s.engine) for s in self.schedulers]
        if len(per) == 1:
            return per[0]
        agg = dict(per[0])
        for d in per[1:]:
            for k, v in d.items():
                if (k in self._NON_ADDITIVE or isinstance(v, bool)
                        or not isinstance(v, (int, float))):
                    continue
                agg[k] = agg.get(k, 0) + v
        agg["mean_batch_occupancy"] = (
            sum(d["mean_batch_occupancy"] for d in per) / len(per))
        if "prefix_cache" in per[0]:
            agg["prefix_cache"] = {
                k: sum(d["prefix_cache"][k] for d in per)
                for k in per[0]["prefix_cache"]}
        # Fleet decode-dispatch latency = element-wise worst replica (an
        # operator alarms on p99; replica 0's copy masquerading as the
        # fleet number would hide a degraded replica).
        rings = [d.get("decode_call_s") for d in per]
        rings = [r for r in rings if r]
        agg["decode_call_s"] = (
            {k: max(r[k] for r in rings) for k in rings[0]} if rings
            else None)
        if "speculative" in per[0]:
            drafted = sum(d["speculative"]["drafted"] for d in per)
            accepted = sum(d["speculative"]["accepted"] for d in per)
            agg["speculative"] = {
                "drafted": drafted, "accepted": accepted,
                "acceptance_rate": (accepted / drafted) if drafted else 0.0}
        agg["replicas"] = per
        agg["dp"] = len(per)
        return agg
