"""Process fleet router: ``EngineGroup`` semantics over worker processes.

The out-of-process half of ROADMAP item 3 (README "Process fleet").
``ProcessEngineGroup`` implements the same facade as the in-process
``EngineGroup`` (submit/cancel, health/stats/metrics/recent snapshots,
prefix-affinity routing, failover, admission control) behind
``--fleet subprocess``, but each dp replica is its own engine-worker OS
process (server/worker.py) speaking the length-prefixed JSON RPC over a
local unix socket — so a worker fault (wedge, crash, ``kill -9``) is one
process, not the whole fleet, and the GIL stops being the dp ceiling.

Supervision: a monitor thread restarts dead workers with doubling
backoff up to ``ServerConfig.worker_restart_max`` per worker, keeping
the ``replica="i"`` metrics label STABLE across incarnations — counter
and histogram series from dead incarnations fold into a per-replica
carry (telemetry.fold_dump_into_carry) so the aggregated /metrics scrape
never resets or double-reports across a restart.

Failure handling replaces the two recompute burns with better moves:

- graceful drain (SIGTERM / drain RPC): the worker exports each live
  request's KV pages (host serialization layout) as ``migrate`` events;
  the router imports them into the destination's host tier and resubmits
  with the streamed-token record, so admission there is a
  swap-in-resume (engine.swap_in_resumes) instead of a re-prefill.
- ``kill -9`` mid-decode: no export is possible, so the router falls
  back to resubmission failover — it replays its own token record as a
  recompute-resume on a survivor (token-identical under greedy), and
  the client stream continues where it left off.

Routing stays PR-5/PR-6 three-temperature prefix affinity: the router
hashes each prompt once and probes every worker's cache tiers through
the side-effect-free ``peek`` RPC, scoring with the same formula as
EngineGroup._pick. Tokens stream through the router without buffering
(one event frame per token, forwarded as it arrives).
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from tpu_inference import telemetry
from tpu_inference.config import (FrameworkConfig, class_rank,
                                  framework_config_to_dict,
                                  resolve_worker_roles)
from tpu_inference.engine import kv_cache as kvc
from tpu_inference.engine.engine import Sequence
from tpu_inference.engine.prefix_cache import _chain_hashes
from tpu_inference.server import kv_fabric, shm_arena
from tpu_inference.server.replicas import (FleetSaturated, FleetUnavailable,
                                           _RETRYABLE, _clone_request,
                                           aggregate_replica_stats)
from tpu_inference.server.transport import (ChaosPolicy, ChaosTransport,
                                            FrameError, recv_frame,
                                            send_frame)


class WorkerGone(ConnectionError):
    """RPC failed because the worker's process/connection died."""


# Per-verb deadline classes (README "Failure model"): every RPC site
# resolves its budget from ServerConfig.rpc_deadline_{fast,slow}_s via
# this table instead of hard-coding a blanket wait. "fast" verbs answer
# from memory; "slow" verbs touch the engine loop or move KV bytes.
# hello/shutdown/embed/profile keep explicit budgets at their call
# sites (boot compile, exit drain, batch forward, profiler capture).
_SLOW_RPC_VERBS = ("submit", "import-kv", "drain")

# Consecutive same-connection RPC timeouts before the router declares
# the connection wedged and recycles it (reconnect, not restart) —
# a silent socket heals without paying a worker boot.
_WEDGE_TIMEOUTS = 3

# How long a failed re-route keeps re-picking before the request fails
# "unavailable". Covers the connection-level failover window (redial +
# hello, bounded by the 5 s connect timeout) and most of a worker
# restart, so a momentary client gap never kills a request outright.
_REROUTE_GRACE_S = 10.0


class WorkerClient:
    """One live RPC connection to one worker incarnation. Requests are
    correlated by id; unsolicited event frames dispatch to the group's
    handler on this client's reader thread."""

    def __init__(self, path: str, proc: subprocess.Popen,
                 connect_timeout: float = 1800.0, replica: int = -1,
                 deadlines: Optional[dict] = None,
                 chaos: Optional[ChaosTransport] = None):
        import socket as _socket

        deadline = time.monotonic() + connect_timeout
        last_err: Optional[Exception] = None
        self.sock = None
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise WorkerGone(
                    f"worker exited rc={proc.returncode} before accepting")
            s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            try:
                s.connect(path)
                self.sock = s
                break
            except OSError as e:
                last_err = e
                s.close()
                time.sleep(0.05)
        if self.sock is None:
            raise WorkerGone(f"could not connect to worker: {last_err}")
        self.rfile = self.sock.makefile("rb")
        self._wlock = threading.Lock()
        self._ids = itertools.count(1)
        self._pending: Dict[int, dict] = {}
        self._plock = threading.Lock()
        self.alive = True
        self.replica = replica
        self.deadlines = deadlines or {}
        self.chaos = chaos
        # Why the reader died, for the group's supervision accounting:
        # "" (clean/unknown) | "frame_error" | "stream_gap".
        self.lost_reason = ""
        self._consec_timeouts = 0
        self.on_event: Optional[Callable] = None     # set by the group
        self.on_lost: Optional[Callable] = None
        self.on_timeout: Optional[Callable] = None   # (verb, timeout_s)
        self._reader = threading.Thread(target=self._read_loop,
                                        name="fleet-worker-reader",
                                        daemon=True)

    def start_reader(self) -> None:
        self._reader.start()

    def close(self) -> None:
        self.alive = False
        try:
            # shutdown() — not just close() — is what actually wakes
            # the reader thread parked in recv(): closing the fd alone
            # leaves it blocked forever, on_lost never fires, and a
            # wedged connection would never be recycled.
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    def resolve_deadline(self, verb: str) -> float:
        if verb in _SLOW_RPC_VERBS:
            return float(self.deadlines.get("slow", 60.0))
        return float(self.deadlines.get("fast", 10.0))

    def rpc(self, verb: str, timeout: Optional[float] = None,
            blob: bytes = b"", **kw) -> dict:
        """Send one request frame and wait for its reply. ``timeout``
        None resolves the verb's deadline class. Raises WorkerGone on a
        dead connection, TimeoutError past the deadline (emitting a
        structured ``rpc_timeout`` event and recycling the connection
        after _WEDGE_TIMEOUTS consecutive ones), RuntimeError on an
        error reply."""
        if not self.alive:
            raise WorkerGone("connection closed")
        if timeout is None:
            timeout = self.resolve_deadline(verb)
        rid = next(self._ids)
        waiter = {"evt": threading.Event(), "reply": None}
        with self._plock:
            self._pending[rid] = waiter
        msg = {"id": rid, "verb": verb}
        msg.update(kw)
        try:
            with self._wlock:
                send_frame(self.sock, msg, blob, chaos=self.chaos,
                           verb=verb, direction="send")
        except (OSError, ConnectionError) as e:
            with self._plock:
                self._pending.pop(rid, None)
            raise WorkerGone(str(e))
        if not waiter["evt"].wait(timeout):
            with self._plock:
                self._pending.pop(rid, None)
            if not self.alive:
                raise WorkerGone("connection lost mid-RPC")
            self._consec_timeouts += 1
            telemetry.log_event("rpc_timeout", level="warning",
                                verb=verb, replica=self.replica,
                                timeout_s=round(float(timeout), 3),
                                consecutive=self._consec_timeouts)
            if self.on_timeout is not None:
                self.on_timeout(verb, float(timeout))
            if self._consec_timeouts >= _WEDGE_TIMEOUTS:
                # The socket is open but mute — a wedged connection.
                # Close it: the reader's on_lost runs the reconnect
                # path (the process is alive), not a worker restart.
                self.lost_reason = self.lost_reason or "wedged"
                self.close()
            raise TimeoutError(f"worker RPC {verb!r} timed out "
                               f"after {timeout:.1f}s")
        self._consec_timeouts = 0
        reply = waiter["reply"]
        if reply is None or not reply[0].get("ok", False):
            err = (reply[0].get("error", "worker error") if reply
                   else "connection lost")
            kind = reply[0].get("kind", "") if reply else "gone"
            if kind in ("gone", "draining"):
                raise WorkerGone(err)
            raise RuntimeError(f"worker RPC {verb!r}: {err}")
        return reply[0]

    def _read_loop(self) -> None:
        try:
            while True:
                obj, blob = recv_frame(self.rfile)
                if "ev" in obj:
                    if self.on_event is not None:
                        self.on_event(self, obj, blob)
                    continue
                with self._plock:
                    waiter = self._pending.pop(obj.get("id"), None)
                if waiter is not None:
                    waiter["reply"] = (obj, blob)
                    waiter["evt"].set()
        except FrameError as e:
            # Malformed frame (desync, truncation, checksum, garbage
            # lengths): the stream cannot be trusted past this point —
            # recycle the connection; the process itself may be fine.
            self.lost_reason = self.lost_reason or "frame_error"
            telemetry.log_event("frame_error", level="warning",
                                replica=self.replica,
                                reason=getattr(e, "reason", ""),
                                error=str(e))
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass
        finally:
            self.alive = False
            with self._plock:
                pending, self._pending = self._pending, {}
            for waiter in pending.values():
                waiter["evt"].set()
            if self.on_lost is not None:
                self.on_lost(self)


# Worker lifecycle states.
BOOTING = "booting"
UP = "up"
DRAINING = "draining"
RESTARTING = "restarting"
DEAD = "dead"           # router teardown
# Crash-loop breaker tripped (restart budget exhausted): the replica is
# routed around and VISIBLE — in /healthz and the
# tpu_inf_worker_quarantined gauge — rather than silently absent.
QUARANTINED = "quarantined"
# Intentional exit: scaled down by the autoscaler or replaced by a
# rolling upgrade. Never respawned, excluded from fleet health math.
RETIRED = "retired"


class WorkerHandle:
    """Supervision state for one replica slot across incarnations. The
    replica index (and its metrics label) is stable; the process, socket
    and client change per restart."""

    def __init__(self, replica: int):
        self.replica = replica
        self.state = BOOTING
        self.proc: Optional[subprocess.Popen] = None
        self.client: Optional[WorkerClient] = None
        self.socket_path = ""
        self.incarnation = 0
        self.restarts = 0               # successful respawns
        self.consecutive_failures = 0   # backoff driver
        self.restart_at = 0.0           # monotonic deadline for respawn
        self.started_unix = 0.0
        self.pid: Optional[int] = None
        self.info: dict = {}
        self.last_stats: dict = {}
        self.last_metrics: list = []
        self.last_health: dict = {}
        self.last_steps: dict = {}
        # Monotonic-series carry from dead incarnations (telemetry.
        # fold_dump_into_carry) — the restart-survival half of the
        # stable replica label. folded_incarnation makes the fold
        # idempotent: the drained event and the monitor's process-exit
        # detection can both report one death.
        self.carry: Dict[tuple, dict] = {}
        self.folded_incarnation = 0
        # SLO breach totals from dead incarnations: the fleet-level
        # tpu_inf_slo_breaches_total sums live worker counts on top of
        # this, so a worker restart never makes the fleet counter
        # decrease (Prometheus rate() reads any dip as a reset).
        self.slo_breach_carry = {"ttft": 0, "tpot": 0}
        # Intentional-exit marker (scale-down / rollout): the monitor's
        # death handler retires this worker instead of respawning it.
        self.retiring = False

    @property
    def routable(self) -> bool:
        return self.state == UP


class _Tracked:
    """Router-side state for one in-flight request across attempts,
    workers, and migrations."""

    __slots__ = ("template", "on_token", "on_finish", "worker", "client",
                 "generation", "attempts", "tokens", "seq_local",
                 "resume_stream_len", "t_submit", "handoff_blob",
                 "handoff_desc", "handoff_meta", "failed_workers")

    def __init__(self, template: Sequence, on_token, on_finish):
        self.template = template
        self.on_token = on_token
        self.on_finish = on_finish
        self.worker: Optional[WorkerHandle] = None
        self.client: Optional[WorkerClient] = None
        self.generation = 0
        self.attempts = 0
        # Every token streamed to the caller, in order — the failover
        # record that lets a killed worker's mid-stream request
        # recompute-resume on a survivor instead of failing.
        self.tokens: List[int] = []
        self.seq_local = _clone_request(template)
        # Tokens the latest resume-resubmission re-prefilled (prompt +
        # replayed generated), for the migrated-vs-recomputed accounting.
        self.resume_stream_len = 0
        self.t_submit = time.perf_counter()
        # P/D handoff state (README "P/D disaggregation"): the prefill
        # worker's live KV export (wire blob + {ctx_len, n_generated}).
        # Kept across retries — valid whenever the router's token record
        # still matches n_generated, so a decode-worker death right
        # after a handoff can re-adopt elsewhere; once decode advanced
        # past the export, resubmission falls back to recompute-resume.
        self.handoff_blob: Optional[bytes] = None
        # Zero-copy variant (README "KV data plane"): the export's
        # shared-memory arena descriptor — the payload never entered
        # this process; the decode worker adopts straight from the
        # arena, crc-verified there, with the blob path as fallback.
        self.handoff_desc: Optional[dict] = None
        self.handoff_meta: Optional[dict] = None
        # Poison-quarantine evidence: replica indices whose worker this
        # request's attempts CRASHED or WEDGED (not mere step errors —
        # those retry via the normal path). At poison_max_workers
        # distinct victims the request is failed terminally instead of
        # marching through the fleet.
        self.failed_workers: set = set()


class _EngineInfo:
    """Model/engine facts the HTTP layer reads off ``group.engine``
    (/api/ps, /api/show, boot prints), fetched once from worker 0's
    hello RPC. ``prefix_cache`` mimics the engine attribute's truthiness
    (the HTTP layer only checks ``is not None``)."""

    def __init__(self, hello: dict):
        self.n_params = hello.get("n_params", 0)
        self.weight_bytes = hello.get("weight_bytes", 0)
        self.attn_backend = hello.get("attn_backend", "?")
        self.ladder = tuple(hello.get("ladder") or (1,))
        self.swa_evict = hello.get("swa_evict", False)
        self.prefix_cache = True if hello.get("prefix_cache") else None
        self.spec_draft = hello.get("spec_draft", False)
        self.host_pool = None


class ProcessEngineGroup:
    """Router + N engine-worker processes behind the EngineGroup facade
    (``ServerConfig.fleet = "subprocess"``)."""

    def __init__(self, cfg: FrameworkConfig):
        pcfg = cfg.parallel
        self.cfg = cfg
        self.server_cfg = cfg.server
        self.engine_cfg = cfg.engine
        self.dp = max(1, pcfg.dp)
        # Worker phase roles (README "P/D disaggregation"): one per
        # replica, "mixed" everywhere unless ServerConfig.worker_roles /
        # EngineConfig.role say otherwise. pd_enabled gates the phase-
        # aware routing below; an all-mixed fleet behaves exactly as
        # before.
        # A list, not a tuple: scale-ups and rollout successors append
        # their role at the new replica index.
        self.roles = list(resolve_worker_roles(
            self.dp, cfg.server.worker_roles,
            default_role=cfg.engine.role))
        self.pd_enabled = any(r != "mixed" for r in self.roles)
        if self.pd_enabled and (
                all(r == "decode" for r in self.roles)
                or all(r == "prefill" for r in self.roles)):
            telemetry.log_event(
                "pd_roles_one_sided", level="warning",
                roles=list(self.roles),
                note="a P/D split needs both phases; this fleet will "
                     "serve via the fallback pools (lazy compiles)")
        self.workers = [WorkerHandle(i) for i in range(self.dp)]
        self._sock_dir = tempfile.mkdtemp(prefix="tpuinf-fleet-")
        self._started = False
        self._stopping = False
        self._start_lock = threading.Lock()
        self._lock = threading.Lock()
        self._tracked: Dict[int, _Tracked] = {}
        self._monitor_stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.engine: Optional[_EngineInfo] = None
        self.warmup_total_s = 0.0
        # Fleet counters — the same supervision family as EngineGroup
        # (torn-read-tolerant plain ints) plus the process-fleet extras.
        self.retries_attempted = 0
        self.retries_succeeded = 0
        self.failovers = 0
        self.requests_shed = 0
        self.requests_unavailable = 0
        self.route_prefix_hits = 0
        self.route_cold = 0
        self.route_fabric_hits = 0      # dispatches that pulled fabric pages
        self.migrations = 0             # drain exports received
        self.migrated_pages = 0
        self.migrated_bytes = 0
        self.resume_resubmits = 0       # resume-replay resubmissions
        self.resume_recomputed_tokens = 0
        self.resume_reused_tokens = 0
        # P/D disaggregation counters: handoff events received, and
        # handoffs whose resubmission had to recompute (stale blob /
        # no adopter) instead of adopting cleanly.
        self.pd_handoffs = 0
        self.pd_handoff_recomputes = 0
        # Byzantine-transport counters (README "Failure model"):
        # connection-level failovers (reconnect+resync, no restart),
        # structured RPC deadline hits, malformed frames the router
        # rejected, corrupt KV blobs rejected router-side, and
        # poison-quarantined requests.
        self.reconnects = 0
        self.rpc_timeouts = 0
        self.frame_errors = 0
        self.kv_rejections = 0
        self.poison_requests = 0
        # Per-verb deadline classes every RPC site resolves through
        # (satellite: the blanket-60 s audit).
        self._deadlines = {"fast": cfg.server.rpc_deadline_fast_s,
                           "slow": cfg.server.rpc_deadline_slow_s}
        # Transport chaos policy (config knobs now, /debug/chaos rpc
        # updates later). One ChaosPolicy per replica so the wedge
        # targets exactly chaos_rpc_wedge_replica and per-replica seeds
        # decorrelate; wedge_spent on the policy makes the wedge
        # one-shot across that replica's reconnects.
        self._chaos_rpc_kw = self._chaos_kw_from_cfg(cfg.server)
        self._chaos_policies: Dict[int, ChaosPolicy] = {}
        # Router-side crash flight recorder: poison quarantines and
        # corrupt-blob rejections capture the router's view (replica -1
        # under the shared blackbox dir) so the offending payload's
        # metadata survives for postmortem.
        self._flight = telemetry.attach_router_flight_recorder(
            cfg.server.blackbox_dir,
            retain=cfg.server.blackbox_retain,
            stats_fn=self.supervision_counters)
        # Elastic fleet (README "Elastic fleet"): autoscaler, rolling
        # upgrades, and per-class admission state.
        self.scale_ups = 0
        self.scale_downs = 0
        self.rollouts = 0
        self.class_preemptions: Dict[str, int] = {}
        self.class_shed: Dict[str, int] = {}
        from collections import deque
        # Bounded per-class deferral queues (batch lanes park here at
        # the admission cap instead of shedding; the monitor pump
        # dispatches them as capacity frees up). Guarded by _lock.
        self._deferred: Dict[str, deque] = {"batch": deque(),
                                            "background": deque()}
        self._breach_since = 0.0      # monotonic start of current breach
        self._idle_since = 0.0        # monotonic start of current lull
        self._last_scale_t = 0.0      # monotonic time of last scale act
        self._rollout_lock = threading.Lock()
        # Router-observed TTFT samples (t_observed, ttft_s), pruned to
        # a time horizon at each autoscale tick. This is the scale-up
        # sensor: unlike the workers' engine-side rings it (a) counts
        # time a request spent PARKED in a class lane — the user-
        # perceived latency overload actually inflates — and (b) decays
        # with wall time, so a burst's breached samples cannot latch
        # the fleet at peak size after traffic stops. Guarded by _lock.
        self._ttft_obs: deque = deque(maxlen=2048)
        # Fan-out pool for the concurrent candidate peeks. Created
        # eagerly (threads only spawn on first submit): lazy creation
        # under concurrent HTTP submits would race and leak the losing
        # executor's threads.
        from concurrent.futures import ThreadPoolExecutor
        self._peek_pool = ThreadPoolExecutor(
            max_workers=max(4, self.dp), thread_name_prefix="fleet-peek")
        # Cross-process trace assembly (README "Observability"): the
        # router's own spans (request root, route, handoff, migrate)
        # record here, and worker-exported spans — riding finish/
        # handoff-spans/migrate event frames, already tagged with their
        # source replica and unix-anchored — fold in via ingest(), so
        # one recorder holds each request's full cross-process span
        # set. /debug/trace reads it; the trace RPC verb is the pull
        # fallback for traces this router never saw finish.
        self._recorder = telemetry.SpanRecorder(replica=-1)
        self._rr = 0
        self._route_stats = [{"hits": 0, "cold": 0, "hit_pages": 0,
                              "host_hit_pages": 0, "fabric_hit_pages": 0}
                             for _ in range(self.dp)]
        # Fleet KV fabric (README "KV fabric"): router-resident pool of
        # serialized prefix pages — workers publish via fabric_put event
        # frames; pulls ship to the routed worker's host tier over the
        # import-kv RPC before its submit.
        self.fabric = kv_fabric.FabricPool(cfg.server.fabric_cache_pages)
        # Zero-copy KV data plane (README "KV data plane"): one shared-
        # memory arena for the whole fleet, one region per boot-time
        # replica. Creation failure (or --kv-plane relay, in-process
        # fleet, non-Linux) leaves arena=None and every path below
        # rides the through-router relay exactly as before.
        self.arena: Optional[shm_arena.ArenaSegment] = None
        self._arena_dir: Optional[shm_arena.SlabDirectory] = None
        self.shm_reclaims = 0
        # Router-relayed KV payload bytes per RPC/event verb — the shm
        # arm's ≈0 on handoff/fabric verbs is the lane's headline grade.
        self.rpc_blob_bytes: Dict[str, int] = {
            "submit": 0, "import-kv": 0, "handoff": 0, "migrate": 0,
            "fabric_put": 0}
        if shm_arena.effective_kv_plane(cfg.server) == "shm":
            try:
                self.arena = shm_arena.ArenaSegment(
                    cfg.server.shm_arena_bytes,
                    regions=max(4, self.dp * 2))
                self._arena_dir = shm_arena.SlabDirectory()
                self.fabric.on_release = self._arena_dir.release
            except Exception as e:  # noqa: BLE001 — degrade to relay
                telemetry.log_event(
                    "shm_arena_unavailable", level="warning",
                    error=str(e),
                    note="kv_plane=shm degraded to relay")
                self.arena = None
        self._fleet_registry = telemetry.Registry()
        self._build_registry()

    # ------------------------------------------------------ registries

    def _build_registry(self) -> None:
        r = self._fleet_registry
        telemetry.register_span_ring(r, self._recorder)
        r.gauge("tpu_inf_replicas",
                "Live replicas (autoscaler/rollout move this; retired "
                "and quarantined workers excluded)",
                fn=lambda: float(len(self._live_workers())))
        r.counter("tpu_inf_retries_attempted_total",
                  "Failover resubmissions attempted",
                  fn=lambda: self.retries_attempted)
        r.counter("tpu_inf_retries_succeeded_total",
                  "Failover resubmissions that finished cleanly",
                  fn=lambda: self.retries_succeeded)
        r.counter("tpu_inf_failovers_total",
                  "Requests stranded by a dead/draining worker and "
                  "resubmitted",
                  fn=lambda: self.failovers)
        r.counter("tpu_inf_requests_shed_total",
                  "Requests shed at the admission queue cap (HTTP 429)",
                  fn=lambda: self.requests_shed)
        r.counter("tpu_inf_requests_unavailable_total",
                  "Requests rejected with no routable worker (HTTP 503)",
                  fn=lambda: self.requests_unavailable)
        r.counter("tpu_inf_route_prefix_hits_total",
                  "Dispatches routed with a non-zero prefix-cache peek",
                  fn=lambda: self.route_prefix_hits)
        r.counter("tpu_inf_route_cold_total",
                  "Dispatches routed with no cached prefix on any "
                  "scored worker",
                  fn=lambda: self.route_cold)
        self._route_hit_pages_hist = r.histogram(
            "tpu_inf_route_hit_pages",
            "Peeked prefix-cache hit pages per warm-routed dispatch",
            buckets=telemetry.COUNT_BUCKETS)
        r.counter("tpu_inf_route_fabric_hits_total",
                  "Dispatches that pulled fabric pages into the routed "
                  "replica's host tier (fourth-temperature warmth)",
                  fn=lambda: self.route_fabric_hits)
        self._route_fabric_hit_pages_hist = r.histogram(
            "tpu_inf_route_fabric_hit_pages",
            "Fabric pages pulled per fabric-warm dispatch",
            buckets=telemetry.COUNT_BUCKETS)
        telemetry.register_fabric(r, self.fabric)
        # Zero-copy KV data plane: how many KV payload bytes still
        # traverse the router per verb (the shm plane's reason to
        # exist is driving the handoff/fabric rows of this family to
        # ~0), plus the arena supervisor's slab books.
        for verb in self.rpc_blob_bytes:
            r.counter("tpu_inf_rpc_blob_bytes_total",
                      "KV payload bytes relayed through the router's "
                      "RPC/event frames, by verb (descriptor frames on "
                      "the shm plane count 0 here — the bytes stay in "
                      "the arena)",
                      fn=lambda v=verb: self.rpc_blob_bytes[v],
                      verb=verb)
        r.gauge("tpu_inf_shm_slabs_total",
                "Arena slabs the router still tracks: live plus "
                "released-but-not-yet-freed (frees batch to the owning "
                "worker on its next stats tick). 0 on the relay plane.",
                fn=lambda: float(self._arena_dir.slabs_tracked
                                 if self._arena_dir is not None else 0))
        r.gauge("tpu_inf_shm_slabs_used",
                "Arena slabs still referenced by a live consumer "
                "(fabric pool entry or pending handoff/migrate)",
                fn=lambda: float(self._arena_dir.slabs_live
                                 if self._arena_dir is not None else 0))
        r.counter("tpu_inf_shm_reclaims_total",
                  "Arena slabs reclaimed by the supervisor via the "
                  "region epoch bump after their owning worker "
                  "incarnation died (kill -9 mid-handoff lands here; "
                  "the in-flight request recompute-resumes)",
                  fn=lambda: self.shm_reclaims)
        r.counter("tpu_inf_fleet_migrations_total",
                  "In-flight requests migrated off a draining worker",
                  fn=lambda: self.migrations)
        r.counter("tpu_inf_fleet_migrated_pages_total",
                  "KV pages moved worker-to-worker by drain migration",
                  fn=lambda: self.migrated_pages)
        r.counter("tpu_inf_fleet_migrated_bytes_total",
                  "Bytes moved worker-to-worker by drain migration",
                  fn=lambda: self.migrated_bytes)
        r.counter("tpu_inf_resume_recomputed_tokens_total",
                  "Tokens re-prefilled from scratch by fleet "
                  "resubmission resumes (lower is better — migration "
                  "exists to shrink this)",
                  fn=lambda: self.resume_recomputed_tokens)
        r.counter("tpu_inf_resume_reused_tokens_total",
                  "Tokens served from cache tiers (incl. migrated "
                  "pages) during fleet resubmission resumes",
                  fn=lambda: self.resume_reused_tokens)
        r.counter("tpu_inf_pd_handoffs_total",
                  "Prefill->decode live KV handoffs routed (README "
                  "'P/D disaggregation')",
                  fn=lambda: self.pd_handoffs)
        r.counter("tpu_inf_pd_handoff_recomputes_total",
                  "Handoffs that fell back to recompute-resume (stale "
                  "export, no adopter, or a worker-side adoption "
                  "failure) instead of a clean adoption",
                  fn=self._pd_recomputes_total)
        r.counter("tpu_inf_worker_reconnects_total",
                  "Connection-level failovers: the socket died or a "
                  "frame was invalid while the worker process stayed "
                  "up, so the router reconnected and resynced instead "
                  "of paying a restart",
                  fn=lambda: self.reconnects)
        r.counter("tpu_inf_rpc_timeouts_total",
                  "Worker RPCs that exceeded their per-verb deadline "
                  "class (each also emits a structured rpc_timeout "
                  "event with verb + replica)",
                  fn=lambda: self.rpc_timeouts)
        r.counter("tpu_inf_frame_errors_total",
                  "Malformed RPC frames the router rejected (bad "
                  "magic/CRC/length) — each one recycles its "
                  "connection",
                  fn=lambda: self.frame_errors)
        r.counter("tpu_inf_kv_integrity_rejections_total",
                  "Corrupt KV blobs rejected by digest verification "
                  "(router gate + worker adopt/import paths); every "
                  "rejection fell back to recompute-resume, never a "
                  "silent adoption",
                  fn=self._kv_rejections_total)
        r.counter("tpu_inf_poison_requests_total",
                  "Requests quarantined after crashing or wedging "
                  "poison_max_workers distinct workers (terminal "
                  "structured 500 + router blackbox capture)",
                  fn=lambda: self.poison_requests)
        self._pd_handoff_s_hist = r.histogram(
            "tpu_inf_pd_handoff_seconds",
            "Prefill->decode handoff wall: worker-side KV export + "
            "router-side routing/dispatch until the decode worker "
            "accepted the resume")
        # Fleet-level rolling SLO gauges: EXACT quantiles pooled across
        # every worker's ring (per-replica p95s do not compose by
        # max/mean), from the cached worker stats the monitor refreshes
        # ~1/s; breach totals add the dead-incarnation carry so a
        # worker restart never makes the fleet counter decrease.
        # Per-replica series render from the workers' own registries
        # under replica="i" labels.
        telemetry.register_fleet_slo(
            r, self._pooled_slo_quantile,
            lambda k: sum(h.slo_breach_carry[k]
                          + (((h.last_stats or {}).get("slo") or {})
                             .get(f"{k}_breaches", 0))
                          for h in self.workers))
        # Elastic-fleet series (README "Elastic fleet"): scale events,
        # rolling upgrades, and the per-class admission lanes.
        telemetry.register_fleet_elastic(
            r,
            scale_ups=lambda: self.scale_ups,
            scale_downs=lambda: self.scale_downs,
            rollouts=lambda: self.rollouts,
            class_preempted=lambda c: self.class_preemptions.get(c, 0),
            class_deferred=lambda c: len(self._deferred.get(c) or ()),
            class_shed=lambda c: self.class_shed.get(c, 0))
        import jax
        telemetry.emit_build_info(
            r, backend=jax.default_backend(), fleet="subprocess",
            kv_quant=self.engine_cfg.kv_quant,
            spec_mode=(self.engine_cfg.spec_mode
                       if self.engine_cfg.num_speculative_tokens > 0
                       else "off"),
            routing=self.server_cfg.routing)
        for h in self.workers:
            self._register_worker_gauges(h)

    def _register_worker_gauges(self, h: WorkerHandle) -> None:
        """Per-worker series under the stable replica label. Called for
        every boot-time handle and again for each worker the autoscaler
        or a rollout adds at a fresh replica index."""
        r = self._fleet_registry
        r.gauge("tpu_inf_worker_role_info",
                "Worker phase role (constant 1; the role is the "
                "label)",
                fn=lambda: 1.0, replica=str(h.replica),
                role=self.roles[h.replica])
        r.gauge("tpu_inf_replica_routable",
                "1 when the worker accepts traffic",
                fn=lambda hh=h: float(hh.routable),
                replica=str(h.replica))
        r.gauge("tpu_inf_worker_up",
                "1 while the worker process is serving",
                fn=lambda hh=h: float(hh.state == UP),
                replica=str(h.replica))
        r.counter("tpu_inf_worker_restarts_total",
                  "Worker process respawns (stable replica label "
                  "across incarnations)",
                  fn=lambda hh=h: hh.restarts,
                  replica=str(h.replica))
        r.gauge("tpu_inf_worker_quarantined",
                "1 while the crash-loop breaker holds this replica "
                "quarantined (restart budget exhausted; routed around)",
                fn=lambda hh=h: float(hh.state == QUARANTINED),
                replica=str(h.replica))

    def _kv_rejections_total(self) -> int:
        """Router-side rejections plus every worker's adopt/import
        rejections (healthz-cached; live counts, no carry needed —
        a corrupt blob implies a live incarnation that rejected it)."""
        return self.kv_rejections + self.fabric.kv_rejections + sum(
            (h.last_health or {}).get("kv_integrity_rejections", 0)
            for h in self.workers)

    @staticmethod
    def _chaos_kw_from_cfg(s) -> dict:
        return {"seed": s.chaos_rpc_seed,
                "corrupt_rate": s.chaos_rpc_corrupt_rate,
                "drop_rate": s.chaos_rpc_drop_rate,
                "delay_rate": s.chaos_rpc_delay_rate,
                "delay_s": s.chaos_rpc_delay_s,
                "truncate_rate": s.chaos_rpc_truncate_rate,
                "wedge_after": s.chaos_rpc_wedge_after,
                "wedge_replica": s.chaos_rpc_wedge_replica,
                "verbs": tuple(s.chaos_rpc_verbs),
                "direction": s.chaos_rpc_direction}

    def _make_chaos(self, replica: int) -> Optional[ChaosTransport]:
        """Router-side chaos shim for one worker connection. The policy
        persists per replica (wedge_spent survives reconnects — the
        wedge is one-shot by design); each connection gets a fresh
        transport over it. None when chaos is off or aimed only at the
        worker->router direction."""
        kw = dict(self._chaos_rpc_kw)
        if kw["direction"] not in ("send", "both"):
            return None
        wedge_after = kw.pop("wedge_after")
        wedge = wedge_after if kw.pop("wedge_replica") == replica else 0
        pol = self._chaos_policies.get(replica)
        if pol is None:
            pol = ChaosPolicy(wedge_after=wedge, **kw)
            pol.seed += replica  # decorrelate per-worker schedules
            if pol.active:
                self._chaos_policies[replica] = pol
        if not pol.active:
            return None
        return ChaosTransport(pol)

    def _live_workers(self) -> List[WorkerHandle]:
        """Workers that count toward fleet size: everything except the
        intentionally-retired (scale-down/rollout) and the crash-loop
        quarantined/dead."""
        return [h for h in self.workers
                if h.state not in (RETIRED, DEAD, QUARANTINED)]

    def _pooled_slo_quantile(self, which: str, q: float) -> float:
        windows = [(((h.last_stats or {}).get("slo") or {})
                    .get(f"{which}_window")) or []
                   for h in self.workers]
        v = telemetry.pooled_quantile(windows, q)
        return float("nan") if v is None else v

    def _fleet_slo(self) -> dict:
        out = telemetry.pooled_slo(
            [(h.last_stats or {}).get("slo") for h in self.workers])
        # Dead-incarnation carry keeps the fleet totals monotone
        # across worker restarts (same stance as the metrics carry).
        out["ttft_breaches"] += sum(h.slo_breach_carry["ttft"]
                                    for h in self.workers)
        out["tpot_breaches"] += sum(h.slo_breach_carry["tpot"]
                                    for h in self.workers)
        return out

    # ----------------------------------------------------------- spawn

    def _envelope(self, replica: int) -> dict:
        import jax

        pcfg = self.cfg.parallel
        env = {
            "config": framework_config_to_dict(self.cfg),
            "platform": jax.default_backend(),
            "cpu_devices": max(1, pcfg.tp * pcfg.sp),
            "warmup": self.cfg.server.warmup,
            # Per-worker phase role: the one envelope field that differs
            # between replicas (README "P/D disaggregation").
            "role": self.roles[replica],
            # Shared-CPU hosts: deprioritize the prefill tier so decode
            # cadence stays flat under prefill bursts (ServerConfig.
            # pd_prefill_nice; no-op at 0 or on per-chip deployments).
            "nice": (self.cfg.server.pd_prefill_nice
                     if self.roles[replica] == "prefill" else 0),
            # Pool watermark at boot (satellite: publish back-pressure);
            # the periodic stats RPC keeps it fresh afterwards.
            "fabric_free": self.fabric.free_pages,
        }
        if self.arena is not None:
            # Zero-copy plane: this worker's region assignment (segment
            # name + geometry + current epoch). None past the region
            # count — a late autoscaled worker rides the relay plane.
            shm = self.arena.region_spec(replica)
            if shm is not None:
                env["shm"] = shm
        return env

    def _spawn(self, h: WorkerHandle) -> None:
        """Launch one worker incarnation and wait for its hello (which
        blocks until the worker's engine is built and warmed)."""
        h.incarnation += 1
        if self.arena is not None and h.incarnation > 1:
            # Supervisor reclaim (README "KV data plane"): the dead
            # incarnation's in-flight slabs — published fabric pages, a
            # handoff export that never got adopted — are taken back by
            # bumping the region epoch: every outstanding descriptor
            # fails closed (ArenaStale) and its consumer falls back to
            # recompute/miss, never a stale adoption. The fresh
            # incarnation mints under the new epoch from a blank region.
            self._reclaim_region(h.replica)
        h.socket_path = os.path.join(
            self._sock_dir, f"w{h.replica}.{h.incarnation}.sock")
        env = dict(os.environ)
        # The repo may be run uninstalled (benchmarks insert sys.path
        # manually); the worker interpreter needs the same root.
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpu_inference.server.worker",
             "--socket", h.socket_path, "--replica", str(h.replica)],
            stdin=subprocess.PIPE, env=env)
        try:
            assert proc.stdin is not None
            proc.stdin.write(json.dumps(
                self._envelope(h.replica)).encode())
            proc.stdin.close()
            client = WorkerClient(h.socket_path, proc,
                                  replica=h.replica,
                                  deadlines=self._deadlines,
                                  chaos=self._make_chaos(h.replica))
            client.on_event = lambda c, obj, blob, hh=h: self._on_event(
                hh, c, obj, blob)
            client.on_lost = lambda c, hh=h: self._on_conn_lost(hh, c)
            client.on_timeout = \
                lambda verb, t, hh=h: self._note_rpc_timeout(hh, verb, t)
            client.start_reader()
            hello = client.rpc("hello", timeout=1800.0)
        except BaseException:
            try:
                proc.kill()
            except OSError:
                pass
            raise
        h.proc, h.client = proc, client
        h.pid = hello.get("pid")
        h.info = hello
        h.started_unix = time.time()
        # Warm worker boot (README "KV fabric"): the fabric's hot set
        # lands in the fresh worker's host tier BEFORE the UP flip
        # makes it routable, so an autoscaled/restarted/upgraded worker
        # serves its first request with fabric hits instead of booting
        # stone-cold. No-op while the pool is empty (initial boot).
        self._fabric_warmboot(h, client)
        h.state = UP
        h.consecutive_failures = 0
        self.warmup_total_s += hello.get("warmup_s", 0.0)
        if self.engine is None:
            self.engine = _EngineInfo(hello)
        telemetry.log_event(
            "worker_up", level="info", replica=h.replica,
            pid=h.pid, incarnation=h.incarnation)

    def _fabric_warmboot(self, h: WorkerHandle,
                         client: WorkerClient) -> int:
        """Push the fabric pool's MRU hot set (capped by
        --fabric-warmboot-pages) into a just-booted worker's host tier
        over import-kv. Each pooled blob re-verifies before shipping —
        a corrupt entry is dropped and counted, never shipped. Best
        effort: any failure leaves the worker cold but serviceable."""
        budget = self.server_cfg.fabric_warmboot_pages
        adopted = 0
        offered_d = 0
        if self.arena is not None:
            # Zero-copy push first: descriptors only — the fresh worker
            # reads each slab straight from the arena and verifies it
            # there; rejected digests come back so the pool drops them.
            hot_d = self.fabric.hot_set_descs(budget)
            if hot_d:
                offered_d = len(hot_d)
                try:
                    r = client.rpc(
                        "import-kv",
                        digests=[d.hex() for d, _ in hot_d],
                        descs=[desc for _, desc in hot_d],
                        idem=f"wbd{h.replica}.{h.incarnation}")
                    adopted += int(r.get("adopted", 0))
                    for hexd in r.get("rejected_digests") or ():
                        self.fabric.reject(bytes.fromhex(hexd))
                except (WorkerGone, TimeoutError, RuntimeError) as e:
                    telemetry.log_event("fabric_warmboot_failed",
                                        level="warning",
                                        replica=h.replica, error=str(e))
                budget = max(0, budget - len(hot_d))
        hot = self.fabric.hot_set(budget)
        pairs = []
        for d, b in hot:
            try:
                pairs.append((d, kvc.deserialize_host_pages(b)[0]))
            except kvc.integrity.KVIntegrityError:
                self.fabric.reject(d)
        if not pairs:
            if adopted:
                telemetry.log_event(
                    "fabric_warmboot", level="info", replica=h.replica,
                    offered=offered_d, adopted=adopted)
            return adopted
        try:
            blob = kvc.serialize_host_pages([p for _, p in pairs])
            with self._lock:
                self.rpc_blob_bytes["import-kv"] += len(blob)
            r = client.rpc(
                "import-kv", blob=blob,
                digests=[d.hex() for d, _ in pairs],
                idem=f"wb{h.replica}.{h.incarnation}")
        except (WorkerGone, TimeoutError, RuntimeError) as e:
            telemetry.log_event("fabric_warmboot_failed",
                                level="warning", replica=h.replica,
                                error=str(e))
            return adopted
        adopted += int(r.get("adopted", 0))
        telemetry.log_event(
            "fabric_warmboot", level="info", replica=h.replica,
            offered=offered_d + len(pairs), adopted=adopted)
        return adopted

    def _reclaim_region(self, rg: int) -> int:
        """Dead-incarnation slab reclaim: drop the region's fabric
        entries, settle the directory books, bump the epoch word so
        every outstanding descriptor fails closed."""
        if self.arena is None or self._arena_dir is None \
                or not (0 <= rg < self.arena.regions):
            return 0
        dropped = self.fabric.drop_region(rg)
        n = self._arena_dir.reclaim(rg)
        self.arena.bump_epoch(rg)
        with self._lock:
            self.shm_reclaims += n
        if n or dropped:
            telemetry.log_event(
                "shm_region_reclaimed", level="info", region=rg,
                slabs=n, fabric_entries=dropped)
        return n

    def _release_handoff_desc(self, entry: "_Tracked") -> None:
        """Drop a tracked handoff's arena slab reference (idempotent).
        Called wherever the blob variant would be dropped — the slab
        frees back to its owner on the next stats tick."""
        desc = entry.handoff_desc
        entry.handoff_desc = None
        if desc is not None and self._arena_dir is not None:
            self._arena_dir.release(desc)

    def _ensure_started(self) -> None:
        with self._start_lock:
            if self._started:
                return
            for h in self.workers:
                self._spawn(h)
            self._started = True

    # ---------------------------------------------------------- facade

    @property
    def engines(self) -> List[_EngineInfo]:
        """Len/iteration parity with EngineGroup.engines (the HTTP layer
        reads ``len(group.engines)`` for the replica count — including
        workers the autoscaler or a rollout added past the configured
        dp, so e.g. /debug/profile can target them)."""
        info = self.engine or _EngineInfo({})
        return [info] * max(self.dp, len(self.workers))

    def warmup(self) -> float:
        self._ensure_started()
        return self.warmup_total_s

    def start(self) -> "ProcessEngineGroup":
        self._ensure_started()
        self._monitor_stop.clear()
        self._monitor = threading.Thread(target=self._watch,
                                         name="fleet-monitor",
                                         daemon=True)
        self._monitor.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        self._stopping = True
        if self._peek_pool is not None:
            self._peek_pool.shutdown(wait=False)
            self._peek_pool = None
        self._monitor_stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        for h in self.workers:
            if h.client is not None and h.client.alive:
                try:
                    h.client.rpc("shutdown", timeout=timeout + 30.0,
                                 drain=drain, timeout_s=timeout)
                except (WorkerGone, TimeoutError, RuntimeError):
                    pass
            if h.proc is not None and h.proc.poll() is None:
                try:
                    h.proc.terminate()
                    h.proc.wait(timeout=10.0)
                except (subprocess.TimeoutExpired, OSError):
                    try:
                        h.proc.kill()
                        h.proc.wait(timeout=5.0)
                    except (subprocess.TimeoutExpired, OSError):
                        pass
            if h.client is not None:
                h.client.close()
            h.state = DEAD
        # Anything still tracked gets its terminal callback (shutdown),
        # so no client stream hangs on a router teardown.
        with self._lock:
            leftovers = list(self._tracked.values())
            self._tracked.clear()
            # Parked batch/background entries are in _tracked too (the
            # ghost-finish below covers them); drop the lane handles.
            for q in self._deferred.values():
                q.clear()
        for entry in leftovers:
            self._release_handoff_desc(entry)
            self._finish_trace(entry, "shutdown")
            ghost = entry.seq_local
            ghost.done, ghost.finish_reason = True, "shutdown"
            ghost.finish_time = time.perf_counter()
            entry.on_finish(ghost)
        if self.arena is not None:
            # Every worker is dead: unlink the segment (the kernel
            # reclaims the pages; attached mappings, if any, die with
            # their processes).
            self.arena.close(unlink=True)
            self.arena = None

    # ------------------------------------------------------ supervision

    def _watch(self) -> None:
        """Monitor thread: process liveness, restart backoff, and the
        periodic metrics/stats cache that bounds kill -9 carry loss."""
        last_scrape = 0.0
        while not self._monitor_stop.wait(0.2):
            now = time.monotonic()
            for h in self.workers:
                if h.state in (UP, DRAINING) and h.proc is not None \
                        and h.proc.poll() is not None:
                    self._on_worker_down(
                        h, f"exit rc={h.proc.returncode}")
                elif h.state == RESTARTING and now >= h.restart_at \
                        and not self._stopping:
                    try:
                        self._spawn(h)
                        h.restarts += 1
                    except (WorkerGone, TimeoutError, RuntimeError,
                            OSError) as e:
                        h.consecutive_failures += 1
                        telemetry.log_event(
                            "worker_respawn_failed", level="error",
                            replica=h.replica, error=str(e))
                        self._schedule_restart(h)
            if now - last_scrape >= 1.0:
                last_scrape = now
                self._refresh_caches()
                if self.server_cfg.autoscale:
                    self._autoscale_tick(now)
            self._pump_deferred()

    def _refresh_caches(self) -> None:
        for h in self.workers:
            if h.state != UP or h.client is None:
                continue
            # The stats tick doubles as the data-plane's control
            # channel: the pool watermark rides out (publish
            # back-pressure) and the batched slab frees ride out
            # (arena lifecycle) — no extra RPCs on the hot path.
            frees = (self._arena_dir.drain_free(h.replica)
                     if self._arena_dir is not None else [])
            try:
                h.last_metrics = h.client.rpc("metrics")["samples"]
                h.last_stats = h.client.rpc(
                    "stats", fabric_free=self.fabric.free_pages,
                    arena_free=frees)["stats"]
                frees = []
                h.last_health = h.client.rpc("healthz")
                h.last_steps = h.client.rpc("steps")["steps"]
            except (WorkerGone, TimeoutError, RuntimeError):
                pass
            finally:
                if frees and self._arena_dir is not None:
                    # The tick that would have carried them failed —
                    # retry next second (a free lost forever is a leak).
                    self._arena_dir.requeue_free(h.replica, frees)

    def _schedule_restart(self, h: WorkerHandle) -> None:
        scfg = self.server_cfg
        # Budget covers BOTH successful respawns and consecutive boot
        # failures — a worker whose boot crashes deterministically
        # (deleted checkpoint, bad device) must go DEAD, not respawn a
        # jax-importing process forever.
        if self._stopping:
            h.state = DEAD
            return
        if (h.restarts >= scfg.worker_restart_max
                or h.consecutive_failures > scfg.worker_restart_max):
            # Crash-loop breaker: the budget is spent, so stop burning
            # boot cycles — but keep the replica VISIBLE. QUARANTINED
            # stays in /healthz (degraded, not absent) and pins the
            # tpu_inf_worker_quarantined gauge to 1 so an operator sees
            # a routed-around replica instead of a silently shrunk dp.
            h.state = QUARANTINED
            telemetry.log_event("worker_quarantined", level="error",
                                replica=h.replica, restarts=h.restarts,
                                consecutive_failures=h.consecutive_failures)
            # No respawn will ever bump this region's epoch — reclaim
            # its slabs now or they pin arena memory forever.
            self._reclaim_region(h.replica)
            return
        backoff = min(30.0, scfg.worker_restart_backoff_s
                      * (2 ** max(0, h.consecutive_failures)))
        h.restart_at = time.monotonic() + backoff
        h.state = RESTARTING

    def _note_rpc_timeout(self, h: WorkerHandle, verb: str,
                          timeout_s: float) -> None:
        with self._lock:
            self.rpc_timeouts += 1

    def _on_conn_lost(self, h: WorkerHandle, client: WorkerClient) -> None:
        if self._stopping or h.client is not client:
            return
        if getattr(client, "lost_reason", "") == "frame_error":
            with self._lock:
                self.frame_errors += 1
        # Distinguish "socket died / frame invalid" from "process
        # died": while the worker process is alive and serving, a
        # broken connection is a transport fault — pay a reconnect
        # (worker.serve accepts again on the same socket path), not a
        # full restart with its boot + warmup bill.
        if (h.state == UP and h.proc is not None
                and h.proc.poll() is None):
            threading.Thread(target=self._reconnect_worker,
                             args=(h, client),
                             name="fleet-reconnect",
                             daemon=True).start()
            return
        # Reader died first (socket reset); the monitor would catch the
        # process exit too — whoever flips the state first acts.
        if h.state in (UP, DRAINING):
            self._on_worker_down(h, "connection lost")

    def _reconnect_worker(self, h: WorkerHandle,
                          old_client: WorkerClient) -> None:
        """Connection-level failover: dial the live worker again, swap
        the client under the lock, then resync every request that was
        riding the dead connection. Falls back to the full worker-down
        path if the redial fails (the process may have died between
        poll() and connect)."""
        with self._lock:
            if h.client is not old_client or h.state != UP:
                return  # another actor (restart/rollout) already won
        old_client.close()
        try:
            client = WorkerClient(h.socket_path, h.proc,
                                  connect_timeout=5.0,
                                  replica=h.replica,
                                  deadlines=self._deadlines,
                                  chaos=self._make_chaos(h.replica))
            client.on_event = lambda c, obj, blob, hh=h: self._on_event(
                hh, c, obj, blob)
            client.on_lost = lambda c, hh=h: self._on_conn_lost(hh, c)
            client.on_timeout = \
                lambda verb, t, hh=h: self._note_rpc_timeout(hh, verb, t)
            client.start_reader()
            client.rpc("hello")
        except (WorkerGone, TimeoutError, RuntimeError, OSError) as e:
            telemetry.log_event("worker_reconnect_failed",
                                level="warning", replica=h.replica,
                                reason=getattr(old_client,
                                               "lost_reason", ""),
                                error=str(e))
            if h.state in (UP, DRAINING):
                self._on_worker_down(h, f"reconnect failed: {e}")
            return
        with self._lock:
            if h.client is not old_client or h.state != UP:
                client.close()
                return
            # Re-resolve chaos at swap time: /debug/chaos may have
            # retuned (e.g. disarmed) the injection while this redial
            # was in flight — installing the policy read at dial time
            # would resurrect a stale fault schedule on the fresh
            # connection.
            client.chaos = self._make_chaos(h.replica)
            h.client = client
            self.reconnects += 1
        telemetry.log_event("worker_reconnect", level="warning",
                            replica=h.replica,
                            reason=getattr(old_client, "lost_reason",
                                           "") or "connection lost")
        self._resync_worker(h, old_client)

    def _resync_worker(self, h: WorkerHandle,
                       old_client: WorkerClient) -> None:
        """Requests that were streaming over the dead connection:
        cancel the worker-side ghost (idempotent — the attempt may
        still be decoding into the void) and re-dispatch from the
        router's token record, preferring the SAME worker (its KV
        pages are warm); recompute-resume keeps the stream
        byte-identical under greedy."""
        with self._lock:
            victims = [e for e in self._tracked.values()
                       if e.worker is h and e.client is old_client]
            for entry in victims:
                entry.generation += 1
                entry.worker = entry.client = None
                entry.attempts += 1
                self.retries_attempted += 1
        for entry in victims:
            rid = entry.template.request_id
            if h.client is not None and h.client.alive:
                try:
                    h.client.rpc("cancel", rid=rid,
                                 idem=f"c{rid}.{entry.generation}")
                except (WorkerGone, TimeoutError, RuntimeError):
                    pass
            if self._quarantine_if_poison(entry):
                continue
            if h.routable and self._dispatch(entry, h, (0, 0, 0)):
                continue
            self._retry_or_fail(entry, exclude=h)

    def _quarantine_if_poison(self, entry: _Tracked) -> bool:
        """Poison-request gate (README "Failure model"): once this
        request's attempts have crashed or wedged poison_max_workers
        DISTINCT workers, fail it terminally — a structured 500 with a
        blackbox capture — instead of feeding it the rest of the
        fleet. Returns True when the request was quarantined."""
        limit = self.server_cfg.poison_max_workers
        if limit <= 0 or len(entry.failed_workers) < limit:
            return False
        rid = entry.template.request_id
        with self._lock:
            if self._tracked.pop(rid, None) is None:
                return True  # already finished/quarantined elsewhere
            self.poison_requests += 1
        telemetry.log_event(
            "poison_quarantined", level="error",
            request_id=entry.template.trace_id or str(rid),
            workers=sorted(entry.failed_workers),
            attempts=entry.attempts, streamed=len(entry.tokens))
        if self._flight is not None:
            self._flight.capture("poison_request", min_interval_s=0.0)
        self._finish_trace(entry, "poison")
        ghost = entry.seq_local
        ghost.generated = list(entry.tokens)
        ghost.done, ghost.finish_reason = True, "poison"
        ghost.finish_time = time.perf_counter()
        entry.on_finish(ghost)
        return True

    def _on_worker_down(self, h: WorkerHandle, reason: str) -> None:
        """A worker incarnation died (kill -9, crash, or post-drain
        exit): fold its last-seen monotonic series into the carry, fail
        over its in-flight requests from the router's token record, and
        schedule a respawn under the same replica label."""
        with self._lock:
            # Monitor (proc poll) and reader (conn lost) can both see
            # the death; the state flip under the lock picks one actor.
            if h.state not in (UP, DRAINING):
                return
            h.state = RETIRED if h.retiring else RESTARTING
        if h.state != RETIRED:
            h.consecutive_failures += 1
        if h.proc is not None and h.proc.poll() is None:
            try:
                h.proc.kill()
            except OSError:
                pass
        if h.client is not None:
            h.client.close()
        if h.folded_incarnation != h.incarnation:
            # Once per incarnation: the drained-event path and a second
            # death report must not double-fold the same totals. The
            # folded dump is then CLEARED — rendering it alongside the
            # carry (e.g. a scrape hitting the fresh incarnation before
            # its first metrics RPC succeeds) would double-count.
            h.folded_incarnation = h.incarnation
            telemetry.fold_dump_into_carry(h.carry, h.last_metrics)
            h.last_metrics = []
            # Fold the dead incarnation's SLO breach totals, then zero
            # the cached copy — keeping both would double-count until
            # the fresh incarnation's first stats refresh.
            slo = (h.last_stats or {}).get("slo") or {}
            h.slo_breach_carry["ttft"] += slo.get("ttft_breaches", 0)
            h.slo_breach_carry["tpot"] += slo.get("tpot_breaches", 0)
            if slo:
                h.last_stats = {**h.last_stats,
                                "slo": {**slo, "ttft_breaches": 0,
                                        "tpot_breaches": 0}}
        if h.state == RETIRED:
            # Intentional exit (scale-down or rollout retirement): the
            # drain already migrated its sequences out, so the failover
            # sweep below is a no-op safety net, and there is nothing
            # to respawn.
            h.retiring = False
            telemetry.log_event("worker_retired", replica=h.replica,
                                reason=reason)
        else:
            telemetry.log_event("worker_down", level="warning",
                                replica=h.replica, reason=reason)
            self._harvest_blackbox(h, reason)
            self._schedule_restart(h)
        self._failover_worker(h)

    def _harvest_blackbox(self, h: WorkerHandle, reason: str) -> None:
        """Post-mortem evidence sweep: the dead worker's flight-recorder
        dir is on the router's local FS (same --blackbox-dir), so a
        kill -9's last periodic heartbeat and any trigger captures are
        sitting there — surface them in the log and the /debug/blackbox
        index so the death is triaged with evidence, not guesses."""
        root = self.server_cfg.blackbox_dir
        if not root:
            return
        rdir = os.path.join(root, f"replica-{h.replica}")
        try:
            captures = sorted(f for f in os.listdir(rdir)
                              if f.endswith(".json"))
        except OSError:
            captures = []
        if captures:
            telemetry.log_event(
                "worker_blackbox_harvested", replica=h.replica,
                reason=reason, captures=len(captures),
                newest=captures[-1], dir=rdir)

    # --------------------------------------------------------- routing

    def _routable(self) -> List[WorkerHandle]:
        return [h for h in self.workers if h.routable]

    def _fleet_load(self, h: WorkerHandle) -> int:
        with self._lock:
            return sum(1 for e in self._tracked.values()
                       if e.worker is h)

    def _digests_for(self, seq: Sequence) -> Tuple[List[bytes], int]:
        """Routing-time prefix digests — same truncation/trim rule as
        EngineGroup._digests_for (replicas.py), over the router's own
        copy of the engine config."""
        ecfg = self.engine_cfg
        prompt_len = min(len(seq.prompt_tokens), ecfg.max_context - 1)
        prompt_pages = kvc.pages_needed(prompt_len, ecfg.page_size)
        cap = (prompt_len - 1) // ecfg.page_size
        if cap <= 0:
            return [], prompt_pages
        if seq.prefix_digests is None:
            tokens = seq.prompt_tokens
            prompt = (tokens[-prompt_len:] if len(tokens) > prompt_len
                      else tokens)
            seq.prefix_digests = _chain_hashes(prompt, ecfg.page_size)
        return seq.prefix_digests[:cap], prompt_pages

    def _pd_recomputes_total(self) -> int:
        """Every non-clean handoff, both ends: router-side fallbacks
        (stale export, no adopter) plus worker-side adoption failures
        (malformed blob, pool shortfall) from the workers' cached
        stats — the ONE number tpu_inf_pd_handoff_recomputes_total and
        the supervision view report."""
        return self.pd_handoff_recomputes + sum(
            (h.last_stats or {}).get("pd_adopt_fallbacks", 0)
            for h in self.workers)

    def _cold_peek(self, h: WorkerHandle) -> dict:
        """Scoring fallback for a worker that can't answer a peek in
        time: no warmth, router-side load estimate, no pressure."""
        return {"hbm": 0, "host": 0, "load": self._fleet_load(h),
                "pressure": False, "occupancy": 0.0, "backlog": 0,
                "role": self.roles[h.replica]}

    def _peek(self, h: WorkerHandle, digests: List[bytes],
              timeout: float = 10.0) -> dict:
        client = h.client
        if client is None:
            return self._cold_peek(h)
        try:
            return client.rpc("peek", timeout=timeout,
                              digests=[d.hex() for d in digests])
        except (WorkerGone, TimeoutError, RuntimeError):
            return self._cold_peek(h)

    def _peek_many(self, cands: List[WorkerHandle],
                   digests: List[bytes]) -> List[dict]:
        """Concurrent candidate peeks with a short fan-out deadline
        (ServerConfig.route_peek_timeout_s): the serial loop used to add
        one slow worker's full round-trip to EVERY admission; now the
        peeks fly together and any straggler scores with the cold
        fallback while its late reply is discarded (the RPC layer's own
        timeout reaps it)."""
        pool = self._peek_pool
        if len(cands) == 1 or self._stopping or pool is None:
            return [self._peek(h, digests) for h in cands]
        from concurrent.futures import wait as _futures_wait
        deadline = self.server_cfg.route_peek_timeout_s
        # The RPC itself is clamped near the fan-out deadline: a wedged
        # worker's straggler threads otherwise block 10s each and can
        # saturate the small pool, cold-scoring HEALTHY candidates too.
        try:
            futs = [pool.submit(self._peek, h, digests, deadline + 0.5)
                    for h in cands]
        except RuntimeError:        # pool shut down by a racing stop()
            return [self._peek(h, digests) for h in cands]
        _futures_wait(futs, timeout=deadline)
        return [f.result() if f.done() else self._cold_peek(h)
                for h, f in zip(cands, futs)]

    def _phase_pool(self, phase: Optional[str]) -> List[WorkerHandle]:
        """Routable workers eligible for one phase (README "P/D
        disaggregation"): new prompts ("prefill") avoid decode-role
        workers, resumes/handoffs ("decode") avoid prefill-role workers.
        An empty phase pool falls back to every routable worker so a
        degraded fleet still serves (the off-role worker lazy-compiles
        the other phase's graphs)."""
        routable = self._routable()
        if not self.pd_enabled or phase is None:
            return routable
        exclude = "decode" if phase == "prefill" else "prefill"
        return ([h for h in routable
                 if self.roles[h.replica] != exclude] or routable)

    @staticmethod
    def _entry_phase(entry: "_Tracked") -> str:
        """Routing phase for a resubmission: a stream with tokens is
        decode work; a zero-delivery retry re-enters as a prompt."""
        return "decode" if entry.tokens else "prefill"

    def _rotate(self, ties: list):
        if len(ties) == 1:
            return ties[0]
        idx = self._rr % len(ties)
        self._rr += 1
        return ties[idx]

    def _pick(self, cands: List[WorkerHandle],
              seq: Optional[Sequence] = None,
              phase: Optional[str] = None
              ) -> Tuple[WorkerHandle, Tuple[int, int, int], int]:
        """Choose a worker; returns (handle, (hbm, host, fabric_extra)
        peeked pages, load at decision time). Candidate peeks fan out
        concurrently (_peek_many); the fabric depth comes from the
        router's OWN pool index — no extra RPC. The scores are
        kv_fabric.prefill_route_score / decode_route_score — THE
        four-temperature formulas shared with EngineGroup._pick
        (replicas.py — the in-process fleet is the documented
        contract), so the two backends cannot drift. For
        ``phase="decode"`` under a P/D split the score flips to the
        decode side's costs — ladder occupancy + load, minus the
        warmth discounts (a handoff lands on the least-loaded decode
        worker, warmth breaking ties)."""
        cfg = self.server_cfg
        digests: List[bytes] = []
        prompt_pages = 0
        if seq is not None and cfg.routing == "prefix_affinity":
            digests, prompt_pages = self._digests_for(seq)
        fdepth = self.fabric.match_depth(digests)
        peeks = self._peek_many(cands, digests)
        if phase == "decode" and self.pd_enabled:
            scored = []
            for h, p in zip(cands, peeks):
                occ = float(p.get("occupancy") or 0.0)
                fx = kv_fabric.fabric_extra_pages(
                    fdepth, p["hbm"] + p["host"], prompt_pages)
                score = kv_fabric.decode_route_score(
                    cfg, hbm=p["hbm"], host=p["host"], fabric=fx,
                    load=p["load"], occupancy=occ,
                    pressured=p["pressure"])
                scored.append(((score, p["pressure"], p["load"]),
                               h, (p["hbm"], p["host"], fx), p["load"]))
            best = min(key for key, _, _, _ in scored)
            return self._rotate([(h, hit, load)
                                 for key, h, hit, load in scored
                                 if key == best])
        if digests and (fdepth > 0
                        or any(p["hbm"] + p["host"] for p in peeks)):
            scored = []
            for h, p in zip(cands, peeks):
                fx = kv_fabric.fabric_extra_pages(
                    fdepth, p["hbm"] + p["host"], prompt_pages)
                score = kv_fabric.prefill_route_score(
                    cfg, prompt_pages=prompt_pages, hbm=p["hbm"],
                    host=p["host"], fabric=fx, load=p["load"],
                    pressured=p["pressure"])
                scored.append(((score, p["pressure"], p["load"]),
                               h, (p["hbm"], p["host"], fx), p["load"]))
            best = min(key for key, _, _, _ in scored)
            return self._rotate([(h, hit, load)
                                 for key, h, hit, load in scored
                                 if key == best])
        keyed = [(kv_fabric.cold_route_key(p["pressure"], p["load"]),
                  h, p["load"])
                 for h, p in zip(cands, peeks)]
        best = min(key for key, _, _ in keyed)
        return self._rotate([(h, (0, 0, 0), load)
                             for key, h, load in keyed if key == best])

    # ------------------------------------------------------- submission

    def submit(self, seq: Sequence, on_token: Callable,
               on_finish: Callable) -> None:
        # Trace-id propagation (README "Observability"): HTTP ingress
        # mints or propagates X-Request-Id; every OTHER ingress (bench
        # harnesses, tests driving the group directly) used to submit
        # with trace_id="" and worker-side logs/spans fell back to the
        # engine-internal str(request_id) — un-joinable across the
        # processes a handoff spans. Mint here so the id exists BEFORE
        # the clone/dispatch below ships it to the first worker.
        if not seq.trace_id:
            import uuid
            seq.trace_id = uuid.uuid4().hex[:16]
        # New prompts are prefill work: under a P/D split they go to the
        # prefill tier only (README "P/D disaggregation"). ONE snapshot
        # of the routable set — a worker dying between an emptiness
        # check and a second _routable() read must not hand _pick an
        # empty pool.
        pool = self._phase_pool("prefill")
        if not pool:
            with self._lock:
                self.requests_unavailable += 1
            raise FleetUnavailable("no routable worker",
                                   self.server_cfg.retry_after_s)
        t_route = time.perf_counter()
        h, hit, load = self._pick(pool, seq)
        self._recorder.add(
            "route", seq.trace_id, t_route, time.perf_counter(),
            dest=h.replica, hbm_hit=hit[0], host_hit=hit[1],
            fabric_hit=hit[2], load=load)
        cap = self.server_cfg.admission_queue_depth
        if cap > 0 and load >= cap:
            # Affinity saturated a warm worker: least-loaded fallback
            # before shedding, exactly like EngineGroup.submit.
            h2, _, load2 = self._pick(pool)
            if load2 >= cap:
                # Class-aware admission (README "Elastic fleet"): with
                # per-class queues enabled, saturation means different
                # things per class. Batch/background requests PARK in
                # a bounded deferred lane instead of bouncing a 429 at
                # the client; interactive requests PREEMPT the newest
                # batch-lane occupant (recompute-resume puts it back,
                # byte-identical under greedy) and take its slot. Only
                # when neither escape works does the legacy shed fire.
                cls = seq.priority_class or "interactive"
                if self.server_cfg.class_queue_depth > 0:
                    if class_rank(cls) > 0:
                        if self._defer(seq, on_token, on_finish, cls):
                            return
                        self._shed(seq, cls, load2, cap)
                    vw = self._preempt_for_interactive()
                    if vw is None:
                        self._shed(seq, cls, load2, cap)
                    h, hit = vw, (0, 0, 0)
                else:
                    self._shed(seq, cls, load2, cap)
            else:
                h, hit = h2, self._peek_hit(h2, seq)
        entry = _Tracked(_clone_request(seq), on_token, on_finish)
        entry.seq_local.trace_id = seq.trace_id
        entry.seq_local.enqueue_time = time.perf_counter()
        with self._lock:
            self._tracked[seq.request_id] = entry
        if not self._dispatch(entry, h, hit):
            self._retry_or_fail(entry, exclude=h)

    def _peek_hit(self, h: WorkerHandle,
                  seq: Sequence) -> Tuple[int, int, int]:
        if self.server_cfg.routing != "prefix_affinity":
            return (0, 0, 0)
        digests, prompt_pages = self._digests_for(seq)
        p = self._peek(h, digests)
        fx = kv_fabric.fabric_extra_pages(
            self.fabric.match_depth(digests), p["hbm"] + p["host"],
            prompt_pages)
        return (p["hbm"], p["host"], fx)

    def _fabric_pull(self, h: WorkerHandle, t: Sequence, warm: int,
                     fabric_extra: int, entry: "_Tracked") -> int:
        """Ship the fabric run beyond ``warm`` pages into worker ``h``'s
        host tier (import-kv). get_pages crc-verifies every blob — a
        corrupt or evicted-since-peek entry just shortens the run — and
        the pages re-serialize into one import blob whose embedded
        digests the worker re-verifies on adoption. Returns the pages
        actually shipped and applied (0 on any transport failure: the
        dispatch proceeds cold — the fabric is an accelerator, never a
        correctness dependency)."""
        if h.client is None:
            return 0
        digests = self._digests_for(t)[0]
        want = digests[warm:warm + fabric_extra]
        if self.arena is not None:
            # Zero-copy pull: ship descriptors; the worker reads each
            # slab from the arena, crc-verifies it there, and reports
            # rejects back so the pool drops them. No KV byte touches
            # a socket or this process.
            descs = self.fabric.get_descs(want)
            if descs:
                try:
                    r = h.client.rpc(
                        "import-kv",
                        digests=[d.hex() for d, _ in descs],
                        descs=[dd for _, dd in descs],
                        idem=f"fd{t.request_id}.{entry.attempts}."
                             f"{entry.generation}")
                    rejected = r.get("rejected_digests") or ()
                    for hexd in rejected:
                        self.fabric.reject(bytes.fromhex(hexd))
                    if not r.get("applied"):
                        return 0
                    return max(0, len(descs) - len(rejected))
                except (WorkerGone, TimeoutError, RuntimeError) as e:
                    telemetry.log_event("fabric_pull_failed",
                                        level="warning",
                                        replica=h.replica, error=str(e))
                    return 0
        entries = self.fabric.get_pages(want)
        if not entries:
            return 0
        try:
            blob = kvc.serialize_host_pages([p for _, p in entries])
            with self._lock:
                self.rpc_blob_bytes["import-kv"] += len(blob)
            r = h.client.rpc(
                "import-kv", blob=blob,
                digests=[d.hex() for d, _ in entries],
                idem=f"f{t.request_id}.{entry.attempts}."
                     f"{entry.generation}")
            if not r.get("applied"):
                return 0
        except (WorkerGone, TimeoutError, RuntimeError) as e:
            telemetry.log_event("fabric_pull_failed", level="warning",
                                replica=h.replica, error=str(e))
            return 0
        return len(entries)

    def _shed(self, seq: Sequence, cls: str, load: int, cap: int) -> None:
        """Terminal 429: count it (globally and per class) and raise.
        Message format is pinned by tests/clients — keep it identical
        to the pre-class-queue single-cap shed."""
        with self._lock:
            self.requests_shed += 1
            self.class_shed[cls] = self.class_shed.get(cls, 0) + 1
        # A shed IS terminal: seal the route span so sustained overload
        # can't fill the recorder's open table and evict a LIVE
        # request's trace.
        self._recorder.seal(seq.trace_id)
        raise FleetSaturated(
            f"admission queue cap reached ({load} >= {cap} on "
            "the least-loaded worker)",
            self.server_cfg.retry_after_s)

    def _defer(self, seq: Sequence, on_token: Callable,
               on_finish: Callable, cls: str) -> bool:
        """Park a batch/background request in its class lane instead of
        shedding it. Returns False when the lane itself is full (then
        the caller sheds — the deferred queues are bounded so a batch
        flood can't grow router memory without limit)."""
        entry = _Tracked(_clone_request(seq), on_token, on_finish)
        entry.seq_local.trace_id = seq.trace_id
        entry.seq_local.enqueue_time = time.perf_counter()
        with self._lock:
            q = self._deferred[cls]
            if len(q) >= self.server_cfg.class_queue_depth:
                return False
            self._tracked[seq.request_id] = entry
            q.append(entry)
        telemetry.log_event("request_deferred", request_id=seq.request_id,
                            trace_id=seq.trace_id, priority_class=cls)
        return True

    def _preempt_for_interactive(self) -> Optional[WorkerHandle]:
        """Watermark preemption: evict the newest lowest-class running
        request back to its deferred lane (recompute-resume replays its
        generated tokens on re-dispatch — byte-identical under greedy)
        and return the worker whose slot it freed."""
        with self._lock:
            victims = [e for e in self._tracked.values()
                       if e.worker is not None
                       and class_rank(e.template.priority_class) > 0]
            if not victims:
                return None
            victim = max(victims, key=lambda e: (
                class_rank(e.template.priority_class), e.t_submit))
            vw, vc = victim.worker, victim.client
            victim.generation += 1
            victim.worker = victim.client = None
            victim.attempts += 1
            vcls = victim.template.priority_class
            self.class_preemptions[vcls] = (
                self.class_preemptions.get(vcls, 0) + 1)
            # Front of its lane: a preempted request resumes before any
            # never-started work of the same class.
            self._deferred[vcls].appendleft(victim)
        rid = victim.template.request_id

        def _rpc_cancel(client=vc):
            try:
                client.rpc("cancel", timeout=10.0, rid=rid)
            except (WorkerGone, TimeoutError, RuntimeError):
                pass

        if vc is not None:
            threading.Thread(target=_rpc_cancel, daemon=True,
                             name="fleet-preempt-cancel").start()
        telemetry.log_event("class_preempted", request_id=rid,
                            trace_id=victim.template.trace_id,
                            priority_class=vcls, replica=vw.replica)
        return vw

    def _pump_deferred(self) -> None:
        """Monitor-thread lane drain: re-admit parked batch/background
        work whenever capacity frees up. Single consumer (the monitor),
        so head-pop races only against cancel()."""
        if not any(self._deferred.values()):
            return
        cap = self.server_cfg.admission_queue_depth
        while True:
            with self._lock:
                entry = None
                for cls in ("batch", "background"):
                    q = self._deferred[cls]
                    # Purge heads cancelled while parked.
                    while q and q[0].template.request_id \
                            not in self._tracked:
                        q.popleft()
                    if q:
                        entry = q[0]
                        break
                if entry is None:
                    return
            pool = self._phase_pool(self._entry_phase(entry))
            if not pool:
                return
            h, hit, load = self._pick(pool, entry.template)
            if cap > 0 and load >= cap:
                return
            with self._lock:
                q = self._deferred[cls]
                if (not q or q[0] is not entry
                        or entry.template.request_id not in self._tracked):
                    continue
                q.popleft()
            if not self._dispatch(entry, h, hit):
                self._retry_or_fail(entry, exclude=h)

    def _dispatch(self, entry: _Tracked, h: WorkerHandle,
                  hit: Tuple[int, int, int]) -> bool:
        """Submit one attempt to one worker. Returns False when the
        worker refused (dead/draining) so the caller can re-route."""
        t = entry.template
        gen_tokens = list(entry.tokens)
        with self._lock:
            entry.worker, entry.client = h, h.client
        hbm, host, fabric_extra = hit
        meta = entry.handoff_meta
        live_handoff = (meta is not None
                        and bool(entry.handoff_blob or entry.handoff_desc)
                        and len(gen_tokens) == meta["n_generated"])
        # Fabric pull (README "KV fabric"): pages the router's pool
        # covers beyond this worker's own warm depth ship to its host
        # tier over the import-kv RPC BEFORE the submit — the verb
        # replies only after the engine loop applied the import, so
        # this request's prefill is guaranteed to see them. A live
        # handoff dispatch skips it: the attempt already carries the
        # full KV, and pre-warming the same pages is a redundant
        # import-kv round trip on the handoff critical path.
        fabric_pulled = 0
        if fabric_extra > 0 and not live_handoff:
            fabric_pulled = self._fabric_pull(
                h, t, hbm + host, fabric_extra, entry)
        total_hit = hbm + host + fabric_pulled
        sl = entry.seq_local
        sl.routed_replica = h.replica
        sl.route_hit_pages = total_hit
        sl.route_host_hit_pages = host
        sl.route_fabric_hit_pages = fabric_pulled
        sl.attempt = entry.attempts
        stats = self._route_stats[h.replica]
        if total_hit > 0:
            self.route_prefix_hits += 1
            stats["hits"] += 1
            stats["hit_pages"] += total_hit
            stats["host_hit_pages"] += host
            self._route_hit_pages_hist.observe(total_hit)
        else:
            self.route_cold += 1
            stats["cold"] += 1
        if fabric_pulled > 0:
            self.route_fabric_hits += 1
            stats["fabric_hit_pages"] += fabric_pulled
            self._route_fabric_hit_pages_hist.observe(fabric_pulled)
        if gen_tokens:
            self.resume_resubmits += 1
            entry.resume_stream_len = (
                min(len(t.prompt_tokens) + len(gen_tokens),
                    self.engine_cfg.max_context - 1))
        payload = {
            "request_id": t.request_id,
            "route_hit_pages": total_hit,
            "route_host_hit_pages": host,
            "route_fabric_hit_pages": fabric_pulled,
            "prompt_tokens": list(t.prompt_tokens),
            "max_new_tokens": t.max_new_tokens,
            "temperature": t.temperature, "top_p": t.top_p,
            "top_k": t.top_k, "seed": t.seed,
            "repeat_penalty": t.repeat_penalty,
            "repeat_last_n": t.repeat_last_n,
            "eos_token_id": t.eos_token_id,
            "trace_id": t.trace_id,
            "class": t.priority_class,
            "attempt": entry.attempts,
            "generated": gen_tokens,
        }
        blob = b""
        if meta is not None:
            if live_handoff:
                # Live handoff resume: the worker adopts the exported KV
                # (incl. the partial final page) and continues decode
                # with zero recomputed tokens. On the shm plane the
                # frame carries only the arena descriptor — the decode
                # worker reads+verifies the slab itself and falls back
                # to recompute-resume on any stale/corrupt read.
                payload["handoff"] = {"ctx_len": meta["ctx_len"]}
                if entry.handoff_desc is not None:
                    payload["handoff"]["kv_desc"] = entry.handoff_desc
                else:
                    blob = entry.handoff_blob
            else:
                # Decode advanced past the export (the blob was dropped
                # at the first post-handoff token, or the length no
                # longer matches — e.g. the adopter died mid-stream):
                # fall back to recompute-resume from the router's token
                # record, byte-identical under greedy.
                entry.handoff_blob = entry.handoff_meta = None
                self._release_handoff_desc(entry)
                with self._lock:
                    self.pd_handoff_recomputes += 1
        # Idempotency token, unique per dispatch attempt: a duplicate
        # submit frame (retry over a fresh connection after a lost ack)
        # replays the recorded ack instead of admitting a second live
        # attempt.
        idem = f"s{t.request_id}.{entry.attempts}.{entry.generation}"
        try:
            if blob:
                with self._lock:
                    self.rpc_blob_bytes["submit"] += len(blob)
            h.client.rpc("submit", seq=payload, blob=blob, idem=idem)
            return True
        except (WorkerGone, RuntimeError) as e:
            telemetry.log_event(
                "dispatch_refused", level="warning", replica=h.replica,
                request_id=t.request_id, error=str(e) or type(e).__name__)
            return False
        except TimeoutError:
            # The worker wedged with this attempt (or the RPC is still
            # QUEUED behind a busy reader): count the victim toward the
            # poison gate, and cancel so the worker cannot later decode
            # a ghost alongside the re-routed copy. Best effort — if
            # the worker is truly dead the cancel fails too.
            entry.failed_workers.add(h.replica)
            try:
                h.client.rpc("cancel", timeout=5.0, rid=t.request_id,
                             idem=f"c{idem}")
            except (WorkerGone, TimeoutError, RuntimeError):
                pass
            return False

    def _retry_or_fail(self, entry: _Tracked,
                       exclude: Optional[WorkerHandle] = None) -> None:
        """Re-route one attempt after a refused/failed dispatch; fail
        cleanly when no worker remains.

        An empty pool or a refused dispatch is often a transient gap,
        not an outage — the target's connection is mid-reconnect after
        a wedge recycle, or the supervisor is restarting the process.
        Re-pick inside a short grace window before declaring the fleet
        unavailable; each round re-checks the claim so a competing
        failover path never double-runs the request."""
        if exclude is not None:
            with self._lock:
                if entry.worker is not exclude:
                    # A competing path (worker-down failover / migrate)
                    # detached and re-dispatched this entry while our
                    # dispatch to `exclude` was failing — re-routing it
                    # again here would run the request twice.
                    return
                entry.worker = entry.client = None
        last = exclude
        deadline = time.monotonic() + _REROUTE_GRACE_S
        while not self._stopping:
            if self._quarantine_if_poison(entry):
                return
            phase = self._entry_phase(entry)
            pool = [h for h in self._phase_pool(phase) if h is not last]
            if not pool:
                pool = ([h for h in self._routable() if h is not last]
                        or self._routable())
            if pool:
                h, hit, _ = self._pick(pool, entry.template, phase=phase)
                if self._dispatch(entry, h, hit):
                    return
                with self._lock:
                    if entry.worker is not h:
                        return      # a competing path took over
                    entry.worker = entry.client = None
                last = h
            if time.monotonic() >= deadline:
                break
            time.sleep(0.25)
        rid = entry.template.request_id
        telemetry.log_event("request_unavailable", level="warning",
                            request_id=rid, attempts=entry.attempts)
        with self._lock:
            self._tracked.pop(rid, None)
            self._release_handoff_desc(entry)
        self._finish_trace(entry, "unavailable")
        ghost = entry.seq_local
        ghost.done, ghost.finish_reason = True, "unavailable"
        ghost.finish_time = time.perf_counter()
        entry.on_finish(ghost)

    def cancel(self, request_id: int) -> None:
        with self._lock:
            entry = self._tracked.pop(request_id, None)
            if entry is not None:
                entry.generation += 1
                h = entry.worker
                self._release_handoff_desc(entry)
        if entry is None or h is None or h.client is None:
            return

        def _rpc_cancel(client=h.client):
            # Fire-and-forget: cancel is called from HTTP handlers
            # (timeouts, disconnects, stop sequences) that must not
            # block on a slow worker; a lost cancel only costs the
            # worker a few wasted tokens before its own reap.
            try:
                client.rpc("cancel", rid=request_id,
                           idem=f"c{request_id}.x")
            except (WorkerGone, TimeoutError, RuntimeError):
                pass

        threading.Thread(target=_rpc_cancel, name="fleet-cancel",
                         daemon=True).start()

    # ----------------------------------------------------------- events

    def _on_event(self, h: WorkerHandle, client: WorkerClient,
                  obj: dict, blob: bytes) -> None:
        ev = obj.get("ev")
        if self._stopping and ev in ("migrate", "drained", "handoff"):
            return      # teardown: no re-routing onto closing workers
        if ev == "token":
            self._on_token(h, client, obj)
        elif ev == "finish":
            self._on_finish(h, client, obj)
        elif ev == "handoff":
            self._on_handoff(h, client, obj, blob)
        elif ev == "spans":
            # A prefill worker's sealed handoff-side spans (the handoff
            # frame itself left before the worker sealed its trace).
            self._recorder.ingest(obj.get("trace") or "",
                                  obj.get("spans") or ())
        elif ev == "migrate":
            self._on_migrate(h, client, obj, blob)
        elif ev == "fabric_put":
            self._on_fabric_put(h, obj, blob)
        elif ev == "drained":
            self._on_drained(h, client, obj)

    def _on_fabric_put(self, h: WorkerHandle, obj: dict,
                       blob: bytes) -> None:
        """Ingest a worker's published prefix pages into the fabric
        pool (README "KV fabric"). The frame carries per-page blob
        lengths so the event thread slices without deserializing;
        integrity is enforced at get time (every pull re-verifies its
        blob's crc32c), so a corrupt publish can occupy a slot but can
        never be adopted. A frame whose lengths disagree with the blob
        is dropped whole — never partially ingested."""
        digests = obj.get("digests") or ()
        descs = obj.get("descs")
        if descs is not None:
            # Zero-copy publish: descriptors only — register each slab
            # with the supervisor's ledger, pool the descriptor. The
            # payload bytes never traversed this socket (the verb's
            # rpc_blob_bytes row stays at 0, the lane's grade).
            if len(digests) != len(descs) or blob:
                with self._lock:
                    self.frame_errors += 1
                telemetry.log_event(
                    "fabric_put_malformed", level="warning",
                    replica=h.replica, digests=len(digests),
                    descs=len(descs), blob_bytes=len(blob))
                return
            for d, desc in zip(digests, descs):
                if self._arena_dir is not None:
                    self._arena_dir.register(desc)
                self.fabric.put_desc(bytes.fromhex(d), desc)
            return
        lens = obj.get("lens") or ()
        if len(digests) != len(lens) or sum(lens) != len(blob):
            with self._lock:
                self.frame_errors += 1
            telemetry.log_event(
                "fabric_put_malformed", level="warning",
                replica=h.replica, digests=len(digests),
                lens=len(lens), blob_bytes=len(blob))
            return
        with self._lock:
            self.rpc_blob_bytes["fabric_put"] += len(blob)
        off = 0
        for d, n in zip(digests, lens):
            self.fabric.put_blob(bytes.fromhex(d), blob[off:off + n])
            off += n

    def _entry_for(self, rid: int, h: WorkerHandle,
                   client: WorkerClient) -> Optional[_Tracked]:
        entry = self._tracked.get(rid)
        if entry is None or entry.worker is not h \
                or entry.client is not client:
            return None
        return entry

    def _on_token(self, h, client, obj) -> None:
        with self._lock:
            entry = self._entry_for(obj["rid"], h, client)
            if entry is None:
                return
            tok = int(obj["t"])
            k = obj.get("k")
            if k is not None and int(k) != len(entry.tokens):
                # Stream-index gap: a frame went missing (or arrived
                # twice) between this worker and us. Appending would
                # silently corrupt the completion — recycle the
                # connection instead and let resync re-route the
                # request from its last known-good prefix.
                client.lost_reason = client.lost_reason or "stream_gap"
                bad = client
            else:
                bad = None
                entry.tokens.append(tok)
        if bad is not None:
            telemetry.log_event(
                "stream_gap", level="error", replica=h.replica,
                request_id=obj["rid"], expected=len(entry.tokens),
                got=int(k))
            bad.close()
            return
        with self._lock:
            meta = entry.handoff_meta
            if ((entry.handoff_blob is not None
                 or entry.handoff_desc is not None) and meta is not None
                    and len(entry.tokens) > meta["n_generated"]):
                # The adopter streamed past the export: the blob can
                # never be dispatched again (a re-adoption would fork
                # the stream) — drop it now rather than pinning
                # megabytes of dead KV for the stream's lifetime. The
                # small meta stays so a later failover still counts as
                # a handoff recompute in _dispatch.
                entry.handoff_blob = None
                self._release_handoff_desc(entry)
            sl = entry.seq_local
            sl.generated.append(tok)
            if sl.first_token_time == 0.0:
                sl.first_token_time = time.perf_counter()
                # Router-observed TTFT (submit -> first streamed token,
                # deferral park time included) — the autoscaler's
                # breach sensor.
                self._ttft_obs.append(
                    (sl.first_token_time,
                     sl.first_token_time - entry.t_submit))
        entry.on_token(sl, tok)

    def _finish_trace(self, entry: _Tracked, reason: str) -> None:
        """Terminal end of a tracked request: emit the router's root
        span (submit -> terminal, every attempt/handoff inside it) and
        seal the assembled cross-process trace into the recent ring —
        the /debug/trace and Chrome-export source."""
        rec = self._recorder
        if not rec.enabled:
            return
        t = entry.template
        tid = t.trace_id or str(t.request_id)
        rec.add("request", tid, entry.t_submit, time.perf_counter(),
                parent="", reason=reason, attempts=entry.attempts,
                output_tokens=len(entry.tokens))
        rec.seal(tid)

    def _on_finish(self, h, client, obj) -> None:
        rid = obj["rid"]
        reason = obj.get("reason", "stop")
        # Worker-side spans ride the finish frame; fold them in before
        # the terminal path below seals the trace.
        self._recorder.ingest(obj.get("trace") or "",
                              obj.get("spans") or ())
        with self._lock:
            entry = self._entry_for(rid, h, client)
            if entry is None:
                return
            retryable = (reason in _RETRYABLE
                         and not entry.tokens
                         and entry.attempts
                         < self.server_cfg.failover_max_retries)
            # Zero-delivery retries replay from the prompt: prefill work.
            pool = ([w for w in self._phase_pool("prefill") if w is not h]
                    or self._routable()) if retryable else []
            if pool:
                entry.attempts += 1
                entry.generation += 1
                entry.worker = entry.client = None   # claim (see above)
                self.retries_attempted += 1
            else:
                self._tracked.pop(rid, None)
                self._release_handoff_desc(entry)
                if entry.attempts and reason in ("stop", "length"):
                    self.retries_succeeded += 1
            # Migration accounting: the resume stream this attempt
            # re-prefilled, minus what the destination's cache tiers
            # (incl. migrated pages) served.
            if entry.resume_stream_len and not pool:
                cached = int(obj.get("cached_tokens", 0))
                reused = min(cached, entry.resume_stream_len)
                self.resume_reused_tokens += reused
                self.resume_recomputed_tokens += (
                    entry.resume_stream_len - reused)
        if pool:
            hh, hit, _ = self._pick(pool, entry.template)
            if self._dispatch(entry, hh, hit):
                return
            self._retry_or_fail(entry, exclude=hh)
            return
        self._finish_trace(entry, reason)
        sl = entry.seq_local
        sl.done = True
        sl.finish_reason = reason
        sl.finish_time = time.perf_counter()
        sl.cached_tokens = int(obj.get("cached_tokens", 0))
        sl.host_restored_pages = int(obj.get("host_restored_pages", 0))
        sl.preemptions = int(obj.get("preemptions", 0))
        if sl.first_token_time and obj.get("prefill_s") is not None:
            # Synthesize a local prefill_start from the worker-reported
            # prefill duration so the Ollama duration counters hold.
            sl.prefill_start = max(
                sl.enqueue_time,
                sl.first_token_time - float(obj["prefill_s"]))
        entry.on_finish(sl)

    def _checked_blob(self, blob: bytes, path: str, rid: int) -> bytes:
        """Gate a KV blob on its end-to-end digest before it can be
        re-dispatched or imported. A corrupt blob is rejected AND
        counted — never adopted silently — and the caller falls back to
        recompute-resume from the router's token record
        (byte-identical under greedy), exactly like a missing blob."""
        if not blob:
            return blob
        err = kvc.verify_host_pages_blob(blob)
        if err is None:
            return blob
        with self._lock:
            self.kv_rejections += 1
        telemetry.log_event(
            "kv_blob_rejected", level="error", path=path,
            request_id=rid, bytes=len(blob), error=err)
        if self._flight is not None:
            self._flight.capture("kv_corruption", min_interval_s=0.0)
        return b""

    def _fabric_salvage(self, digests: List[bytes], blob: bytes,
                        rid: int, path: str) -> int:
        """Pool-mediated fallback for a point-to-point KV transfer
        whose destination vanished (README "KV fabric" decision table):
        park the export's full prompt-prefix pages in the fabric pool,
        keyed by their chain digests, so the eventual resubmission's
        fabric pull restores them instead of re-prefilling the whole
        stream. Partial/suffix pages beyond the digest chain are not
        poolable (chain digests key FULL pages only) and still ride the
        recompute path. Returns pages parked."""
        if self.fabric.capacity <= 0 or not blob or not digests:
            return 0
        try:
            pages = kvc.deserialize_host_pages(blob)
        except Exception:  # noqa: BLE001 — checked upstream; best-effort
            return 0
        n = self.fabric.put_pages(list(zip(digests, pages)))
        if n:
            telemetry.log_event(
                "fabric_salvage", level="info", path=path,
                request_id=rid, pages=n)
        return n

    def _on_handoff(self, h, client, obj, blob) -> None:
        """A prefill worker settled a prompt's prefill and exported the
        LIVE sequence (README "P/D disaggregation"): KV pages including
        the partial final page, plus the stream state the router already
        tracks. Route it to the least-loaded decode worker and resume
        there as an adoption — no re-prefill, zero recomputed tokens on
        the clean path; every failure mode degrades to the existing
        recompute-resume machinery (byte-identical under greedy)."""
        rid = obj["rid"]
        t0 = time.perf_counter()
        with self._lock:
            entry = self._entry_for(rid, h, client)
            if entry is None:
                return
            entry.generation += 1
            # DETACH under the lock (the _on_migrate claim pattern): a
            # racing worker-down failover must not double-resubmit.
            entry.worker = entry.client = None
            entry.attempts += 1
            self.pd_handoffs += 1
        n_gen = int(obj.get("n_generated", 0))
        entry.handoff_meta = {"ctx_len": int(obj.get("ctx_len", 0)),
                              "n_generated": n_gen}
        kv_desc = obj.get("kv_desc")
        if kv_desc is not None:
            # Zero-copy handoff: the export rode the arena, only this
            # descriptor crossed the socket. Register the slab so the
            # leak ledger tracks it until the decode worker adopted (or
            # every fallback released it).
            if self._arena_dir is not None:
                self._arena_dir.register(kv_desc)
            entry.handoff_desc = dict(kv_desc)
            blob = b""
        else:
            if blob:
                with self._lock:
                    self.rpc_blob_bytes["handoff"] += len(blob)
            blob = self._checked_blob(blob, "handoff", rid)
        entry.handoff_blob = blob or None
        if n_gen != len(entry.tokens):
            # Out of sync with the export (events are FIFO per
            # connection, so this should not happen): recompute-resume.
            telemetry.log_event(
                "handoff_token_mismatch", level="warning",
                request_id=entry.template.trace_id or str(rid),
                worker_generated=n_gen,
                router_streamed=len(entry.tokens))
            entry.handoff_blob = entry.handoff_meta = None
            self._release_handoff_desc(entry)
            with self._lock:
                self.pd_handoff_recomputes += 1
        pool = [w for w in self._phase_pool("decode") if w is not h]
        if not pool:
            pool = ([w for w in self._routable() if w is not h]
                    or self._routable())
        if not pool:
            # Point-to-point handoff lost its destination: park the
            # settled prefix in the fabric pool so whichever worker the
            # grace-window retry eventually finds pulls it from the
            # pool instead of re-prefilling the whole stream. A
            # descriptor export is materialized from the arena first
            # (the salvage outlives the slab's region).
            if not blob and entry.handoff_desc is not None \
                    and self.arena is not None:
                try:
                    blob = self.arena.read(entry.handoff_desc)
                except shm_arena.ArenaError:
                    blob = b""
                self._release_handoff_desc(entry)
                entry.handoff_blob = blob or None
            self._fabric_salvage(
                self._digests_for(entry.template)[0], blob, rid,
                "handoff")
            self._retry_or_fail(entry)     # already claimed above
            return
        if len(pool) == 1 and (entry.handoff_blob
                               or entry.handoff_desc is not None):
            # Forced choice: one decode candidate and a live export in
            # hand. The peek RPC would only rank a single option, and
            # the dispatch carries the full KV so warmth cannot change
            # the answer — skip the round trip on the handoff critical
            # path.
            dest, hit = pool[0], (0, 0, 0)
        else:
            dest, hit, _ = self._pick(pool, entry.template,
                                      phase="decode")
        telemetry.log_event(
            "request_handoff", level="info",
            request_id=entry.template.trace_id or str(rid),
            source=h.replica, dest=dest.replica,
            ctx_len=entry.handoff_meta["ctx_len"]
            if entry.handoff_meta else 0,
            streamed=len(entry.tokens))
        if self._dispatch(entry, dest, hit):
            self._pd_handoff_s_hist.observe(
                float(obj.get("export_s") or 0.0)
                + time.perf_counter() - t0)
            # Router-side handoff span: routing + dispatch until the
            # decode worker accepted the resume (the worker-side export
            # span precedes it on the assembled timeline).
            self._recorder.add(
                "handoff", entry.template.trace_id or str(rid),
                t0, time.perf_counter(), source=h.replica,
                dest=dest.replica, export_s=obj.get("export_s"),
                streamed=len(entry.tokens))
        else:
            self._retry_or_fail(entry, exclude=dest)

    def _on_migrate(self, h, client, obj, blob) -> None:
        """A draining worker exported one in-flight request: import its
        KV pages into a destination worker's host tier and resubmit with
        the router's token record — the swap-in-resume path."""
        rid = obj["rid"]
        t_mig = time.perf_counter()
        # The draining worker's in-flight spans (chunks, swaps, the
        # drain_export) ride the migrate event — fold them in so the
        # trace survives the process that recorded them.
        self._recorder.ingest(obj.get("trace") or "",
                              obj.get("spans") or ())
        with self._lock:
            entry = self._entry_for(rid, h, client)
            if entry is None:
                return
            entry.generation += 1
            # DETACH under the lock: the monitor's worker-down failover
            # can race this handler for the same entry (the draining
            # process exits while its last events are still in the
            # reader's buffer); whoever claims it first owns the one
            # resubmission, the loser's _entry_for sees a changed
            # worker and stands down.
            entry.worker = entry.client = None
            entry.attempts += 1
            self.migrations += 1
            self.retries_attempted += 1
            self.failovers += 1
        n_gen = int(obj.get("n_generated", 0))
        if n_gen != len(entry.tokens):
            telemetry.log_event(
                "migrate_token_mismatch", level="warning",
                request_id=entry.template.trace_id or str(rid),
                worker_generated=n_gen, router_streamed=len(entry.tokens))
        digests = [bytes.fromhex(d) for d in obj.get("digests") or ()]
        kv_desc = obj.get("kv_desc")
        if kv_desc is not None and self._arena_dir is not None:
            self._arena_dir.register(kv_desc)
        if blob:
            with self._lock:
                self.rpc_blob_bytes["migrate"] += len(blob)
        blob = self._checked_blob(blob, "migrate", rid)
        phase = self._entry_phase(entry)
        others = ([w for w in self._phase_pool(phase) if w is not h]
                  or [w for w in self._routable() if w is not h])
        if not others:
            # Migration lost its destination: park the exported pages
            # in the fabric pool (keyed by the digests the export
            # carried) so the grace-window retry's dispatch pulls them
            # back instead of recompute-prefilling the stream. A
            # descriptor export is materialized from the arena first.
            if not blob and kv_desc is not None and self.arena is not None:
                try:
                    blob = self.arena.read(kv_desc)
                except shm_arena.ArenaError:
                    blob = b""
            if kv_desc is not None and self._arena_dir is not None:
                self._arena_dir.release(kv_desc)
            self._fabric_salvage(digests, blob, rid, "migrate")
            # No exclude: this entry is already claimed (detached) by
            # the block above and no dispatch was attempted — the guard
            # in _retry_or_fail only applies after a failed dispatch.
            self._retry_or_fail(entry)
            return
        dest, hit, _ = self._pick(others, entry.template, phase=phase)
        if (kv_desc is not None and digests
                and self.server_cfg.fleet_migrate
                and dest.client is not None):
            # Zero-copy migrate: forward the descriptor; the destination
            # adopts straight from the arena. The router never touches
            # the payload bytes.
            try:
                r = dest.client.rpc(
                    "import-kv", kv_desc=kv_desc,
                    digests=[d.hex() for d in digests],
                    idem=f"i{rid}.{entry.generation}")
                with self._lock:
                    self.migrated_pages += int(r.get("adopted", 0))
                    self.migrated_bytes += int(kv_desc.get("len", 0))
                hit = self._peek_hit(dest, entry.template)
            except (WorkerGone, TimeoutError, RuntimeError) as e:
                telemetry.log_event("migrate_import_failed",
                                    level="warning", error=str(e))
            finally:
                if self._arena_dir is not None:
                    self._arena_dir.release(kv_desc)
        elif (blob and digests and self.server_cfg.fleet_migrate
                and dest.client is not None):
            try:
                with self._lock:
                    self.rpc_blob_bytes["import-kv"] += len(blob)
                r = dest.client.rpc(
                    "import-kv", blob=blob,
                    digests=[d.hex() for d in digests],
                    idem=f"i{rid}.{entry.generation}")
                with self._lock:
                    self.migrated_pages += int(r.get("adopted", 0))
                    self.migrated_bytes += len(blob)
                # Re-peek so the routing span reflects the just-imported
                # warmth the resubmission will actually find.
                hit = self._peek_hit(dest, entry.template)
            except (WorkerGone, TimeoutError, RuntimeError) as e:
                telemetry.log_event("migrate_import_failed",
                                    level="warning", error=str(e))
        elif kv_desc is not None and self._arena_dir is not None:
            # Import preconditions failed (migration disabled, no
            # digests): the descriptor has no consumer — release it.
            self._arena_dir.release(kv_desc)
        telemetry.log_event(
            "request_migrated", level="warning",
            request_id=entry.template.trace_id or str(rid),
            source=h.replica, dest=dest.replica,
            pages=len(digests), streamed=len(entry.tokens))
        if self._dispatch(entry, dest, hit):
            self._recorder.add(
                "migrate", entry.template.trace_id or str(rid),
                t_mig, time.perf_counter(), source=h.replica,
                dest=dest.replica, pages=len(digests),
                streamed=len(entry.tokens))
        else:
            self._retry_or_fail(entry, exclude=dest)

    def _on_drained(self, h, client, obj) -> None:
        """Graceful exit notice: the final stats/metrics dump IS the
        restart carry (nothing is lost on a drain, unlike kill -9 where
        the carry is the last periodic scrape)."""
        if obj.get("metrics") and h.folded_incarnation != h.incarnation:
            h.last_metrics = obj["metrics"]
        if obj.get("stats"):
            h.last_stats = obj["stats"]
        if h.state == UP:
            h.state = DRAINING
        telemetry.log_event(
            "worker_drained", level="info", replica=h.replica,
            migrated_requests=obj.get("migrated_requests", 0))
        # The process exits right after this event; the monitor's poll()
        # flips it to RESTARTING and respawns. Any request the drain did
        # NOT migrate (e.g. migration raced the export budget) fails
        # over from the router's token record like a kill.

    def _failover_worker(self, h: WorkerHandle) -> None:
        """Resubmit every tracked request of a dead worker from the
        router's own token record (recompute-resume on a survivor;
        token-identical under greedy). Requests with no survivor fail
        cleanly with "unavailable"."""
        with self._lock:
            victims = [e for e in self._tracked.values()
                       if e.worker is h]
            for e in victims:
                e.generation += 1
                # Detach (see _on_migrate): claims the one resubmission
                # against a racing migrate-event handler.
                e.worker = e.client = None
                e.attempts += 1
                e.failed_workers.add(h.replica)
                self.retries_attempted += 1
                self.failovers += 1
        for entry in victims:
            if self._quarantine_if_poison(entry):
                continue
            phase = self._entry_phase(entry)
            others = ([w for w in self._phase_pool(phase) if w is not h]
                      or [w for w in self._routable() if w is not h])
            if not others:
                rid = entry.template.request_id
                with self._lock:
                    self._tracked.pop(rid, None)
                self._finish_trace(entry, "unavailable")
                ghost = entry.seq_local
                ghost.done, ghost.finish_reason = True, "unavailable"
                ghost.finish_time = time.perf_counter()
                entry.on_finish(ghost)
                continue
            dest, hit, _ = self._pick(others, entry.template, phase=phase)
            telemetry.log_event(
                "request_failover", level="warning",
                request_id=(entry.template.trace_id
                            or str(entry.template.request_id)),
                resubmitted=True, attempts=entry.attempts,
                streamed=len(entry.tokens))
            if not self._dispatch(entry, dest, hit):
                self._retry_or_fail(entry, exclude=dest)

    # ------------------------------------------------------------ chaos

    def apply_chaos(self, body: dict) -> dict:
        """POST /debug/chaos for the subprocess fleet: engine-level
        knobs forward to workers over the chaos RPC; the process-level
        verbs the in-process fleet can only simulate are REAL here —
        ``{"replica": i, "kill": "kill9"}`` SIGKILLs the worker process
        (supervisor restarts it; in-flight requests fail over from the
        router's token record) and ``{"kill": "sigterm"}`` triggers the
        graceful drain-and-migrate path. ``{"rpc": {...}}`` retunes the
        router<->worker frame-level fault injection (transport chaos)
        at runtime: the kwargs mirror the --chaos-rpc-* knobs, apply to
        every subsequently sent frame on both sides, and reset the
        per-replica deterministic schedules."""
        rpc = body.get("rpc")
        if rpc is not None:
            for k, v in dict(rpc).items():
                if k in self._chaos_rpc_kw and v is not None:
                    self._chaos_rpc_kw[k] = (tuple(v) if k == "verbs"
                                             else v)
            with self._lock:
                # Drop cached policies so new rates rebuild the
                # deterministic schedule from frame 0 (and a re-armed
                # wedge can fire again).
                self._chaos_policies.clear()
            for h in self.workers:
                if h.client is not None and h.client.alive:
                    h.client.chaos = self._make_chaos(h.replica)
                    try:
                        h.client.rpc("chaos", rpc=dict(rpc))
                    except (WorkerGone, TimeoutError, RuntimeError):
                        pass
            return {"rpc": {k: (list(v) if isinstance(v, tuple) else v)
                            for k, v in self._chaos_rpc_kw.items()}}
        kill = body.get("kill")
        if kill is not None:
            if kill not in ("kill9", "sigkill", "sigterm", "drain"):
                raise ValueError(
                    f"unknown kill chaos {kill!r}: one of "
                    "('kill9', 'sigterm')")
            idx = int(body["replica"])
            h = self.workers[idx]
            if h.proc is None or h.proc.poll() is not None:
                raise ValueError(f"worker {idx} has no live process")
            sig = (signal.SIGKILL if kill in ("kill9", "sigkill")
                   else signal.SIGTERM)
            os.kill(h.pid, sig)
            return {"replica": idx, "killed": kill, "pid": h.pid}
        replica = body.get("replica")
        targets = (self.workers if replica is None
                   else [self.workers[int(replica)]])
        fields = {k: body[k] for k in ("step_failure_rate",
                                       "step_wedge_s", "page_pressure")
                  if body.get(k) is not None}
        out = []
        for h in self.workers:
            state = {"step_failure_rate": None, "step_wedge_s": None,
                     "page_pressure": None}
            if h.client is not None and h.client.alive:
                try:
                    state = h.client.rpc(
                        "chaos", **(fields if h in targets else {}))
                    state = {k: v for k, v in state.items()
                             if k not in ("id", "ok")}
                except (WorkerGone, TimeoutError, RuntimeError):
                    pass
            out.append(state)
        return {"replicas": out}

    def drain_worker(self, replica: int,
                     migrate: Optional[bool] = None) -> None:
        """Programmatic graceful drain (benchmarks): same path as
        SIGTERM, but selectable migration for the comparison arm."""
        h = self.workers[replica]
        if h.client is None:
            raise ValueError(f"worker {replica} not running")
        kw = {} if migrate is None else {"migrate": migrate}
        h.client.rpc("drain", **kw)

    # --------------------------------------------------- elastic fleet

    def _add_worker(self, role: str) -> WorkerHandle:
        """Append a new replica slot (handle + role + per-replica
        routing/gauge state) without booting it. Index-keyed arrays
        grow BEFORE the workers append so no reader ever sees a worker
        whose replica index is out of range."""
        with self._lock:
            h = WorkerHandle(len(self.workers))
            self.roles.append(role)
            self._route_stats.append({"hits": 0, "cold": 0,
                                      "hit_pages": 0,
                                      "host_hit_pages": 0,
                                      "fabric_hit_pages": 0})
            self.workers.append(h)
        self._register_worker_gauges(h)
        return h

    def _autoscale_tick(self, now: float) -> None:
        """One control-loop step (monitor thread, ~1/s): scale up on a
        sustained pooled p95 SLO breach, scale down on a sustained lull.
        Hysteresis = separate breach/idle windows; flap damping = one
        cooldown shared by both directions; and NO action while any
        worker is mid-transition (booting/restarting/draining) — that
        is what makes a chaos kill and a scale-up never double-spawn."""
        scfg = self.server_cfg
        if self._stopping or self._rollout_lock.locked():
            return
        if any(h.state in (BOOTING, RESTARTING, DRAINING)
               for h in self.workers):
            self._breach_since = 0.0
            return
        live = self._live_workers()
        n = len(live)
        max_n = scfg.autoscale_max_replicas or (self.dp + 2)
        min_n = max(1, scfg.autoscale_min_replicas)
        cooled = (now - self._last_scale_t) >= scfg.autoscale_cooldown_s
        breached = False
        ecfg = self.engine_cfg
        if ecfg.slo_ttft_ms:
            # Router-observed TTFT over a rolling time horizon: the
            # sensor sees lane park time (engine-side rings do not),
            # and samples age out, so a finished burst releases the
            # breach and lets the idle path scale back down.
            horizon = max(5.0 * scfg.autoscale_breach_window_s,
                          2.0 * scfg.autoscale_cooldown_s)
            cut = time.perf_counter() - horizon  # samples' own clock
            with self._lock:
                while self._ttft_obs and self._ttft_obs[0][0] < cut:
                    self._ttft_obs.popleft()
                xs = sorted(v for _, v in self._ttft_obs)
            if xs:
                p95 = xs[min(len(xs) - 1, int(0.95 * len(xs)))]
                breached = p95 > ecfg.slo_ttft_ms / 1000.0
        if not breached and ecfg.slo_tpot_ms and self._tracked:
            # TPOT breach from the workers' pooled rings, gated on live
            # in-flight work (a count-based ring cannot age out on its
            # own — without traffic it must not pin the fleet wide).
            p95 = self._pooled_slo_quantile("tpot", 0.95)
            if p95 == p95 and p95 > ecfg.slo_tpot_ms / 1000.0:
                breached = True
        if breached:
            self._idle_since = 0.0
            if not self._breach_since:
                self._breach_since = now
            elif (now - self._breach_since >= scfg.autoscale_breach_window_s
                    and cooled and n < max_n):
                self._scale_up("slo_breach")
            return
        self._breach_since = 0.0
        occs = [float((h.last_health or {}).get("ladder_occupancy") or 0.0)
                for h in live if h.state == UP]
        pooled_occ = (sum(occs) / len(occs)) if occs else 1.0
        backlog = any(self._deferred.values())
        if backlog or pooled_occ >= scfg.autoscale_low_watermark:
            self._idle_since = 0.0
            return
        if not self._idle_since:
            self._idle_since = now
        elif (now - self._idle_since >= scfg.autoscale_idle_window_s
                and cooled and n > min_n and n > 1):
            self._scale_down("idle")

    def _scale_up(self, reason: str) -> None:
        t0 = time.perf_counter()
        role = self.server_cfg.autoscale_role or (
            "decode" if self.pd_enabled else "mixed")
        h = self._add_worker(role)
        telemetry.log_event("fleet_scale_up", replica=h.replica,
                            role=role, reason=reason)
        try:
            self._spawn(h)
        except (WorkerGone, TimeoutError, RuntimeError, OSError) as e:
            # Boot failed: hand the slot to the ordinary supervisor
            # (backoff respawn → quarantine) rather than special-casing.
            h.consecutive_failures += 1
            telemetry.log_event("worker_respawn_failed", level="error",
                                replica=h.replica, error=str(e))
            self._schedule_restart(h)
        with self._lock:
            self.scale_ups += 1
        self._last_scale_t = time.monotonic()
        self._breach_since = 0.0
        tid = f"scale-up-{self.scale_ups}"
        self._recorder.add("scale_up", tid, t0, time.perf_counter(),
                           parent="", replica=h.replica, role=role,
                           reason=reason)
        self._recorder.seal(tid)

    def _scale_down(self, reason: str) -> None:
        t0 = time.perf_counter()
        h = self._retire_candidate()
        if h is None:
            return
        h.retiring = True
        try:
            # PR 9 lossless scale-down: drain exports live KV as
            # migrate events, the router re-lands them on survivors,
            # and the post-drain exit lands in RETIRED (not a respawn)
            # because retiring is set.
            self.drain_worker(h.replica)
        except (WorkerGone, TimeoutError, RuntimeError, ValueError) as e:
            h.retiring = False
            telemetry.log_event("fleet_scale_down_failed", level="warning",
                                replica=h.replica, error=str(e))
            return
        with self._lock:
            self.scale_downs += 1
        self._last_scale_t = time.monotonic()
        self._idle_since = 0.0
        telemetry.log_event("fleet_scale_down", replica=h.replica,
                            reason=reason)
        tid = f"scale-down-{self.scale_downs}"
        self._recorder.add("scale_down", tid, t0, time.perf_counter(),
                           parent="", replica=h.replica, reason=reason)
        self._recorder.seal(tid)

    def _retire_candidate(self) -> Optional[WorkerHandle]:
        """Coldest UP replica that can leave without killing a P/D
        phase: fewest in-flight requests, then lowest occupancy, ties
        retire the newest index (scale-ups go first)."""
        cands = [h for h in self.workers
                 if h.state == UP and not h.retiring]
        if len(cands) <= 1:
            return None
        if self.pd_enabled:
            def _ok_without(w):
                rest = [self.roles[h.replica] for h in cands if h is not w]
                return (any(r in ("prefill", "mixed") for r in rest)
                        and any(r in ("decode", "mixed") for r in rest))
            cands = [h for h in cands if _ok_without(h)]
            if not cands:
                return None
        return min(cands, key=lambda h: (
            self._fleet_load(h),
            float((h.last_health or {}).get("ladder_occupancy") or 0.0),
            -h.replica))

    def rollout(self) -> dict:
        """Zero-downtime rolling upgrade (POST /debug/rollout): replace
        each worker one at a time under live traffic — spawn the
        successor FIRST, then drain-and-migrate the predecessor into
        the fleet, then let its post-drain exit retire it. In-flight
        sequences ride the migrate path (or recompute-resume), so no
        request fails or restarts from zero."""
        self._ensure_started()
        if self._stopping:
            raise ValueError("fleet is stopping")
        if not self._rollout_lock.acquire(blocking=False):
            raise ValueError("a rollout is already in progress")
        t0 = time.perf_counter()
        replaced, failed = [], []
        try:
            targets = [h for h in self.workers
                       if h.state == UP and not h.retiring]
            telemetry.log_event("fleet_rollout_start",
                                targets=[h.replica for h in targets])
            for old in targets:
                if old.state != UP:
                    continue    # died mid-rollout; supervisor owns it
                succ = self._add_worker(self.roles[old.replica])
                try:
                    self._spawn(succ)
                except (WorkerGone, TimeoutError, RuntimeError,
                        OSError) as e:
                    # Never retire a predecessor without a live
                    # successor: abort the rollout, keep serving.
                    succ.state = DEAD
                    failed.append({"replica": old.replica,
                                   "successor": succ.replica,
                                   "error": str(e)})
                    telemetry.log_event("fleet_rollout_spawn_failed",
                                        level="error",
                                        replica=succ.replica,
                                        error=str(e))
                    break
                old.retiring = True
                try:
                    self.drain_worker(old.replica)
                except (WorkerGone, TimeoutError, RuntimeError,
                        ValueError) as e:
                    # The predecessor died or restarted out from under
                    # the rollout (e.g. chaos): the supervisor owns it
                    # now and its in-flight work already failed over.
                    # The successor stays (extra capacity is harmless);
                    # move on without stalling the pass.
                    old.retiring = False
                    telemetry.log_event("fleet_rollout_drain_failed",
                                        level="warning",
                                        replica=old.replica,
                                        error=str(e))
                    replaced.append({"old": old.replica,
                                     "new": succ.replica,
                                     "old_state": old.state})
                    continue
                deadline = (time.monotonic()
                            + self.server_cfg.drain_timeout_s + 30.0)
                while (time.monotonic() < deadline
                       and old.state not in (RETIRED, DEAD)
                       and old.retiring):
                    time.sleep(0.05)
                replaced.append({"old": old.replica,
                                 "new": succ.replica,
                                 "old_state": old.state})
        finally:
            with self._lock:
                self.rollouts += 1
            tid = f"rollout-{self.rollouts}"
            self._recorder.add("rollout", tid, t0, time.perf_counter(),
                               parent="", replaced=len(replaced),
                               failed=len(failed))
            self._recorder.seal(tid)
            self._rollout_lock.release()
        wall = time.perf_counter() - t0
        telemetry.log_event("fleet_rollout_done",
                            replaced=len(replaced), failed=len(failed),
                            wall_s=round(wall, 3))
        return {"replaced": replaced, "failed": failed,
                "live": len(self._live_workers()),
                "wall_s": round(wall, 3)}

    # ---------------------------------------------------- observability

    def embed_many(self, batch):
        import numpy as np

        routable = self._routable()
        if not routable:
            with self._lock:
                self.requests_unavailable += 1
            raise FleetUnavailable("no routable worker",
                                   self.server_cfg.retry_after_s)
        h, _, _ = self._pick(routable)
        r = h.client.rpc("embed", timeout=600.0, batch=batch)
        return np.asarray(r["embeddings"])

    def supervision_counters(self) -> dict:
        stats = [h.last_stats for h in self.workers if h.last_stats]
        with self._lock:
            return {
                "retries_attempted": self.retries_attempted,
                "retries_succeeded": self.retries_succeeded,
                "failovers": self.failovers,
                "requests_shed": self.requests_shed,
                "requests_unavailable": self.requests_unavailable,
                "route_prefix_hits": self.route_prefix_hits,
                "route_cold": self.route_cold,
                # Fleet KV fabric (README "KV fabric"): same keys as
                # the in-process backend's view.
                "route_fabric_hits": self.route_fabric_hits,
                "fabric_puts": self.fabric.puts,
                "fabric_hits": self.fabric.hits,
                "preemptions": sum(d.get("preemptions", 0)
                                   for d in stats),
                "recompute_resumes": sum(d.get("recompute_resumes", 0)
                                         for d in stats),
                "states": [h.state for h in self.workers],
                # Process-fleet extras (README "Process fleet").
                "fleet": "subprocess",
                "worker_restarts": sum(h.restarts for h in self.workers),
                # P/D disaggregation (README "P/D disaggregation").
                "roles": list(self.roles),
                "pd_handoffs": self.pd_handoffs,
                "pd_handoff_recomputes": self._pd_recomputes_total(),
                "pd_adoptions": sum(d.get("pd_adoptions", 0)
                                    for d in stats),
                # Router-side handoff wall as a diffable phase snapshot
                # (the engine "phases" shape): a handoff stall shows up
                # here without log-diving.
                "phases": {"pd_handoff_s":
                           self._pd_handoff_s_hist.phase_snapshot()},
                "migrations": self.migrations,
                "migrated_pages": self.migrated_pages,
                "migrated_bytes": self.migrated_bytes,
                "resume_resubmits": self.resume_resubmits,
                "resume_recomputed_tokens": self.resume_recomputed_tokens,
                "resume_reused_tokens": self.resume_reused_tokens,
                "swap_in_resumes": sum(d.get("swap_in_resumes", 0)
                                       for d in stats),
                # Elastic fleet (README "Elastic fleet").
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "rollouts": self.rollouts,
                "class_preemptions": dict(self.class_preemptions),
                "class_shed": dict(self.class_shed),
                "class_deferred": {c: len(q)
                                   for c, q in self._deferred.items()},
                # Byzantine transport (README "Failure model").
                "worker_reconnects": self.reconnects,
                "rpc_timeouts": self.rpc_timeouts,
                "frame_errors": self.frame_errors,
                "kv_integrity_rejections": self._kv_rejections_total(),
                "poison_requests": self.poison_requests,
            }

    def health_snapshot(self) -> dict:
        replicas = []
        for h in self.workers:
            hz = dict(h.last_health) if h.state == UP else {}
            if h.state == UP and h.client is not None:
                try:
                    hz = h.client.rpc("healthz")
                    hz.pop("id", None), hz.pop("ok", None)
                    h.last_health = hz
                except (WorkerGone, TimeoutError, RuntimeError):
                    pass
            d = {
                "state": ("healthy" if h.state == UP else h.state),
                "worker_state": h.state,
                "role": self.roles[h.replica],
                "pid": h.pid,
                "uptime_s": (round(time.time() - h.started_unix, 3)
                             if h.started_unix and h.state == UP
                             else 0.0),
                "restarts": h.restarts,
                "incarnation": h.incarnation,
                "routing": dict(self._route_stats[h.replica]),
            }
            for k in ("pool_pressure", "under_pressure", "preemptions",
                      "load", "draining", "host_cache",
                      "swap_in_resumes", "prefill_backlog",
                      "ladder_occupancy", "pd_handoffs", "pd_adoptions",
                      "pd_adopt_fallbacks", "slo",
                      "kv_integrity_rejections",
                      "fabric_published_pages"):
                if k in hz:
                    d[k] = hz[k]
            replicas.append(d)
        # RETIRED replicas left the fleet ON PURPOSE (scale-down or a
        # rollout retirement) — they must not drag status to degraded
        # forever. QUARANTINED stays in the denominator: a crash-looped
        # replica is a visible degradation, not an intentional absence.
        live = [h for h in self.workers if h.state != RETIRED]
        routable = sum(1 for h in live if h.routable)
        if routable == 0:
            status = "unavailable"
        elif routable == len(live):
            status = "ok"
        else:
            status = "degraded"
        return {
            "status": status,
            "fleet": "subprocess",
            "routing": self.server_cfg.routing,
            "replicas": replicas,
            # Fleet-aggregated rolling SLO view: EXACT quantiles pooled
            # across worker windows (the autoscaler's input signal).
            "slo": self._fleet_slo(),
            # Fleet KV fabric pool occupancy + churn (README "KV
            # fabric"); same shape under both fleet backends.
            "fabric": self.fabric.snapshot(),
            "supervision": self.supervision_counters(),
        }

    def stats_snapshot(self) -> dict:
        per = []
        for h in self.workers:
            d = None
            if h.state == UP and h.client is not None:
                try:
                    d = h.client.rpc("stats", timeout=30.0)["stats"]
                    h.last_stats = d
                except (WorkerGone, TimeoutError, RuntimeError):
                    d = None
            if d is None:
                d = dict(h.last_stats) if h.last_stats else None
            if d is not None:
                d["health"] = {"state": h.state, "pid": h.pid,
                               "restarts": h.restarts}
                per.append(d)
        if not per:
            return {"supervision": self.supervision_counters(),
                    "dp": self.dp}
        return aggregate_replica_stats(per,
                                       self.supervision_counters())

    def steps_snapshot(self) -> dict:
        """Step-ledger roofline attribution (GET /debug/steps): live
        per-replica reports (cache fallback for downed workers, same
        stance as stats_snapshot) + the fleet-merged report."""
        reports: Dict[str, dict] = {}
        for h in self.workers:
            d = None
            if h.state == UP and h.client is not None:
                try:
                    d = h.client.rpc("steps", timeout=30.0)["steps"]
                    h.last_steps = d
                except (WorkerGone, TimeoutError, RuntimeError):
                    d = None
            if d is None and h.last_steps:
                d = dict(h.last_steps)
                d["stale"] = True
            if d is not None:
                reports[str(h.replica)] = d
        return {"replicas": reports,
                "fleet": telemetry.merge_steps_reports(
                    list(reports.values()))}

    def blackbox_index(self) -> dict:
        """Flight-recorder capture index (GET /debug/blackbox): scans
        the operator's blackbox_dir on the router's FS — captures from
        dead incarnations are listed exactly like live ones (the dir
        survives kill -9; that is the point)."""
        return telemetry.blackbox_index(self.server_cfg.blackbox_dir)

    def prometheus_text(self) -> str:
        groups = []
        for h in self.workers:
            dump = None
            if h.state == UP and h.client is not None:
                try:
                    dump = h.client.rpc("metrics",
                                        timeout=30.0)["samples"]
                    h.last_metrics = dump
                except (WorkerGone, TimeoutError, RuntimeError):
                    dump = None
            if dump is None:
                # Dead/booting worker: keep its series rendering so
                # nothing vanishes mid-restart — from the last live
                # dump if the death hasn't been folded into the carry
                # yet, else from the carry ALONE (rendering both would
                # double-count the folded totals during the gap).
                dump = (h.last_metrics
                        if h.folded_incarnation != h.incarnation else [])
            merged = telemetry.apply_carry(h.carry, dump)
            groups.append(({"replica": str(h.replica)},
                           telemetry.registry_from_dump(merged)))
        groups.append(({}, self._fleet_registry))
        return telemetry.render_prometheus(groups)

    def recent_snapshot(self, n: int) -> List[dict]:
        items: List[dict] = []
        for h in self.workers:
            if h.state != UP or h.client is None:
                continue
            try:
                items.extend(h.client.rpc("recent", timeout=10.0,
                                          n=n)["recent"])
            except (WorkerGone, TimeoutError, RuntimeError):
                pass
        items.sort(key=lambda t: t.get("finished_unix", 0.0))
        return items[-n:]

    # -------------------------------------------- tracing + profiling

    def _pid_names(self) -> dict:
        return {0: "router",
                **{h.replica + 1:
                   f"replica {h.replica} ({self.roles[h.replica]})"
                   for h in self.workers}}

    def trace_snapshot(self, trace_id: str) -> Optional[dict]:
        """One request's assembled cross-process span tree (GET
        /debug/trace?id=). The router's recorder holds the event-frame
        assembly; a miss falls back to the workers' trace pull verb
        (e.g. the router restarted mid-request)."""
        spans = self._recorder.get_trace(trace_id)
        if spans is None:
            pulled: List[dict] = []
            for h in self.workers:
                if h.state != UP or h.client is None:
                    continue
                try:
                    pulled.extend(h.client.rpc(
                        "trace", timeout=10.0, trace=trace_id)["spans"])
                except (WorkerGone, TimeoutError, RuntimeError):
                    pass
            spans = pulled or None
        if not spans:
            return None
        return telemetry.assemble_trace(trace_id, spans)

    def trace_chrome(self, n: int = 128) -> dict:
        """The recent-request ring as Chrome trace-event JSON (GET
        /debug/trace?format=chrome): one pid per replica, router as
        pid 0, loadable in Perfetto."""
        maintenance: List[dict] = []
        for h in self.workers:
            if h.state != UP or h.client is None:
                continue
            try:
                maintenance.extend(h.client.rpc(
                    "trace", timeout=10.0, n=0)["maintenance"])
            except (WorkerGone, TimeoutError, RuntimeError):
                pass
        return telemetry.spans_to_chrome(
            self._recorder.recent_traces(n), self._pid_names(),
            maintenance=maintenance,
            other_data={"fleet": "subprocess",
                        "roles": list(self.roles),
                        "spans_dropped": self._recorder.spans_dropped})

    def capture_profile(self, replica: int, seconds: float) -> dict:
        """POST /debug/profile {"seconds": N, "replica": i}: forward a
        jax.profiler capture to one live worker over the profile RPC;
        the worker writes the trace dir (under the operator-configured
        profile_dir) and returns its path."""
        h = self.workers[int(replica)]
        if h.state != UP or h.client is None:
            raise ValueError(f"worker {replica} not serving "
                             f"(state={h.state})")
        r = h.client.rpc("profile", timeout=float(seconds) + 120.0,
                         seconds=float(seconds))
        return {k: v for k, v in r.items() if k not in ("id", "ok")}
