"""Fleet KV fabric: a router-side, digest-keyed pool of serialized KV
prefix pages shared by EVERY replica (README "KV fabric").

At million-user scale most traffic shares system prompts and few-shot
prefixes, but a prefix prefilled on replica A is invisible to replica
B — each replica pays its own prefill for the same bytes, and autoscaled
workers boot stone-cold. Mooncake (Qin et al., 2024) showed a
disaggregated fleet-shared KVCache pool is the single biggest lever for
exactly this workload. The substrate already exists in this tree:
``serialize_host_pages`` is a bit-exact, crc32c-carrying wire format
for every kv_quant mode, the ``import-kv`` RPC moves pages between any
two workers, and the prefix chain digests are self-contained keys. This
module generalizes them into a fabric:

- **FabricPool** — a capacity-bounded (in pages) LRU of per-page
  serialized blobs living in the ROUTER process, identical under
  ``--fleet in-process|subprocess``. Workers publish settled prefix
  pages after prefill; a prefill routed anywhere pulls matching fabric
  entries into that replica's host tier (the existing
  ``request_import_host`` path) before prefilling — so a prefix
  prefilled on ANY replica warms ALL replicas, byte-identically.
- **Integrity** — every ``get`` re-verifies the per-blob crc32c before
  adoption: a corrupt pool entry is dropped, counted, and treated as a
  miss, never adopted silently (the Byzantine-transport stance).
- **Routing score helpers** — THE prefill/decode scoring formulas both
  fleet backends share (previously copy-pasted five times), grown a
  fourth cache temperature: fabric-warm scores between host-warm and
  cold, from the router's own local index — no extra RPC.

Thread stance: one lock around the OrderedDict (puts arrive from event
threads, gets from submit threads, scoring peeks from pickers); counter
reads are GIL-atomic like the rest of the telemetry layer.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from tpu_inference.engine import kv_cache as kvc


class _Entry:
    """One pooled page: either the serialized blob itself (relay
    plane) or a shared-memory arena descriptor (shm plane — the bytes
    never entered this process; ``nbytes`` is the slab length)."""

    __slots__ = ("blob", "desc", "nbytes")

    def __init__(self, blob: Optional[bytes] = None,
                 desc: Optional[dict] = None):
        self.blob = blob
        self.desc = desc
        self.nbytes = len(blob) if blob is not None \
            else int(desc["len"])


class FabricPool:
    """Digest-keyed LRU pool of individually-serialized KV pages.

    One entry = one page = one ``serialize_host_pages([page])`` blob, so
    entries evict independently and every ``get`` can re-verify its own
    crc32c. Digests are the prefix chain hashes (``_chain_hashes``):
    self-contained keys, so any contiguous-from-page-0 subset resident
    anywhere still matches.
    """

    def __init__(self, capacity_pages: int):
        self.capacity = max(0, int(capacity_pages))
        self._entries: "collections.OrderedDict[bytes, _Entry]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._bytes = 0
        # Monotone counters (telemetry.register_fabric read-through).
        self.hits = 0                  # pages served by get_pages
        self.misses = 0                # lookups that ended short
        self.puts = 0                  # pages accepted (incl. supersede)
        self.superseded = 0            # puts that replaced a live entry
        self.evictions = 0             # LRU capacity drops
        self.kv_rejections = 0         # corrupt entries dropped on get
        # Zero-copy plane hook (server/shm_arena): called with the
        # arena descriptor of every desc-entry this pool stops
        # referencing (evict, supersede, reject, clear, region drop) —
        # the fleet releases the slab back to its owning worker.
        self.on_release = None

    # ------------------------------------------------------------- put

    def _release_entry(self, e: "_Entry") -> None:
        """Hand a desc-entry's slab back to the release hook (the
        supervisor's slab ledger) — without it, a dropped descriptor
        pins arena memory until the region's next epoch reclaim."""
        if e.desc is not None and self.on_release is not None:
            try:
                self.on_release(e.desc)
            except Exception:  # noqa: BLE001 — release is best-effort
                pass

    def _drop_entry(self, e: "_Entry") -> None:
        """Lock held by caller; entry WAS resident: settle the byte
        books and release its slab."""
        self._bytes -= e.nbytes
        self._release_entry(e)

    def _put_entry(self, digest: bytes, e: "_Entry") -> None:
        if self.capacity <= 0:
            # Never resident — no byte books to settle, but a
            # descriptor's slab still needs its release.
            self._release_entry(e)
            return
        with self._lock:
            old = self._entries.pop(digest, None)
            if old is not None:
                self._drop_entry(old)
                self.superseded += 1
            while len(self._entries) >= self.capacity:
                _, ev = self._entries.popitem(last=False)
                self._drop_entry(ev)
                self.evictions += 1
            self._entries[digest] = e
            self._bytes += e.nbytes
            self.puts += 1

    def put_blob(self, digest: bytes, blob: bytes) -> None:
        """Insert/supersede ONE page's serialized blob under its chain
        digest. Re-publishing the same prefix from a second replica
        stores once (byte-identical pages; the fresh blob supersedes),
        and the entry moves to MRU either way."""
        self._put_entry(digest, _Entry(blob=blob))

    def put_desc(self, digest: bytes, desc: dict) -> None:
        """Zero-copy publish: pool the arena DESCRIPTOR of one page's
        blob — the payload stays in the worker-written slab, never
        traverses the router. Integrity moves to adoption time: the
        reading worker verifies crc32c and reports rejects back."""
        self._put_entry(digest, _Entry(desc=dict(desc)))

    def put_pages(self, pairs: Sequence[Tuple[bytes, "kvc.HostKVPage"]]
                  ) -> int:
        """Publish (digest, HostKVPage) pairs — the in-process backend's
        direct path (the subprocess router ingests pre-serialized blobs
        from worker event frames instead). Returns pages stored."""
        n = 0
        for digest, page in pairs:
            self.put_blob(digest, kvc.serialize_host_pages([page]))
            n += 1
        return n

    # ------------------------------------------------------------- get

    def match_depth(self, digests: Sequence[bytes]) -> int:
        """Contiguous-from-page-0 pages resident for this digest chain.
        Side-effect-free (no counters, no LRU touch): the router's
        scoring peek, called once per candidate scan."""
        if self.capacity <= 0 or not digests:
            return 0
        with self._lock:
            n = 0
            for d in digests:
                if d not in self._entries:
                    break
                n += 1
            return n

    def get_pages(self, digests: Sequence[bytes]
                  ) -> List[Tuple[bytes, "kvc.HostKVPage"]]:
        """Pull the contiguous run of pages for ``digests``, verifying
        each blob's crc32c before adoption. A corrupt entry is dropped
        from the pool, counted under kv_rejections, and ends the run (a
        miss — never adopted silently). Served entries move to MRU."""
        out: List[Tuple[bytes, "kvc.HostKVPage"]] = []
        for d in digests:
            with self._lock:
                e = self._entries.get(d)
                if e is not None:
                    self._entries.move_to_end(d)
            if e is None or e.blob is None:
                # Absent — or a desc-entry: the blob lives in the
                # arena, not this process; the shm plane pulls it via
                # get_descs and the adopting worker's direct read.
                self.misses += 1
                break
            try:
                page = kvc.deserialize_host_pages(e.blob)[0]
            except kvc.integrity.KVIntegrityError:
                with self._lock:
                    live = self._entries.pop(d, None)
                    if live is not None:
                        self._drop_entry(live)
                self.kv_rejections += 1
                self.misses += 1
                break
            self.hits += 1
            out.append((d, page))
        return out

    def get_descs(self, digests: Sequence[bytes]
                  ) -> List[Tuple[bytes, dict]]:
        """Zero-copy pull: the contiguous run of DESC-entries for
        ``digests`` — counted like get_pages, but no bytes move here;
        the adopting worker reads + crc-verifies each slab itself and
        reports rejects back (``reject``). A blob entry ends the run
        (the relay path serves it on the next pull)."""
        out: List[Tuple[bytes, dict]] = []
        for d in digests:
            with self._lock:
                e = self._entries.get(d)
                if e is not None and e.desc is not None:
                    self._entries.move_to_end(d)
            if e is None or e.desc is None:
                self.misses += 1
                break
            self.hits += 1
            out.append((d, dict(e.desc)))
        return out

    def reject(self, digest: bytes) -> None:
        """Drop a corrupt entry discovered OUTSIDE get_pages (the
        warmboot re-verify, or a worker-side arena read that failed
        crc) — counted exactly like a get-time integrity rejection,
        never adopted silently."""
        with self._lock:
            live = self._entries.pop(digest, None)
            if live is not None:
                self._drop_entry(live)
        self.kv_rejections += 1

    def drop_region(self, rg: int) -> int:
        """Reclaim support: drop every desc-entry whose slab lives in
        arena region ``rg`` (its owning worker incarnation died; the
        epoch bump already made the descriptors fail closed). Returns
        entries dropped. Not an eviction and not a rejection — the
        pages were fine, their backing store went away."""
        with self._lock:
            dead = [d for d, e in self._entries.items()
                    if e.desc is not None
                    and int(e.desc.get("rg", -1)) == int(rg)]
            for d in dead:
                self._drop_entry(self._entries.pop(d))
            return len(dead)

    def hot_set(self, max_pages: int) -> List[Tuple[bytes, bytes]]:
        """The MRU-first (digest, blob) list for warm worker boot —
        puts land in chain order, so MRU slices keep prefix chains
        roughly intact. No counter side effects (the import's adoption
        is what the warmboot grade counts)."""
        if max_pages <= 0:
            return []
        with self._lock:
            ds = [d for d in self._entries
                  if self._entries[d].blob is not None][-max_pages:]
            ds.reverse()
            return [(d, self._entries[d].blob) for d in ds]

    def hot_set_descs(self, max_pages: int) -> List[Tuple[bytes, dict]]:
        """MRU-first (digest, descriptor) list — the shm plane's warm
        worker boot: the fresh worker adopts straight from the arena,
        verifying each slab itself."""
        if max_pages <= 0:
            return []
        with self._lock:
            ds = [d for d in self._entries
                  if self._entries[d].desc is not None][-max_pages:]
            ds.reverse()
            return [(d, dict(self._entries[d].desc)) for d in ds]

    # ------------------------------------------------------ accounting

    @property
    def used(self) -> int:
        return len(self._entries)

    @property
    def bytes_used(self) -> int:
        return self._bytes

    @property
    def free_pages(self) -> int:
        """Pool watermark the router advertises to workers (satellite:
        publish back-pressure) — publishes larger than this are
        instant-evict churn and get skipped at the source."""
        return max(0, self.capacity - self.used)

    def snapshot(self) -> Dict[str, int]:
        """Operator view for /healthz (both fleet backends emit the
        identical shape under ``"fabric"``)."""
        return {
            "capacity_pages": self.capacity,
            "pages_used": self.used,
            "bytes_used": self.bytes_used,
            "hits": self.hits,
            "misses": self.misses,
            "puts": self.puts,
            "superseded": self.superseded,
            "evictions": self.evictions,
            "kv_rejections": self.kv_rejections,
        }

    def clear(self) -> None:
        with self._lock:
            for e in self._entries.values():
                self._drop_entry(e)
            self._entries.clear()
            self._bytes = 0


# ---------------------------------------------------------------------------
# Shared routing-score formulas (README "Cache-aware routing").
#
# Before this module the prefill and decode scores were copy-pasted five
# times across server/replicas.py and server/fleet.py; the two fleet
# backends could silently drift. These are now THE formulas — both
# backends call them, and the fourth temperature (fabric-warm, weighted
# between host-warm and cold) exists in exactly one place.
# ---------------------------------------------------------------------------


def fabric_extra_pages(fabric_depth: int, warm_depth: int,
                       prompt_pages: int) -> int:
    """Pages the fabric covers BEYOND a candidate's own warm depth
    (HBM + host): only those earn the fabric discount — pages the
    replica already holds are scored at their warmer tier."""
    return max(0, min(int(fabric_depth), int(prompt_pages))
               - int(warm_depth))


def prefill_route_score(cfg, *, prompt_pages: int, hbm: float, host: float,
                        fabric: float, load: float,
                        pressured: bool) -> float:
    """Expected prefill cost in pages, load-blended: prompt pages minus
    warmth discounts (HBM at route_hit_weight, host at
    route_host_hit_weight, fabric-covered remainder at
    route_fabric_hit_weight — between host-warm and cold) plus queue
    depth; KV-pressured candidates are shifted behind every unpressured
    one without erasing relative order."""
    score = (prompt_pages
             - cfg.route_hit_weight * hbm
             - cfg.route_host_hit_weight * host
             - cfg.route_fabric_hit_weight * fabric
             + cfg.route_load_pages * load)
    if pressured:
        score += prompt_pages + 1
    return score


def decode_route_score(cfg, *, hbm: float, host: float, fabric: float,
                       load: float, occupancy: float,
                       pressured: bool) -> float:
    """Decode/P-D destination cost: load + lane occupancy minus the
    same three warmth discounts (a decode destination holding the
    sequence's pages adopts without a swap-in), pressure-shifted like
    the prefill score."""
    score = (cfg.route_load_pages * load
             + cfg.route_occupancy_pages * occupancy
             - cfg.route_hit_weight * hbm
             - cfg.route_host_hit_weight * host
             - cfg.route_fabric_hit_weight * fabric)
    if pressured:
        score += cfg.route_occupancy_pages + 1
    return score


def cold_route_key(pressured: bool, load: float) -> Tuple[bool, float]:
    """The cold-fallthrough sort key (no peek data): unpressured first,
    then least loaded — ties rotate via the caller's round-robin."""
    return (bool(pressured), load)
