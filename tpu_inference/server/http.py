"""Ollama-protocol HTTP server over the TPU engine.

Wire contract (load-bearing — SURVEY.md §2c; the reference's traffic
generator must run unchanged against this server):

- ``POST /api/generate`` with JSON ``{"model", "prompt", "temperature",
  "max_tokens", "stream"}`` (reference: traffic_generator/main.py:241-247).
  ``options.temperature`` / ``options.num_predict`` are honored too (the
  documented Ollama placement).
- stream=true: ``200`` with ``Content-Type: application/x-ndjson`` and
  chunked transfer; one JSON line per token
  ``{"model", "created_at", "response", "done": false}``; the terminal line
  adds ``done_reason``, ``context`` (token ids) and the ns-duration counters
  ``total_duration, load_duration, prompt_eval_count, prompt_eval_duration,
  eval_count, eval_duration``.
- stream=false: one JSON object, ``response`` = full text + same counters.
- **Headers are withheld until the first token is ready** so the client-side
  TTFT metric (first streamed chunk ≈ header arrival; reference
  logs/log.json) measures model latency, not connection latency.

Also serves ``GET /api/tags``, ``/api/version``, ``/healthz``, and
``/metrics`` (scheduler counters: batch occupancy, KV-page utilization —
SURVEY.md §5 observability).

Documented sampling divergences from Ollama: ``repeat_penalty`` defaults
to 1.0 (off), not Ollama's 1.1 — send ``options.repeat_penalty`` for
parity. Options accepted but not honored exactly (``repeat_last_n``
beyond the static penalty window; ``repeat_penalty`` under speculative
decoding, where rejection sampling needs the unmodified target
distribution) are reported in a ``warnings`` list on the terminal record.
"""

from __future__ import annotations

import asyncio
import datetime
import itertools
import json
import random
import time
import uuid
from typing import Any, Optional

from aiohttp import web

from tpu_inference import telemetry
from tpu_inference.config import PRIORITY_CLASSES, FrameworkConfig
from tpu_inference.engine.engine import InferenceEngine, Sequence
from tpu_inference.engine.sampling import PENALTY_WINDOW
from tpu_inference.server.replicas import (FleetSaturated, FleetUnavailable)
from tpu_inference.server.tokenizer import (IncrementalDecoder, StopMatcher,
                                            build_tokenizer)


def _now_iso() -> str:
    return (datetime.datetime.now(datetime.timezone.utc)
            .strftime("%Y-%m-%dT%H:%M:%S.%f000Z"))


def build_engine_group(cfg: FrameworkConfig, load_params=None,
                       draft_cfg=None, load_draft=None) -> "EngineGroup":
    """Construct the dp replica fleet for a FrameworkConfig.

    ``cfg.server.fleet`` picks the backend (README "Process fleet"):
    "in-process" builds dp engines in this process behind an EngineGroup
    (dp=1: one engine over the whole (tp, sp) mesh; dp>1: each replica
    its own tp*sp-device submesh, KV pool and scheduler thread);
    "subprocess" returns a ProcessEngineGroup router that spawns one
    engine-worker OS process per replica at start(). ``load_params``/
    ``load_draft`` are callables (mesh | None) -> params so checkpoints
    stream into each replica's own device layout (in-process only —
    workers load their own checkpoints from cfg.checkpoint_path).
    """
    import jax

    from tpu_inference.config import ParallelConfig
    from tpu_inference.parallel.mesh import build_mesh
    from tpu_inference.server.replicas import EngineGroup

    if cfg.server.fleet == "subprocess":
        if draft_cfg is not None:
            raise ValueError(
                "--fleet subprocess does not support draft-model "
                "speculative decoding yet (the worker boots its own "
                "params; use spec_mode='ngram' or the in-process fleet)")
        from tpu_inference.server.fleet import ProcessEngineGroup
        return ProcessEngineGroup(cfg)
    if cfg.server.fleet != "in-process":
        raise ValueError(f"unknown fleet backend {cfg.server.fleet!r}; "
                         "one of ('in-process', 'subprocess')")
    if (any(r != "mixed" for r in cfg.server.worker_roles)
            or cfg.engine.role != "mixed"):
        raise ValueError(
            "P/D worker roles (--role/--roles/--pd-ratio) need "
            "--fleet subprocess: the live KV handoff moves pages "
            "between worker PROCESSES (README 'P/D disaggregation'); "
            "the in-process fleet serves every replica mixed")
    pcfg = cfg.parallel
    if pcfg.dp <= 1:
        meshes = [build_mesh(pcfg) if pcfg.n_devices > 1 else None]
    else:
        per = pcfg.tp * pcfg.sp
        devices = jax.devices()
        if len(devices) < per * pcfg.dp:
            raise ValueError(f"dp={pcfg.dp} replicas of {per} devices need "
                             f"{per * pcfg.dp}; only {len(devices)} visible")
        sub = ParallelConfig(tp=pcfg.tp, sp=pcfg.sp)
        meshes = [build_mesh(sub, devices=devices[i * per:(i + 1) * per])
                  for i in range(pcfg.dp)]
    engines = []
    for mesh in meshes:
        params = load_params(mesh) if load_params else None
        draft_params = (load_draft(mesh)
                        if (load_draft and draft_cfg is not None) else None)
        engines.append(InferenceEngine(
            cfg.model, cfg.engine, params=params, seed=cfg.seed, mesh=mesh,
            draft_cfg=draft_cfg, draft_params=draft_params))
    return EngineGroup(engines, cfg.server)


class InferenceServer:
    """Engine replicas + schedulers + tokenizer behind the Ollama HTTP
    protocol."""

    def __init__(self, cfg: FrameworkConfig,
                 engine: Optional[InferenceEngine] = None,
                 group: Optional[Any] = None,
                 load_duration_ns: Optional[int] = None):
        """``load_duration_ns``: time spent building engines/loading
        weights when the caller built the group itself (build_server) —
        it feeds the Ollama ``load_duration`` wire field."""
        from tpu_inference.server.replicas import EngineGroup

        self.cfg = cfg
        # Tokenizer first: its consistency check needs no engine, so a
        # broken deployment fails in milliseconds, not after minutes of
        # weight load + XLA compile.
        self.tokenizer = build_tokenizer(cfg.server.tokenizer,
                                         vocab_size=cfg.model.vocab_size)
        if self.tokenizer.vocab_size > cfg.model.vocab_size:
            # A tokenizer that can emit ids the model cannot embed is a
            # broken deployment: the XLA gather would clamp those ids
            # silently on the prompt path, and request validation
            # (context ids < model vocab) would reject the server's own
            # context arrays. Fail loudly at boot, not one wrong
            # embedding at a time.
            raise ValueError(
                f"tokenizer vocab ({self.tokenizer.vocab_size}) exceeds "
                f"model vocab ({cfg.model.vocab_size}): prompts could "
                "encode to ids the model cannot embed; use the "
                "checkpoint's own tokenizer or a model with a matching "
                "embedding table")
        t0 = time.perf_counter()
        if group is None:
            group = (EngineGroup([engine], cfg.server) if engine is not None
                     else build_engine_group(cfg))
        self.group = group
        self.load_duration_ns = (load_duration_ns if load_duration_ns
                                 is not None else
                                 int((time.perf_counter() - t0) * 1e9))
        self._ids = itertools.count()

    @property
    def engine(self):
        """Primary replica's engine facts (tests/bench and the model-
        card routes). In-process: the engine object itself; subprocess
        fleet: a read-only info proxy fetched from worker 0 (None until
        the fleet has spawned — routes only run after startup)."""
        return self.group.engine

    # ------------------------------------------------------------- app

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/api/generate", self.handle_generate)
        app.router.add_post("/api/chat", self.handle_chat)
        app.router.add_get("/api/tags", self.handle_tags)
        app.router.add_post("/api/show", self.handle_show)
        app.router.add_post("/api/embeddings", self.handle_embeddings)
        app.router.add_post("/api/embed", self.handle_embeddings)
        app.router.add_get("/api/ps", self.handle_ps)
        app.router.add_get("/api/version", self.handle_version)
        app.router.add_get("/healthz", self.handle_health)
        app.router.add_get("/metrics", self.handle_metrics)
        if self.cfg.server.enable_debug:
            app.router.add_get("/debug/requests", self.handle_debug_requests)
            app.router.add_get("/debug/trace", self.handle_debug_trace)
            app.router.add_get("/debug/steps", self.handle_debug_steps)
            app.router.add_get("/debug/blackbox",
                               self.handle_debug_blackbox)
            app.router.add_post("/debug/profile", self.handle_profile)
            app.router.add_post("/debug/chaos", self.handle_chaos)
            app.router.add_post("/debug/rollout", self.handle_rollout)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app) -> None:
        if self.cfg.server.warmup:
            secs = self.group.warmup()
            print(f"engine warmup: compiled all graphs in {secs:.1f}s")
        # start() before the boot prints: the subprocess fleet spawns
        # its workers here, and the prints below read worker-0 facts.
        self.group.start()
        scfg = self.cfg.server
        wd = (f"{scfg.step_watchdog_s:g}s" if scfg.step_watchdog_s > 0
              else "off")
        cap = scfg.admission_queue_depth or "off"
        host_pages = self.cfg.engine.host_cache_pages
        ladder = self.engine.ladder if self.engine is not None else (1,)
        if len(ladder) > 1:
            print(f"batch ladder: rungs={list(ladder)} "
                  f"(decode graph per rung; dispatch follows occupancy)")
        print(f"supervision: fleet={scfg.fleet} "
              f"dp={len(self.group.engines)} "
              f"routing={scfg.routing} "
              f"hit_weight={scfg.route_hit_weight:g} "
              f"host_hit_weight={scfg.route_host_hit_weight:g} "
              f"host_cache_pages={host_pages} "
              f"step_watchdog={wd} "
              f"quarantine_after={scfg.quarantine_after_failures} "
              f"cooldown={scfg.quarantine_cooldown_s:g}s "
              f"failover_retries={scfg.failover_max_retries} "
              f"queue_cap={cap}")

    async def _on_cleanup(self, app) -> None:
        self.group.stop(drain=False)

    # ------------------------------------------------------------- routes

    @staticmethod
    def _retry_after_headers(retry_after_s: float) -> dict:
        # Retry-After takes integer seconds; round up so "0.5" doesn't
        # become "retry immediately".
        return {"Retry-After": str(max(1, int(-(-retry_after_s // 1))))}

    async def handle_health(self, request: web.Request) -> web.Response:
        """Fleet health: per-replica state machine + shed/retry counters.
        200 while at least one replica is routable ("ok"/"degraded"),
        503 with Retry-After when the whole fleet is quarantined — load
        balancers and the traffic generator back off on exactly this.
        Off the event loop: under --fleet subprocess this does worker
        RPCs (in-process it is in-memory reads; to_thread is cheap)."""
        snap = await asyncio.to_thread(self.group.health_snapshot)
        if snap["status"] == "unavailable":
            return web.json_response(
                snap, status=503, headers=self._retry_after_headers(
                    self.cfg.server.retry_after_s))
        return web.json_response(snap)

    async def handle_version(self, request: web.Request) -> web.Response:
        from tpu_inference import __version__

        return web.json_response({"version": __version__})

    def _parameter_size(self) -> str:
        """Ollama-shaped parameter_size ("8.0B", "124.4M") computed from
        the actual parameter count, not the config name (ADVICE r5)."""
        n = self.engine.n_params
        for div, suffix in ((1e9, "B"), (1e6, "M"), (1e3, "K")):
            if n >= div:
                return f"{n / div:.1f}{suffix}"
        return str(n)

    def _quantization_level(self) -> str:
        """Ollama quantization_level vocabulary ("Q8_0"/"Q4_0"-style;
        unquantized models report the serving dtype, F16/BF16/F32)."""
        q = {"int8": "Q8_0", "int4": "Q4_0"}.get(self.cfg.engine.quant)
        if q is not None:
            return q
        import jax.numpy as jnp
        dtype = self.cfg.model.dtype
        return {jnp.bfloat16: "BF16", jnp.float16: "F16"}.get(dtype, "F32")

    async def handle_tags(self, request: web.Request) -> web.Response:
        return web.json_response({"models": [{
            "name": self.cfg.server.model_name,
            "model": self.cfg.server.model_name,
            "details": {"family": self.cfg.model.family,
                        "parameter_size": self._parameter_size(),
                        "quantization_level": self._quantization_level()},
        }]})

    async def handle_ps(self, request: web.Request) -> web.Response:
        """Ollama GET /api/ps: the loaded ("running") models. One entry —
        this server loads its model at boot and never unloads it, so
        ``expires_at`` is the zero time (Ollama's "never"). ``size`` is
        ONE model copy (Ollama semantics — ADVICE r5); the dp replica
        count is exposed separately so fleet HBM is size * replicas."""
        mc = self.cfg.model
        size = int(self.engine.weight_bytes)
        return web.json_response({"models": [{
            "name": self.cfg.server.model_name,
            "model": self.cfg.server.model_name,
            "size": size,
            "size_vram": size,     # weights live in HBM, nothing on host
            "replicas": len(self.group.engines),   # additive field: dp
            "details": {"family": mc.family,
                        "parameter_size": self._parameter_size(),
                        "quantization_level": self._quantization_level()},
            "expires_at": "0001-01-01T00:00:00Z",
        }]})

    async def handle_show(self, request: web.Request) -> web.Response:
        """Ollama /api/show: model card for clients that introspect before
        generating. Serves the architecture + serving knobs of the one
        loaded model regardless of the requested name (single-model
        server, like `ollama show` on a single-model host)."""
        mc, ec = self.cfg.model, self.cfg.engine
        return web.json_response({
            "modelfile": "",
            "details": {"family": mc.family, "format": "safetensors",
                        "parameter_size": self._parameter_size(),
                        "quantization_level": self._quantization_level()},
            "model_info": {
                "general.architecture": mc.family,
                "general.parameter_count": self.engine.n_params,
                f"{mc.family}.context_length": ec.max_context,
                f"{mc.family}.embedding_length": mc.d_model,
                f"{mc.family}.block_count": mc.n_layers,
                f"{mc.family}.attention.head_count": mc.n_heads,
                f"{mc.family}.attention.head_count_kv": mc.n_kv_heads,
                f"{mc.family}.vocab_size": mc.vocab_size,
                # Resolved backend (not the "auto" sentinel) — matches
                # what /metrics reports.
                "serving.attn_backend": self.engine.attn_backend,
                "serving.kv_quant": ec.kv_quant,
                # SWA composition rules actually in effect (README
                # "Sliding-window models"): operators can confirm them
                # here instead of grepping the boot log.
                f"{mc.family}.attention.sliding_window":
                    mc.sliding_window or 0,
                "serving.swa_eviction": self.engine.swa_evict,
                "serving.prefix_cache": self.engine.prefix_cache is not None,
            },
        })

    async def handle_embeddings(self, request: web.Request) -> web.Response:
        """Ollama /api/embeddings ({"prompt": str} -> {"embedding": [..]})
        and /api/embed ({"input": str | [str]} -> {"embeddings": [[..]]}).
        Mean-pooled final hidden states from the loaded model. Runs in a
        worker thread so compile/forward never stalls the event loop."""
        # Same fault-injection gate as generate/chat: embedding clients
        # get exercised against failures too (previously only
        # /api/generate was chaos-gated).
        await self._chaos_gate()
        try:
            body = await request.json()
            assert isinstance(body, dict)
        except (json.JSONDecodeError, UnicodeDecodeError, AssertionError):
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "invalid JSON body"}), content_type="application/json")
        # Shape is keyed on the ROUTE (not on which keys the client sent):
        # /api/embeddings takes a single "prompt" string and returns
        # {"embedding"}; /api/embed takes "input" (str or list) and
        # returns {"model", "embeddings"}.
        legacy = request.path.endswith("/embeddings")
        if legacy:
            texts = body.get("prompt")
            if not isinstance(texts, str):
                raise web.HTTPBadRequest(text=json.dumps(
                    {"error": "missing 'prompt' string"}),
                    content_type="application/json")
            texts = [texts]
        else:
            texts = body.get("input")
            if isinstance(texts, str):
                texts = [texts]
            if (not isinstance(texts, list) or not texts
                    or not all(isinstance(t, str) for t in texts)):
                raise web.HTTPBadRequest(text=json.dumps(
                    {"error": "missing 'input' string or list of strings"}),
                    content_type="application/json")

        def compute():
            ids = [self.tokenizer.encode(t) for t in texts]
            return self.group.embed_many(ids).tolist()

        try:
            vecs = await asyncio.to_thread(compute)
        except FleetUnavailable as e:
            raise web.HTTPServiceUnavailable(
                text=json.dumps({"error": str(e)}),
                content_type="application/json",
                headers=self._retry_after_headers(e.retry_after_s))
        if legacy:
            return web.json_response({"embedding": vecs[0]})
        return web.json_response({"model": self.cfg.server.model_name,
                                  "embeddings": vecs})

    async def handle_metrics(self, request: web.Request) -> web.Response:
        """Prometheus text exposition (the default — scrapeable by any
        standard collector, per-replica labels under dp>1); the legacy
        JSON snapshot is preserved under ``?format=json`` (which also
        carries the diffable "phases" histograms the bench scrapes)."""
        # to_thread: the subprocess fleet scrapes each worker over RPC —
        # a slow worker must stall this scrape, not the whole server.
        if request.query.get("format") == "json":
            return web.json_response(
                await asyncio.to_thread(self.group.stats_snapshot))
        return web.Response(
            text=await asyncio.to_thread(self.group.prometheus_text),
            headers={"Content-Type": telemetry.PROMETHEUS_CONTENT_TYPE})

    async def handle_debug_requests(self, request: web.Request
                                    ) -> web.Response:
        """Per-request event timelines for the last <=256 finished
        requests: queue wait, prefill, decode, TPOT (SURVEY.md §5)."""
        try:
            n = int(request.query.get("n", 50))
        except ValueError:
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "'n' must be an integer"}),
                content_type="application/json")
        if n <= 0:
            return web.json_response([])
        return web.json_response(
            await asyncio.to_thread(self.group.recent_snapshot, n))

    async def handle_debug_steps(self, request: web.Request
                                 ) -> web.Response:
        """Step-ledger roofline attribution (README "Performance
        attribution"): per-replica + fleet-merged bottleneck verdicts
        per step kind, cross-checked against tpu_inf_mfu_estimate."""
        return web.json_response(
            await asyncio.to_thread(self.group.steps_snapshot))

    async def handle_debug_blackbox(self, request: web.Request
                                    ) -> web.Response:
        """Crash flight-recorder capture index: every capture under the
        operator's --blackbox-dir, newest first — including those left
        behind by dead (kill -9'd) worker incarnations."""
        return web.json_response(
            await asyncio.to_thread(self.group.blackbox_index))

    async def handle_debug_trace(self, request: web.Request
                                 ) -> web.Response:
        """Distributed request traces (README "Observability").

        ``GET /debug/trace?id=<trace_id>`` returns one request's
        assembled cross-process span tree (router + every worker that
        served an attempt/handoff under one trace id);
        ``GET /debug/trace?format=chrome`` renders the recent-request
        ring as Chrome trace-event JSON — one pid per replica, router
        as pid 0 — loadable at ui.perfetto.dev or chrome://tracing."""
        if request.query.get("format") == "chrome":
            try:
                n = int(request.query.get("n", 128))
            except ValueError:
                raise web.HTTPBadRequest(text=json.dumps(
                    {"error": "'n' must be an integer"}),
                    content_type="application/json")
            return web.json_response(
                await asyncio.to_thread(self.group.trace_chrome, n))
        tid = (request.query.get("id") or "").strip()
        if not tid:
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "pass ?id=<trace_id> or ?format=chrome"}),
                content_type="application/json")
        snap = await asyncio.to_thread(self.group.trace_snapshot, tid)
        if snap is None:
            raise web.HTTPNotFound(text=json.dumps(
                {"error": f"no trace {tid!r} in the recent ring"}),
                content_type="application/json")
        return web.json_response(snap)

    async def handle_profile(self, request: web.Request) -> web.Response:
        """On-demand jax.profiler capture (TensorBoard / Perfetto).

        POST {"seconds": N, "replica": i} captures a device profile on
        the chosen replica for N seconds while it keeps serving (the
        subprocess fleet forwards over the profile RPC; the worker
        writes the trace dir and returns its path). The legacy
        {"action": "start"} / {"action": "stop"} pair still profiles
        this process. Traces always land under
        ServerConfig.profile_dir — the client cannot choose a
        filesystem path.
        """
        import jax

        try:
            body = await request.json()
            assert isinstance(body, dict)
        except (json.JSONDecodeError, UnicodeDecodeError, AssertionError):
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "body must be a JSON object"}),
                content_type="application/json")
        if body.get("seconds") is not None:
            try:
                seconds = float(body["seconds"])
                replica = int(body.get("replica", 0))
                if not (0 < seconds <= 60):
                    raise ValueError("'seconds' must be in (0, 60]")
                if not (0 <= replica < len(self.group.engines)):
                    raise ValueError(f"no replica {replica}")
            except (TypeError, ValueError) as e:
                raise web.HTTPBadRequest(text=json.dumps(
                    {"error": str(e)}), content_type="application/json")
            try:
                result = await asyncio.to_thread(
                    self.group.capture_profile, replica, seconds)
            except Exception as e:  # noqa: BLE001 — worker-side failure
                return web.json_response({"error": str(e)}, status=503)
            return web.json_response({"status": "captured", **result})
        action = body.get("action")
        if action == "start":
            trace_dir = self.cfg.server.profile_dir
            try:
                jax.profiler.start_trace(trace_dir)
            except RuntimeError as e:     # already started
                return web.json_response({"error": str(e)}, status=409)
            self._profile_dir = trace_dir
            return web.json_response({"status": "tracing",
                                      "dir": trace_dir})
        if action == "stop":
            try:
                jax.profiler.stop_trace()
            except RuntimeError as e:
                return web.json_response({"error": str(e)}, status=409)
            return web.json_response(
                {"status": "stopped",
                 "dir": getattr(self, "_profile_dir", None)})
        raise web.HTTPBadRequest(text=json.dumps(
            {"error": "action must be 'start' or 'stop'"}),
            content_type="application/json")

    async def _chaos_gate(self) -> None:
        """HTTP-level fault injection for harness-resilience testing (off
        unless ServerConfig.chaos_* set; SURVEY.md §5). Applied to
        generate, chat, AND embed — every client type gets exercised.
        The engine-level counterpart (EngineConfig.chaos_step_*) injects
        below the router instead, exercising supervision itself."""
        scfg = self.cfg.server
        if scfg.chaos_delay_s > 0:
            await asyncio.sleep(random.uniform(0, scfg.chaos_delay_s))
        if scfg.chaos_failure_rate > 0:
            if random.random() < scfg.chaos_failure_rate:
                raise web.HTTPServiceUnavailable(text=json.dumps(
                    {"error": "chaos: injected failure"}),
                    content_type="application/json")

    async def handle_chaos(self, request: web.Request) -> web.Response:
        """Arm/disarm fault injection at runtime: ``POST {"replica": i |
        null, "step_failure_rate": p, "step_wedge_s": s,
        "page_pressure": n}`` — null replica applies to all. The
        subprocess fleet additionally takes ``{"replica": i, "kill":
        "kill9" | "sigterm"}`` — the REAL out-of-process failure modes
        (SIGKILL a worker mid-decode; SIGTERM = graceful drain with KV
        migration) the in-process chaos_step_wedge_s only simulates.
        Returns the per-replica settings now in effect. Debug-only
        (with /debug/requests), so chaos cannot be armed on a
        production endpoint that didn't opt in."""
        try:
            body = await request.json()
            assert isinstance(body, dict)
        except (json.JSONDecodeError, UnicodeDecodeError, AssertionError):
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "body must be a JSON object"}),
                content_type="application/json")
        try:
            # Both fleet backends implement apply_chaos; process-level
            # kill verbs are a usage error on the in-process one.
            result = await asyncio.to_thread(self.group.apply_chaos, body)
        except (IndexError, TypeError, ValueError, KeyError) as e:
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": f"invalid chaos spec: {e}"}),
                content_type="application/json")
        return web.json_response(result)

    async def handle_rollout(self, request: web.Request) -> web.Response:
        """Zero-downtime rolling upgrade (README "Elastic fleet"):
        ``POST /debug/rollout`` replaces every worker one at a time
        under live traffic — spawn successor, drain-and-migrate the
        predecessor's in-flight sequences, retire it. Subprocess fleet
        only (the in-process group has no worker processes to roll).
        409 when a rollout is already running; debug-only so a
        production endpoint can't be rolled by an anonymous POST."""
        roll = getattr(self.group, "rollout", None)
        if roll is None:
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "rolling upgrades need --fleet subprocess"}),
                content_type="application/json")
        try:
            result = await asyncio.to_thread(roll)
        except ValueError as e:
            raise web.HTTPConflict(text=json.dumps(
                {"error": str(e)}), content_type="application/json")
        return web.json_response(result)

    async def handle_chat(self, request: web.Request) -> web.StreamResponse:
        """Ollama ``/api/chat``: messages-based wrapper over the same
        engine path (the reference's notebooks drive this via ChatOllama —
        reference notebooks/request_demo.ipynb cell 4d5cf82f). Messages
        render through the checkpoint's own chat template when the
        tokenizer has one, else flatten to a role-prefix transcript;
        responses use the ``message`` record shape instead of
        ``response``."""
        await self._chaos_gate()
        try:
            body = await request.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "invalid JSON body"}), content_type="application/json")
        msgs = body.get("messages")
        if msgs == []:
            # Ollama load/ping contract, chat flavor: an empty messages
            # array preloads the model and acks immediately (mirrors the
            # empty-prompt /api/generate probe).
            return web.json_response({
                "model": body.get("model") or self.cfg.server.model_name,
                "created_at": _now_iso(),
                "message": {"role": "assistant", "content": ""},
                "done": True,
                "done_reason": "load",
            })
        if (not isinstance(msgs, list) or not msgs
                or not all(isinstance(m, dict) and "content" in m
                           for m in msgs)):
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "missing 'messages'"}),
                content_type="application/json")
        # Prefer the checkpoint's own chat template (instruct models are
        # trained on their specific format); fall back to a role-prefix
        # transcript for template-less tokenizers (byte, bare BPE).
        prompt = None
        if hasattr(self.tokenizer, "apply_chat_template"):
            prompt = self.tokenizer.apply_chat_template(
                [{"role": m.get("role", "user"), "content": m["content"]}
                 for m in msgs])
        if prompt is None:
            prompt = "\n".join(f"{m.get('role', 'user')}: {m['content']}"
                               for m in msgs) + "\nassistant:"
        body = dict(body)
        body["prompt"] = prompt
        return await self._generate_impl(request, body, chat=True)

    async def handle_generate(self, request: web.Request) -> web.StreamResponse:
        # Gate here, not in _generate_impl: handle_chat gates itself, and
        # gating the shared impl too would double the chat failure rate.
        await self._chaos_gate()
        try:
            body = await request.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "invalid JSON body"}), content_type="application/json")
        return await self._generate_impl(request, body)

    async def _generate_impl(self, request: web.Request, body: dict,
                             chat: bool = False) -> web.StreamResponse:
        recv_t = time.perf_counter()
        prompt = body.get("prompt")
        if not isinstance(prompt, str):
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "missing 'prompt'"}), content_type="application/json")
        if not chat and prompt == "" and not body.get("context"):
            # Ollama load/ping contract: an empty generate request warms
            # the model and returns immediately (the ollama CLI and
            # client libraries use this as a liveness/load probe). The
            # model here is always resident, so it's a pure ack.
            return web.json_response({
                "model": body.get("model") or self.cfg.server.model_name,
                "created_at": _now_iso(),
                "response": "",
                "done": True,
                "done_reason": "load",
            })

        opts = body.get("options") or {}
        if not isinstance(opts, dict):
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "'options' must be an object"}),
                content_type="application/json")
        ecfg = self.cfg.engine
        try:
            temperature = float(opts.get(
                "temperature", body.get("temperature", ecfg.temperature)))
            max_tokens = int(opts.get(
                "num_predict", body.get("max_tokens", ecfg.max_new_tokens)))
            max_tokens = max(1, min(max_tokens, ecfg.max_context - 1))
            top_p = float(opts.get("top_p", body.get("top_p", ecfg.top_p)))
            top_k = opts.get("top_k", body.get("top_k"))
            top_k = int(top_k) if top_k is not None else None
            seed = opts.get("seed", body.get("seed"))
            seed = int(seed) if seed is not None else None
            # Documented divergence from Ollama: repeat_penalty defaults
            # to 1.0 (off) here, not Ollama's 1.1 — an inference engine
            # shouldn't silently reshape the model's distribution; send
            # options.repeat_penalty=1.1 for bug-for-bug parity. Requests
            # whose penalty options can't be honored exactly get a
            # "warnings" field in the terminal record (ADVICE r3).
            warnings: list = []
            repeat_penalty = float(opts.get("repeat_penalty", 1.0))
            if repeat_penalty <= 0:
                raise ValueError("'repeat_penalty' must be > 0")
            repeat_last_n = int(opts.get("repeat_last_n", 64))
            if repeat_penalty != 1.0:
                # With the penalty off, clamping/ignoring its window is
                # a no-op — warn only when sampling actually diverges.
                # -1 is Ollama's "whole context"; the engine clamps both
                # cases to its static window (engine._penalty_arrays).
                if repeat_last_n > PENALTY_WINDOW or repeat_last_n < 0:
                    warnings.append(
                        f"repeat_last_n={repeat_last_n} clamped to the "
                        f"static penalty window {PENALTY_WINDOW}")
                if getattr(self.engine, "spec_draft", False):
                    # Draft-model spec only: the q/p acceptance ratio
                    # needs both distributions unmodified. Draft-free
                    # ngram spec applies the penalty inside the verify
                    # round (one-hot proposals have no p to corrupt),
                    # so it composes with no warning.
                    warnings.append(
                        "repeat_penalty ignored: draft-model speculative "
                        "decoding samples from the unmodified target "
                        "distribution")
            stop = opts.get("stop", body.get("stop"))
            if stop is None:
                stop = []
            elif isinstance(stop, str):
                stop = [stop]
            elif not (isinstance(stop, list)
                      and all(isinstance(s, str) for s in stop)):
                raise ValueError("'stop' must be a string or list of strings")
            stop = [s for s in stop if s]
        except (TypeError, ValueError) as e:
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": f"invalid sampling options: {e}"}),
                content_type="application/json")
        stream = bool(body.get("stream", True))
        model_name = body.get("model") or self.cfg.server.model_name

        prompt_ids = self.tokenizer.encode(prompt)
        # Stateful continuation (Ollama /api/generate "context"): a prior
        # response's context token array prepends to this prompt — the
        # reference's captured wire format round-trips exactly these ids
        # (its terminal records carry them). With the prefix cache on,
        # the continued context's KV pages are reused, not recomputed.
        # Generate-only, like Ollama: /api/chat never emits a context, so
        # honoring one there would prepend stale ids into the transcript.
        ctx_ids = body.get("context") if not chat else None
        if ctx_ids is not None:
            # bool is an int subclass; true/false are not token ids.
            if not (isinstance(ctx_ids, list)
                    and all(isinstance(t, int) and not isinstance(t, bool)
                            and 0 <= t for t in ctx_ids)):
                raise web.HTTPBadRequest(text=json.dumps(
                    {"error": "'context' must be a list of token ids"}),
                    content_type="application/json")
            # Validate against the MODEL vocab: the XLA embedding gather
            # clamps out-of-range ids silently, so an id the model can't
            # embed must 400 here, not "work" with a wrong embedding
            # (ADVICE r3). The server's own context arrays only contain
            # ids the model produced or the tokenizer encoded, both
            # < model vocab in a consistent deployment.
            vocab = self.cfg.model.vocab_size
            if any(t >= vocab for t in ctx_ids):
                raise web.HTTPBadRequest(text=json.dumps(
                    {"error": f"'context' token id out of range "
                              f"(vocab_size={vocab})"}),
                    content_type="application/json")
        if ctx_ids:
            # The encoder's BOS belongs at the very start, not mid-stream.
            if (prompt_ids and self.tokenizer.bos_token_id is not None
                    and prompt_ids[0] == self.tokenizer.bos_token_id):
                prompt_ids = prompt_ids[1:]
            prompt_ids = list(ctx_ids) + prompt_ids
        rid = next(self._ids)
        # End-to-end request tracing: honor a client-supplied
        # X-Request-Id (sanitized: printable, capped) or mint one. It
        # rides the Sequence through the scheduler/engine into the
        # structured logs, the /debug/requests span, the response's
        # X-Request-Id header and the terminal record's request_id.
        trace_id = (request.headers.get("X-Request-Id") or "").strip()
        trace_id = ("".join(c for c in trace_id if c.isprintable())[:64]
                    or uuid.uuid4().hex[:16])
        # Priority class (README "Elastic fleet"): X-Priority header
        # (interactive | batch | background), else the server default.
        # An unknown name is a 400 — silently ranking a typo'd class as
        # interactive would defeat the batch lane it asked for.
        pcls = (request.headers.get("X-Priority") or "").strip().lower()
        if not pcls:
            pcls = self.cfg.server.default_class
        if pcls not in PRIORITY_CLASSES:
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": f"unknown X-Priority {pcls!r} (expected one "
                          f"of {', '.join(PRIORITY_CLASSES)})"}),
                content_type="application/json")
        seq = Sequence(request_id=rid, prompt_tokens=prompt_ids,
                       max_new_tokens=max_tokens, temperature=temperature,
                       top_p=top_p, top_k=top_k, seed=seed,
                       repeat_penalty=repeat_penalty,
                       repeat_last_n=repeat_last_n,
                       eos_token_id=self.tokenizer.eos_token_id,
                       trace_id=trace_id, priority_class=pcls)
        telemetry.log_event(
            "request_received", level="info", request_id=trace_id,
            route="chat" if chat else "generate",
            prompt_tokens=len(prompt_ids), max_tokens=max_tokens,
            priority_class=pcls, stream=stream)

        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue()

        def on_token(s: Sequence, tok: int) -> None:
            loop.call_soon_threadsafe(queue.put_nowait, ("token", tok))

        def on_finish(s: Sequence) -> None:
            loop.call_soon_threadsafe(queue.put_nowait, ("finish", s))

        try:
            # to_thread: under --fleet subprocess, submit does routing
            # peeks + the submit RPC over worker sockets — blocking I/O
            # that must not freeze the event loop (and so every other
            # stream) behind one slow worker. In-process submit is
            # thread-safe by design (callbacks already arrive from
            # engine threads).
            await asyncio.to_thread(self.group.submit, seq, on_token,
                                    on_finish)
        except FleetSaturated as e:
            # Admission control: reject NOW with a backoff hint instead
            # of queueing until request_timeout_s.
            raise web.HTTPTooManyRequests(
                text=json.dumps({"error": str(e)}),
                content_type="application/json",
                headers=self._retry_after_headers(e.retry_after_s))
        except FleetUnavailable as e:
            raise web.HTTPServiceUnavailable(
                text=json.dumps({"error": str(e)}),
                content_type="application/json",
                headers=self._retry_after_headers(e.retry_after_s))
        try:
            if stream:
                return await self._stream_response(request, queue, seq,
                                                   model_name, recv_t, chat,
                                                   stop, warnings)
            return await self._unary_response(request, queue, seq, model_name,
                                              recv_t, chat, stop, warnings)
        except asyncio.TimeoutError:
            # Request exceeded request_timeout_s: free the slot and pages.
            self.group.cancel(rid)
            raise web.HTTPGatewayTimeout(text=json.dumps(
                {"error": "request timed out"}), content_type="application/json")
        except (asyncio.CancelledError, ConnectionResetError):
            self.group.cancel(rid)
            raise

    # ------------------------------------------------------------- helpers

    @staticmethod
    def _token_line(model_name: str, chunk: str, chat: bool) -> dict:
        line = {"model": model_name, "created_at": _now_iso(), "done": False}
        if chat:
            line["message"] = {"role": "assistant", "content": chunk}
        else:
            line["response"] = chunk
        return line

    def _final_record(self, seq: Sequence, model_name: str,
                      recv_t: float, chat: bool = False,
                      warnings: Optional[list] = None) -> dict:
        now = time.perf_counter()
        prompt_eval_ns = max(0, int((seq.first_token_time - seq.prefill_start)
                                    * 1e9)) if seq.first_token_time else 0
        finish = seq.finish_time or now
        eval_ns = max(0, int((finish - (seq.first_token_time or finish)) * 1e9))
        rec = {
            "model": model_name,
            "created_at": _now_iso(),
            # Propagated trace id (additive field): lets a client join
            # its response to server-side spans/logs without headers.
            "request_id": seq.trace_id,
            "response": "",
            "done": True,
            "done_reason": seq.finish_reason or "stop",
            "context": list(seq.prompt_tokens) + list(seq.generated),
            "total_duration": int((now - recv_t) * 1e9),
            "load_duration": self.load_duration_ns,
            "prompt_eval_count": len(seq.prompt_tokens),
            "prompt_eval_duration": prompt_eval_ns,
            "eval_count": len(seq.generated),
            "eval_duration": eval_ns,
        }
        if warnings:
            # Options accepted but not honored exactly (clamped/ignored);
            # additive field, absent when everything applied as sent.
            rec["warnings"] = list(warnings)
        if chat:
            # Ollama chat records use `message` and omit `context`.
            del rec["response"], rec["context"]
            rec["message"] = {"role": "assistant", "content": ""}
        return rec

    async def _stream_response(self, request: web.Request, queue: asyncio.Queue,
                               seq: Sequence, model_name: str,
                               recv_t: float, chat: bool = False,
                               stop: Optional[list] = None,
                               warnings: Optional[list] = None
                               ) -> web.StreamResponse:
        resp = web.StreamResponse(status=200, headers={
            "Content-Type": "application/x-ndjson",
            "X-Request-Id": seq.trace_id})
        resp.enable_chunked_encoding()
        decoder = IncrementalDecoder(self.tokenizer,
                                     prompt_tail=seq.prompt_tokens[-8:])
        matcher = StopMatcher(stop or [])
        consumed: list = []            # token ids delivered to THIS handler
        prepared = False
        timeout = self.cfg.server.request_timeout_s

        async def write_line(text: str) -> None:
            await resp.write(json.dumps(self._token_line(
                model_name, text, chat)).encode() + b"\n")

        async def finish(stopped: bool, fseq: Sequence = seq
                         ) -> web.StreamResponse:
            # fseq is the sequence the finish event delivered — after a
            # failover it is the resubmitted attempt, which carries the
            # real tokens/timings (the closure seq is the dead first
            # attempt).
            final = self._final_record(fseq, model_name, recv_t, chat,
                                       warnings)
            if stopped:
                # The engine thread may still be appending to
                # seq.generated until the cancel lands; report only what
                # this handler consumed so context/eval_count are
                # deterministic and never include post-stop tokens.
                final["done_reason"] = "stop"
                final["eval_count"] = len(consumed)
                if "context" in final:
                    final["context"] = list(seq.prompt_tokens) + consumed
            await resp.write(json.dumps(final).encode() + b"\n")
            await resp.write_eof()
            return resp

        while True:
            kind, payload = await asyncio.wait_for(queue.get(), timeout)
            if kind == "token":
                consumed.append(payload)
                emit, stopped = matcher.push(decoder.push(payload))
                if not prepared:
                    # First token ready -> now send headers (TTFT contract).
                    await resp.prepare(request)
                    prepared = True
                if stopped:
                    # A stop sequence completed: cut the stream here and
                    # cancel the rest of the generation (never emit the
                    # stop string itself).
                    if emit:
                        await write_line(emit)
                    self.group.cancel(seq.request_id)
                    return await finish(stopped=True)
                await write_line(emit)
            else:
                if payload.finish_reason == "poison" and not prepared:
                    # Terminal quarantine: this request crashed/wedged
                    # poison_max_workers distinct workers. A structured
                    # 500 WITHOUT Retry-After — resubmitting it would
                    # only burn more of the fleet (README "Failure
                    # model").
                    raise web.HTTPInternalServerError(
                        text=json.dumps({
                            "error": "request quarantined as poison",
                            "request_id": seq.trace_id}),
                        content_type="application/json")
                if (payload.finish_reason in ("error", "unavailable")
                        and not consumed and not prepared):
                    # The replica died (or was quarantined) before a
                    # single token left the server and the failover
                    # budget is spent: headers are unsent, so fail as a
                    # clean retryable 503 instead of a 200 whose terminal
                    # record buries done_reason="error".
                    raise web.HTTPServiceUnavailable(
                        text=json.dumps(
                            {"error": "replica failure before first token"}),
                        content_type="application/json",
                        headers=self._retry_after_headers(
                            self.cfg.server.retry_after_s))
                if not prepared:
                    await resp.prepare(request)
                    prepared = True
                tail, stopped = matcher.push(decoder.flush())
                if not stopped:
                    tail += matcher.flush()
                if tail:
                    await write_line(tail)
                return await finish(stopped, fseq=payload)

    async def _unary_response(self, request: web.Request, queue: asyncio.Queue,
                              seq: Sequence, model_name: str,
                              recv_t: float, chat: bool = False,
                              stop: Optional[list] = None,
                              warnings: Optional[list] = None
                              ) -> web.Response:
        decoder = IncrementalDecoder(self.tokenizer,
                                     prompt_tail=seq.prompt_tokens[-8:])
        matcher = StopMatcher(stop or [])
        parts: list = []
        consumed: list = []            # token ids delivered to THIS handler
        timeout = self.cfg.server.request_timeout_s

        def respond(payload, stopped: bool) -> web.Response:
            final = self._final_record(payload, model_name, recv_t, chat,
                                       warnings)
            if stopped:
                # Snapshot only handler-consumed tokens (the engine thread
                # may append more before the cancel lands).
                final["done_reason"] = "stop"
                final["eval_count"] = len(consumed)
                if "context" in final:
                    final["context"] = list(seq.prompt_tokens) + consumed
            text = "".join(parts)
            if chat:
                final["message"] = {"role": "assistant", "content": text}
            else:
                final["response"] = text
            return web.json_response(
                final, headers={"X-Request-Id": seq.trace_id})

        while True:
            kind, payload = await asyncio.wait_for(queue.get(), timeout)
            if kind == "token":
                consumed.append(payload)
                emit, stopped = matcher.push(decoder.push(payload))
                parts.append(emit)
                if stopped:
                    self.group.cancel(seq.request_id)
                    return respond(seq, stopped=True)
            else:
                if payload.finish_reason == "poison":
                    # Terminal quarantine (mirrors the streaming path):
                    # structured 500, no Retry-After — the request
                    # itself is the fault, not the fleet's state.
                    raise web.HTTPInternalServerError(
                        text=json.dumps({
                            "error": "request quarantined as poison",
                            "request_id": seq.trace_id}),
                        content_type="application/json")
                if (payload.finish_reason in ("error", "unavailable")
                        and not consumed):
                    # Replica failure before any token, failover budget
                    # spent: clean retryable 503 (mirrors the streaming
                    # path).
                    raise web.HTTPServiceUnavailable(
                        text=json.dumps(
                            {"error": "replica failure before first token"}),
                        content_type="application/json",
                        headers=self._retry_after_headers(
                            self.cfg.server.retry_after_s))
                tail, stopped = matcher.push(decoder.flush())
                parts.append(tail)
                if not stopped:
                    parts.append(matcher.flush())
                return respond(payload, stopped)


def build_server(model: str = "tiny-llama", tokenizer: str = "byte",
                 checkpoint: Optional[str] = None, warmup: bool = True,
                 tp: int = 1, sp: int = 1, dp: int = 1,
                 draft_model: Optional[str] = None,
                 draft_checkpoint: Optional[str] = None,
                 enable_debug: bool = False,
                 server_overrides: Optional[dict] = None,
                 **engine_overrides) -> InferenceServer:
    """Convenience constructor used by CLI, tests, and benchmarks.

    ``model``/``draft_model`` accept a preset name, a path to a HF
    checkpoint directory (architecture read from its config.json), or
    "auto" with ``checkpoint`` set. ``tokenizer="auto"`` uses the
    checkpoint directory's tokenizer files when present, else bytes.
    ``server_overrides`` are extra ServerConfig fields (supervision
    knobs: step_watchdog_s, admission_queue_depth, ...).
    """
    import os

    from tpu_inference.config import EngineConfig, ParallelConfig, ServerConfig

    # Single model-resolution rule, shared with the pre-boot auto-sizing
    # path so the model that gets sized is the model that boots.
    from tpu_inference.engine.autosize import resolve_model_and_checkpoint
    resolve = resolve_model_and_checkpoint

    model_cfg, checkpoint = resolve(model, checkpoint)
    if tokenizer == "auto":
        has_tok = checkpoint and any(
            os.path.exists(os.path.join(checkpoint, f))
            for f in ("tokenizer.json", "tokenizer_config.json"))
        tokenizer = checkpoint if has_tok else "byte"
    engine_cfg = EngineConfig(**engine_overrides) if engine_overrides else EngineConfig()
    cfg = FrameworkConfig(model=model_cfg, engine=engine_cfg,
                          parallel=ParallelConfig(dp=dp, tp=tp, sp=sp),
                          server=ServerConfig(model_name=model,
                                              tokenizer=tokenizer,
                                              warmup=warmup,
                                              enable_debug=enable_debug,
                                              **(server_overrides or {})),
                          checkpoint_path=checkpoint)
    draft_cfg = None
    if draft_model:
        draft_cfg, draft_checkpoint = resolve(draft_model, draft_checkpoint)
    if draft_cfg is not None and checkpoint and not draft_checkpoint:
        # Trained target + random draft = ~zero acceptance: every
        # round pays draft+verify to emit one token. Refuse loudly.
        raise ValueError(
            "--draft-model with --checkpoint requires "
            "--draft-checkpoint: a random-weight draft makes "
            "speculative decoding a pure slowdown")

    def _loader(mcfg, path):
        """(mesh | None) -> params: checkpoints stream per-replica so each
        replica's leaves land directly in ITS device layout — never an
        unsharded copy on host or device 0 (host-OOM at 70B scale). With
        quant on, each matmul weight quantizes as it lands, so peak device
        memory stays ~int8-model-sized (never full bf16 + int8)."""
        def load(mesh):
            from tpu_inference.models import weights

            shardings = None
            if mesh is not None:
                from tpu_inference.parallel import shardings as shd

                shardings = shd.param_shardings(mcfg, mesh)
            return weights.load_checkpoint(mcfg, path, shardings=shardings,
                                           quant=cfg.engine.quant)

        return load

    t0 = time.perf_counter()
    group = build_engine_group(
        cfg,
        load_params=_loader(model_cfg, checkpoint) if checkpoint else None,
        draft_cfg=draft_cfg,
        load_draft=(_loader(draft_cfg, draft_checkpoint)
                    if draft_checkpoint else None))
    load_ns = int((time.perf_counter() - t0) * 1e9)
    return InferenceServer(cfg, group=group, load_duration_ns=load_ns)
