"""Engine-worker process: one engine + scheduler per OS process.

One half of the subprocess fleet (README "Process fleet"; the other half
is ``server/fleet.py``'s router). The worker owns exactly one dp replica
— its own devices, KV pool, prefix cache/host tier, and continuous-
batching scheduler thread — and serves a small length-prefixed JSON RPC
over a local unix socket:

    frame   = [u32 magic][u32 json_len][u32 blob_len][u32 crc32c]
              [json][blob]                    (see server/transport.py)
    request = {"id": n, "verb": ..., ...}        -> {"id": n, "ok": ...}
    event   = {"ev": "token" | "finish" | "migrate" | "drained", ...}

Verbs: ``hello`` (worker/model facts), ``submit`` / ``cancel`` (request
lifecycle; tokens and the terminal record stream back as events on the
same connection, unbuffered), ``peek`` (side-effect-free tiered prefix
probe + load/pressure — the router's prefix-affinity scoring input),
``stats`` / ``metrics`` / ``healthz`` / ``recent`` (observability),
``chaos`` (engine-level fault injection), ``embed``, ``drain``
(graceful wind-down with KV export), ``import-kv`` (adopt a sibling
replica's drain export into the host tier), ``shutdown``, and ``debug``
(pool-invariant snapshot for the leak tests).

Graceful drain (SIGTERM or the drain RPC): the worker stops admitting,
settles in-flight dispatches, and — with migration enabled — exports
each live sequence's KV pages in the host serialization layout
(engine.export_sequence_kv) as one ``migrate`` event per request, so
the router can import them into a destination worker's host tier and
resubmission becomes a swap-in-resume instead of a from-scratch
re-prefill. ``kill -9`` skips all of this by definition; the router's
resubmission failover (fleet-side token record, recompute-resume)
covers it.

The module top imports only the stdlib so the router can import the
frame codec without paying for jax; everything heavy loads inside
``EngineWorker.boot``.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import queue
import signal
import socket
import struct
import sys
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Tuple

# ---------------------------------------------------------------------------
# Frame codec — ONE implementation, shared with server/fleet.py. Lives
# in server/transport.py (checksummed v2 format + the chaos shim);
# re-exported here because both the router and older tests import the
# codec from this module.
# ---------------------------------------------------------------------------

from tpu_inference.integrity import KVIntegrityError  # noqa: E402
from tpu_inference.server.transport import (  # noqa: F401,E402
    MAX_FRAME,
    ChaosPolicy,
    ChaosTransport,
    FrameError,
    recv_frame,
    send_frame,
)


class _Conn:
    """One router connection: a reader thread dispatching verbs and a
    writer thread draining an outbound queue, so engine-thread callbacks
    (token/finish events) never block on socket I/O."""

    def __init__(self, worker: "EngineWorker", sock: socket.socket):
        self.worker = worker
        self.sock = sock
        self.rfile = sock.makefile("rb")
        self.outq: "queue.Queue" = queue.Queue()
        self.alive = True
        self._writer = threading.Thread(target=self._write_loop,
                                        name="worker-conn-writer",
                                        daemon=True)
        self._reader = threading.Thread(target=self._read_loop,
                                        name="worker-conn-reader",
                                        daemon=True)
        self._writer.start()
        self._reader.start()

    def send(self, obj: Dict[str, Any], blob: bytes = b"",
             verb: str = "") -> None:
        """Queue one outbound frame. ``verb`` tags it for the chaos
        shim's per-verb filter (reply frames carry their request verb,
        events their event name)."""
        if self.alive:
            self.outq.put((obj, blob, verb))

    def flush(self, timeout: float = 5.0) -> None:
        """Wait for every ALREADY-queued frame to finish its sendall
        (drain exit path: the migrate/drained events must leave before
        the process does). A sentinel rides the queue — the writer sets
        it only after the preceding frames' writes completed, so this
        cannot race a frame mid-write like an emptiness poll would."""
        evt = threading.Event()
        self.outq.put(("__flush__", evt))
        evt.wait(timeout)

    def close(self) -> None:
        self.alive = False
        self.outq.put(None)
        try:
            self.sock.close()
        except OSError:
            pass

    def _write_loop(self) -> None:
        while True:
            item = self.outq.get()
            if item is None:
                return
            if item[0] == "__flush__":
                item[1].set()
                continue
            try:
                # Worker->router frames are the chaos shim's "recv"
                # direction (named from the router's point of view).
                send_frame(self.sock, item[0], item[1],
                           chaos=self.worker.chaos_rpc,
                           verb=item[2], direction="recv")
            except (OSError, ConnectionError):
                self.alive = False
                return

    def _read_loop(self) -> None:
        try:
            while True:
                obj, blob = recv_frame(self.rfile)
                self.worker.handle(self, obj, blob)
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass
        finally:
            self.alive = False
            self.worker.forget_conn(self)


class EngineWorker:
    """One replica's engine + scheduler behind the RPC socket."""

    def __init__(self, cfg, replica: int, socket_path: str,
                 warmup: bool = True):
        self.cfg = cfg
        self.replica = replica
        self.socket_path = socket_path
        # Phase role (README "P/D disaggregation"): the router ships a
        # per-worker role in the envelope (main() folds it into
        # cfg.engine.role before construction). "prefill" workers hand
        # each settled prefill off instead of decoding it; "decode"
        # workers adopt handoffs; "mixed" is the pre-P/D behavior.
        self.role = cfg.engine.role
        self.do_warmup = warmup
        self.warmup_s = 0.0
        self.started_unix = time.time()
        # Orphan guard: a worker whose router died (kill -9 of the
        # ROUTER, bench shortcut teardown) must not linger as an idle
        # orphan — reparenting to init is the tell.
        self._parent_pid = os.getppid()
        self.engine = None
        self.sched = None
        self.draining = False
        self._drained_evt = threading.Event()
        self._shutdown = threading.Event()
        self._conns: list = []
        self._conns_lock = threading.Lock()
        # rid -> the connection that submitted it (migrate events go
        # back to the submitting router connection).
        self._req_conn: Dict[int, _Conn] = {}
        # Byzantine-transport defenses (README "Failure model"):
        # worker-side chaos shim for worker->router frames, the
        # idempotency-replay cache (token -> recorded reply, so a verb
        # retried over a new connection cannot double-apply). Corrupt-KV
        # rejections count on engine.kv_integrity_rejections (healthz).
        self.chaos_rpc = self._build_chaos_rpc()
        self._idem: "OrderedDict[str, dict]" = OrderedDict()
        self._idem_lock = threading.Lock()
        # Zero-copy KV plane (README "KV data plane"): the router's
        # boot envelope may carry a shared-memory region spec; when
        # attached, KV exports (fabric publish, P/D handoff, drain
        # migrate) write payloads into the arena and ship descriptors
        # instead of blobs. None = relay plane (blobs over the socket).
        self._arena = None
        # Router pool watermark (fabric back-pressure): free pages the
        # fabric pool advertised at boot, refreshed on every stats
        # tick. None = no watermark yet, publish freely.
        self._fabric_free = None
        self.fabric_publish_skipped = 0

    def attach_arena(self, spec) -> None:
        """Map the router's shm segment from a boot-envelope region
        spec. Failure is not fatal — the worker simply stays on the
        relay plane (every payload rides the socket)."""
        if not spec:
            return
        from tpu_inference import telemetry
        from tpu_inference.server import shm_arena
        try:
            self._arena = shm_arena.WorkerArena(spec)
        except Exception as e:  # noqa: BLE001 — relay fallback, not fatal
            self._arena = None
            telemetry.log_event(
                "shm_arena_attach_failed", level="warning",
                replica=self.replica, error=str(e))

    def _arena_blob(self, desc, path: str):
        """Materialize a descriptor's payload from the arena, typed by
        failure: returns (blob, rejected) where rejected=True means the
        slab FAILED ITS INTEGRITY CHECK (counted, the router must drop
        the descriptor) and blob=b'' with rejected=False means the slab
        is stale/unreachable (epoch bumped after a reclaim, arena not
        attached) — the caller falls back to recompute/relay."""
        from tpu_inference import telemetry
        from tpu_inference.server import shm_arena
        if self._arena is None or desc is None:
            return b"", False
        try:
            return self._arena.read(desc), False
        except shm_arena.ArenaCorrupt as e:
            self.engine.kv_integrity_rejections += 1
            telemetry.log_event(
                "arena_slab_rejected", level="error", path=path,
                replica=self.replica, reason=e.reason, detail=e.detail)
            return b"", True
        except shm_arena.ArenaError:
            return b"", False

    def _build_chaos_rpc(self, over: Dict[str, Any] = None):
        """Worker-side chaos transport from config knobs (+ runtime
        overrides via the chaos verb). The wedge fault is router-side
        only — its detection signal (per-verb RPC deadlines) lives in
        the router, so the worker never arms ``wedge_after``."""
        s = self.cfg.server
        kw = {"seed": getattr(s, "chaos_rpc_seed", 0),
              "corrupt_rate": getattr(s, "chaos_rpc_corrupt_rate", 0.0),
              "drop_rate": getattr(s, "chaos_rpc_drop_rate", 0.0),
              "delay_rate": getattr(s, "chaos_rpc_delay_rate", 0.0),
              "delay_s": getattr(s, "chaos_rpc_delay_s", 0.02),
              "truncate_rate": getattr(s, "chaos_rpc_truncate_rate", 0.0),
              "verbs": getattr(s, "chaos_rpc_verbs", ()),
              "direction": getattr(s, "chaos_rpc_direction", "both")}
        for k, v in (over or {}).items():
            if k in kw and v is not None:
                kw[k] = tuple(v) if k == "verbs" else v
        if kw["direction"] not in ("recv", "both"):
            return None
        # Decorrelate from the router side's schedule (seed + replica).
        kw["seed"] = int(kw["seed"]) + 7919 * (self.replica + 1)
        pol = ChaosPolicy(**kw)
        return ChaosTransport(pol) if pol.active else None

    # ------------------------------------------------------------- boot

    def boot(self) -> None:
        from tpu_inference.engine.engine import InferenceEngine
        from tpu_inference.engine.scheduler import EngineScheduler

        cfg = self.cfg
        pcfg = cfg.parallel
        mesh = None
        if pcfg.tp * pcfg.sp > 1:
            from tpu_inference.config import ParallelConfig
            from tpu_inference.parallel.mesh import build_mesh
            mesh = build_mesh(ParallelConfig(tp=pcfg.tp, sp=pcfg.sp))
        params = None
        if cfg.checkpoint_path:
            from tpu_inference.models import weights
            shardings = None
            if mesh is not None:
                from tpu_inference.parallel import shardings as shd
                shardings = shd.param_shardings(cfg.model, mesh)
            params = weights.load_checkpoint(
                cfg.model, cfg.checkpoint_path, shardings=shardings,
                quant=cfg.engine.quant)
        self.engine = InferenceEngine(cfg.model, cfg.engine, params=params,
                                      seed=cfg.seed, mesh=mesh)
        self.sched = EngineScheduler(self.engine)
        # Tracing + dashboard-join series: spans this worker records
        # carry its stable replica index, and the registry emits the
        # build_info gauge with config-pure labels (identical across
        # restarts, so the router's carry never sees a label change).
        import jax as _jax

        from tpu_inference import telemetry as _tm
        self.engine.telemetry.recorder.replica = self.replica
        _tm.emit_build_info(
            self.engine.telemetry.registry,
            backend=_jax.default_backend(),
            fleet=cfg.server.fleet,
            kv_quant=cfg.engine.kv_quant,
            spec_mode=(self.engine.spec_mode if self.engine.spec_enabled
                       else "off"),
            routing=cfg.server.routing)
        # Zero-copy KV plane counters (README "KV data plane"): arena
        # traffic this worker moved without a socket copy, plus the
        # publishes the fabric watermark gated off. Registered on the
        # relay plane too (flat zeros) so dashboards join across arms.
        reg = self.engine.telemetry.registry
        reg.counter(
            "tpu_inf_kv_plane_shm_puts_total",
            "KV payloads published into the shm arena",
            fn=lambda: self._arena.puts if self._arena else 0)
        reg.counter(
            "tpu_inf_kv_plane_shm_gets_total",
            "KV payloads adopted out of the shm arena",
            fn=lambda: self._arena.gets if self._arena else 0)
        reg.counter(
            "tpu_inf_kv_plane_shm_bytes_total",
            "bytes moved through the shm arena by direction",
            fn=lambda: self._arena.put_bytes if self._arena else 0,
            op="put")
        reg.counter(
            "tpu_inf_kv_plane_shm_bytes_total",
            "bytes moved through the shm arena by direction",
            fn=lambda: self._arena.get_bytes if self._arena else 0,
            op="get")
        reg.counter(
            "tpu_inf_fabric_publish_skipped_total",
            "fabric publishes skipped by the pool-watermark gate",
            fn=lambda: self.fabric_publish_skipped)
        if self.role == "prefill":
            self.sched.on_prefill_handoff = self._emit_handoff
        # Fleet KV fabric (README "KV fabric"): arm the engine's
        # publish hook — settled prefix pages broadcast to the router's
        # pool as fabric_put event frames, so a prefix prefilled here
        # warms every replica. The knobs ride the config envelope.
        if (cfg.server.fabric_cache_pages > 0
                and self.engine.prefix_cache is not None):
            self.engine.fabric_publish = self._publish_fabric
            self.engine.fabric_publish_min_pages = \
                cfg.server.fabric_publish_min_pages
        # Crash flight recorder: per-replica dir under the OPERATOR's
        # --blackbox-dir ('' = off). The dir outlives this process, so
        # the fleet monitor can harvest evidence after a kill -9.
        import dataclasses as _dc
        _tm.attach_flight_recorder(
            self.engine.telemetry, cfg.server.blackbox_dir, self.replica,
            retain=cfg.server.blackbox_retain,
            config=_dc.asdict(cfg),
            stats_fn=lambda: self.sched.stats.snapshot(self.engine))
        if self.do_warmup:
            self.warmup_s = self.engine.warmup()
        self.sched.start()

    # ------------------------------------------------------------ serve

    def serve(self) -> None:
        """Bind/listen FIRST (so the router's connect succeeds while the
        engine still boots — its hello RPC simply waits), then boot, then
        accept until shutdown."""
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self.socket_path)
        srv.listen(4)
        srv.settimeout(0.25)
        self.boot()
        print(f"[worker {self.replica}] pid={os.getpid()} serving on "
              f"{self.socket_path}", file=sys.stderr, flush=True)
        while not self._shutdown.is_set():
            if os.getppid() != self._parent_pid:
                print(f"[worker {self.replica}] router gone (reparented)"
                      " — exiting", file=sys.stderr, flush=True)
                break
            try:
                sock, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            with self._conns_lock:
                self._conns.append(_Conn(self, sock))
        try:
            srv.close()
            os.unlink(self.socket_path)
        except OSError:
            pass

    def forget_conn(self, conn: _Conn) -> None:
        with self._conns_lock:
            if conn in self._conns:
                self._conns.remove(conn)

    def _broadcast(self, obj: Dict[str, Any], blob: bytes = b"",
                   verb: str = "") -> None:
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            c.send(obj, blob, verb)

    def _publish_fabric(self, pairs) -> None:
        """Ship settled prefix pages to the router's fabric pool
        (engine thread, via _publish_to_fabric). Each page is
        serialized individually — the pool stores per-page entries so
        they evict independently and every adoption re-verifies its own
        crc32c.

        Back-pressure gate first (README "KV fabric"): the router
        advertises its pool's free-page watermark (boot envelope +
        every stats tick); a publish that cannot fit would only be
        serialized, shipped, and evicted on arrival — skip it here and
        count the skip instead.

        On the shm plane the payloads go into this worker's arena
        region and only descriptors cross the socket; a full region
        falls back to the relay frame for the overflow pages."""
        from tpu_inference.engine import kv_cache as kvc
        from tpu_inference.server import shm_arena
        free = self._fabric_free
        if free is not None:
            if len(pairs) > free:
                self.fabric_publish_skipped += len(pairs)
                return
            self._fabric_free = free - len(pairs)
        if self._arena is not None:
            hex_descs, descs, relay = [], [], []
            for d, p in pairs:
                blob = kvc.serialize_host_pages([p])
                try:
                    descs.append(self._arena.publish(blob))
                    hex_descs.append(d.hex())
                except shm_arena.ArenaFull:
                    relay.append((d, blob))
            if descs:
                self._broadcast({"ev": "fabric_put",
                                 "digests": hex_descs,
                                 "descs": descs,
                                 "replica": self.replica},
                                verb="fabric_put")
            if not relay:
                return
            self._broadcast({"ev": "fabric_put",
                             "digests": [d.hex() for d, _ in relay],
                             "lens": [len(b) for _, b in relay],
                             "replica": self.replica},
                            b"".join(b for _, b in relay),
                            verb="fabric_put")
            return
        blobs = [kvc.serialize_host_pages([p]) for _, p in pairs]
        self._broadcast({"ev": "fabric_put",
                         "digests": [d.hex() for d, _ in pairs],
                         "lens": [len(b) for b in blobs],
                         "replica": self.replica},
                        b"".join(blobs), verb="fabric_put")

    # --------------------------------------------------------- dispatch

    # Verbs that can block for seconds (device forwards, engine-loop
    # waits, scheduler drains) run on their own thread so the reader
    # stays responsive — the router's routing peeks must never stall
    # behind a migration import or an embed batch on the same worker.
    _SLOW_VERBS = ("import_kv", "embed", "shutdown", "profile")

    # Verbs with side effects the router may retry over a fresh
    # connection: the idempotency token dedups exact duplicates so a
    # retransmitted frame replays the recorded reply instead of
    # re-applying (submit admitting a second live attempt, import-kv
    # re-offering pages).
    _IDEM_VERBS = ("submit", "cancel", "import_kv")
    _IDEM_CAP = 512

    def handle(self, conn: _Conn, obj: Dict[str, Any],
               blob: bytes) -> None:
        rid = obj.get("id")
        verb = str(obj.get("verb")).replace("-", "_")
        idem = obj.get("idem") if verb in self._IDEM_VERBS else None

        def run() -> None:
            if idem is not None:
                with self._idem_lock:
                    prev = self._idem.get(idem)
                if prev is not None:
                    out = {"id": rid}
                    out.update(prev)
                    if verb == "submit" and "rid" in prev:
                        # The first submit applied; rebind the stream
                        # to the retrying connection so in-flight
                        # tokens reach the live router socket.
                        self._req_conn[int(prev["rid"])] = conn
                    conn.send(out, verb=verb)
                    return
            try:
                fn = getattr(self, "_verb_" + verb, None)
                if fn is None:
                    raise ValueError(f"unknown verb {obj.get('verb')!r}")
                reply = fn(conn, obj, blob)
                if reply is not None:
                    out = {"id": rid, "ok": True}
                    out.update(reply)
                    if idem is not None and out.get("ok"):
                        with self._idem_lock:
                            self._idem[idem] = {k: v for k, v
                                                in out.items()
                                                if k != "id"}
                            while len(self._idem) > self._IDEM_CAP:
                                self._idem.popitem(last=False)
                    conn.send(out, verb=verb)
            except Exception as e:  # noqa: BLE001 — RPC errors reply
                conn.send({"id": rid, "ok": False, "error": str(e),
                           "kind": type(e).__name__}, verb=verb)

        if verb in self._SLOW_VERBS:
            threading.Thread(target=run, name=f"worker-{verb}",
                             daemon=True).start()
        else:
            run()

    # ------------------------------------------------------------ verbs

    def _emit_handoff(self, seq) -> bool:
        """Scheduler hook (engine thread, prefill role): export the
        settled live sequence and push it to the submitting router
        connection as a ``handoff`` event — the router imports/adopts it
        on a decode worker and the stream continues there. Returns False
        (sequence keeps decoding locally, the mixed fallback) when the
        connection is gone or nothing is exportable (e.g. SWA-evicted
        pages)."""
        from tpu_inference import telemetry
        from tpu_inference.engine import kv_cache as kvc
        conn = self._req_conn.get(seq.request_id)
        if conn is None or not conn.alive or self.draining:
            return False
        t0 = time.perf_counter()
        try:
            digests, pages, ctx_len = \
                self.engine.export_sequence_kv_live(seq)
        except Exception as e:  # noqa: BLE001 — fall back to local decode
            telemetry.log_event("handoff_export_failed", level="warning",
                                request_id=seq.trace_id
                                or str(seq.request_id), error=str(e))
            return False
        if not pages:
            return False
        parts = kvc.serialize_host_pages_parts(pages)
        total = sum(len(p) for p in parts)
        # Trace span: the live KV export — adjacent to (never
        # overlapping) this worker's prefill span and the decode
        # worker's handoff_adopt on the assembled timeline. It ends
        # HERE, before the payload leaves for the data plane: the
        # gather+serialize is identical work on every plane, while the
        # arena publish (shm) and the frame send (relay) are the data
        # plane itself and belong to the handoff window that follows.
        t_ser = time.perf_counter()
        # Zero-copy plane: the serialized parts gather-write into one
        # arena slab — the payload's single copy — and only the
        # descriptor rides the handoff frame; the decode worker adopts
        # straight from shared memory. A full region falls back to the
        # relay frame (the parts join into a blob over the socket).
        kv_desc = None
        if self._arena is not None:
            from tpu_inference.server import shm_arena
            try:
                kv_desc = self._arena.publish_parts(parts)
            except shm_arena.ArenaFull:
                kv_desc = None
        self.engine.telemetry.recorder.add(
            "handoff_export", seq.trace_id or str(seq.request_id),
            t0, t_ser, pages=len(pages), bytes=total,
            ctx_len=ctx_len, plane="shm" if kv_desc else "relay")
        self._req_conn.pop(seq.request_id, None)
        ev = {"ev": "handoff", "rid": seq.request_id,
              "n_generated": len(seq.generated),
              "ctx_len": ctx_len,
              "export_s": round(time.perf_counter() - t0, 6),
              "digests": [d.hex() for d in digests]}
        if kv_desc is not None:
            ev["kv_desc"] = kv_desc
            conn.send(ev, verb="handoff")
        else:
            conn.send(ev, b"".join(parts), verb="handoff")
        return True

    def _verb_hello(self, conn, obj, blob) -> dict:
        e = self.engine
        return {
            "pid": os.getpid(),
            "replica": self.replica,
            "role": self.role,
            "uptime_s": round(time.time() - self.started_unix, 3),
            "warmup_s": round(self.warmup_s, 3),
            "n_params": e.n_params,
            "weight_bytes": e.weight_bytes,
            "attn_backend": e.attn_backend,
            "ladder": list(e.ladder),
            "swa_evict": e.swa_evict,
            "prefix_cache": e.prefix_cache is not None,
            "host_cache_pages": (e.host_pool.capacity
                                 if e.host_pool is not None else 0),
            "spec_draft": bool(getattr(e, "spec_draft", False)),
            "spec_mode": e.spec_mode if e.spec_enabled else None,
        }

    def _verb_submit(self, conn, obj, blob) -> dict:
        if self.draining:
            return {"ok": False, "kind": "draining",
                    "error": "worker draining"}
        from tpu_inference.engine.engine import Sequence
        s = obj["seq"]
        seq = Sequence(
            request_id=int(s["request_id"]),
            prompt_tokens=list(s["prompt_tokens"]),
            max_new_tokens=int(s["max_new_tokens"]),
            temperature=float(s.get("temperature", 0.0)),
            top_p=float(s.get("top_p", 1.0)),
            top_k=s.get("top_k"),
            seed=s.get("seed"),
            repeat_penalty=float(s.get("repeat_penalty", 1.0)),
            repeat_last_n=int(s.get("repeat_last_n", 64)),
            eos_token_id=s.get("eos_token_id"),
            trace_id=s.get("trace_id", ""),
            priority_class=s.get("class", "interactive"),
            attempt=int(s.get("attempt", 0)))
        # Router-side routing accounting rides the payload so this
        # worker's /debug/requests timelines show which replica served
        # the attempt and the fabric pull that warmed the dispatch
        # (README "KV fabric").
        seq.routed_replica = self.replica
        seq.route_hit_pages = int(s.get("route_hit_pages", 0))
        seq.route_host_hit_pages = int(
            s.get("route_host_hit_pages", 0))
        seq.route_fabric_hit_pages = int(
            s.get("route_fabric_hit_pages", 0))
        generated = s.get("generated") or []
        if generated:
            # Fleet-side recompute-resume (README "Process fleet"): the
            # router replays the tokens it already streamed; prefill
            # covers prompt + generated (host-tier hits from a drain
            # import make it a swap-in-resume) and decode continues.
            seq.generated = list(generated)
            seq.resume_base = len(generated)
        handoff = s.get("handoff")
        if handoff and not blob and handoff.get("kv_desc") is not None:
            # Zero-copy adoption: pull the export straight out of the
            # arena slab the prefill worker wrote. A stale slab (owner
            # died, region reclaimed) or a failed crc leaves blob empty
            # and the recompute-resume fallback below takes over —
            # byte-identical under greedy, exactly the relay semantics.
            blob, _ = self._arena_blob(handoff["kv_desc"], "handoff")
        if handoff and blob and generated:
            # P/D handoff resume (README "P/D disaggregation"): the blob
            # carries the prefill worker's settled KV pages (incl. the
            # partial final page); admission adopts them directly — no
            # re-prefill, zero recomputed tokens. A malformed blob falls
            # back to the recompute-resume path above at adoption time.
            from tpu_inference.engine import kv_cache as kvc
            try:
                # copy=False: the adopt path hands the pages straight
                # to the device restore — views over the blob (kept
                # alive by the arrays) skip a full payload copy.
                pages = kvc.deserialize_host_pages(blob, copy=False)
            except KVIntegrityError:
                # Corrupt blob: rejected AND counted — never adopted.
                self.engine.kv_integrity_rejections += 1
                pages = []
            except Exception:  # noqa: BLE001 — recompute-resume fallback
                pages = []
            if pages:
                seq.adopt_kv = (pages, int(handoff.get("ctx_len", 0)))
            else:
                self.engine.adopt_fallbacks += 1
        elif handoff and generated and not blob:
            self.engine.adopt_fallbacks += 1
        if self.role == "prefill" and seq.adopt_kv is None:
            # Prefill-role workers hand every prefill they settle off to
            # the decode tier (adoptions skip _prefill_done, so an
            # adopted fallback landing here decodes locally instead of
            # bouncing forever).
            seq.handoff_after_prefill = True
        rid = seq.request_id
        # A resubmitted rid (router retry after a reconnect resync or a
        # lost ack) must never leave TWO live attempts decoding the
        # same request — cancel the ghost before admitting this one.
        def _rid_live() -> bool:
            with self.sched._lock:
                return (rid in self.sched._callbacks or any(
                    p.seq.request_id == rid
                    for p in self.sched._waiting))

        if _rid_live():
            self.sched.cancel(rid)
            # cancel() only FLAGS a running attempt done — the engine
            # loop reaps it next tick. Admitting the same rid before
            # the reap would leave two registered attempts: the ghost
            # keeps streaming stale tokens through the new binding
            # (the router sees a stream gap). Wait the reap out.
            deadline = time.monotonic() + 5.0
            while _rid_live() and time.monotonic() < deadline:
                time.sleep(0.005)
            if _rid_live():
                return {"error": f"request {rid} still draining "
                                 "its previous attempt"}
        self._req_conn[rid] = conn

        # "k" is the token's absolute stream index, counted here: the
        # engine appends to seq.generated as it steps but may deliver
        # several buffered tokens in one burst (e.g. after a batch-shape
        # recompile), so len(generated)-1 at callback time would stamp
        # the last index on every token of the burst. The counter starts
        # at the resume prefix so a migrated/handoff resume continues
        # the router's stream where it left off.
        knext = itertools.count(len(seq.generated))

        def on_token(sq, tok: int) -> None:
            conn.send({"ev": "token", "rid": rid, "t": int(tok),
                       "k": next(knext)}, verb="token")

        def on_finish(sq) -> None:
            self._req_conn.pop(rid, None)
            tid = sq.trace_id or str(rid)
            spans = self.engine.telemetry.recorder.export_recent(tid)
            if sq.finish_reason == "handoff":
                # The handoff event already left on this connection and
                # IS the request's continuation — a finish frame here
                # would terminate the client stream mid-generation. The
                # prefill-side spans (sealed just now, AFTER the
                # handoff frame) ship on their own event instead.
                if spans:
                    conn.send({"ev": "spans", "rid": rid, "trace": tid,
                               "spans": spans}, verb="spans")
                return
            fin = sq.finish_time or time.perf_counter()
            first = sq.first_token_time or fin
            start = sq.prefill_start or first
            conn.send({
                "ev": "finish", "rid": rid,
                "reason": sq.finish_reason or "stop",
                "n_generated": len(sq.generated),
                "cached_tokens": sq.cached_tokens,
                "host_restored_pages": sq.host_restored_pages,
                "preemptions": sq.preemptions,
                "resume_base": sq.resume_base,
                "prefill_s": round(max(0.0, first - start), 6),
                "decode_s": round(max(0.0, fin - first), 6),
                # Completed spans ride the finish frame back to the
                # router's trace assembly (README "Observability").
                "trace": tid,
                "spans": spans,
            }, verb="finish")

        self.sched.submit(seq, on_token, on_finish)
        return {"rid": rid}

    def _verb_cancel(self, conn, obj, blob) -> dict:
        self.sched.cancel(int(obj["rid"]))
        self._req_conn.pop(int(obj["rid"]), None)
        return {}

    def _verb_peek(self, conn, obj, blob) -> dict:
        """Router scoring probe: tiered prefix peek + load/pressure.
        Side-effect-free on the cache (PrefixCache.peek contract), safe
        from this RPC thread."""
        digests = [bytes.fromhex(d) for d in obj.get("digests") or ()]
        hbm = host = 0
        pc = self.engine.prefix_cache
        if pc is not None and digests:
            hbm, host = pc.peek_digests_tiered(digests)
        return {"hbm": hbm, "host": host, "load": self.sched.load,
                "pressure": bool(self.engine.under_pressure),
                # P/D routing inputs (README "P/D disaggregation"):
                # phase role, prefill backlog depth (queued requests),
                # and decode ladder occupancy (bound lanes / top rung).
                "role": self.role,
                "backlog": len(self.sched._waiting),
                "occupancy": self._ladder_occupancy()}

    def _ladder_occupancy(self) -> float:
        e = self.engine
        return round(sum(s is not None for s in e.slots)
                     / max(e.ladder[-1], 1), 4)

    def _verb_stats(self, conn, obj, blob) -> dict:
        # The router's stats tick doubles as the data plane's control
        # channel: the fabric pool's free-page watermark rides in
        # (publish back-pressure) and the batched arena slab frees ride
        # in (descriptor lifecycle — the router freed every consumer).
        ff = obj.get("fabric_free")
        if ff is not None:
            self._fabric_free = int(ff)
        frees = obj.get("arena_free")
        if frees and self._arena is not None:
            for off in frees:
                self._arena.free(int(off))
        return {"stats": self.sched.stats.snapshot(self.engine)}

    def _verb_steps(self, conn, obj, blob) -> dict:
        """Step-ledger roofline report (GET /debug/steps): windowed
        per-step-kind bottleneck verdicts from this replica's ring."""
        return {"steps": self.engine.telemetry.steps_report()}

    def _verb_metrics(self, conn, obj, blob) -> dict:
        from tpu_inference import telemetry
        return {"samples": telemetry.dump_registry(
            self.engine.telemetry.registry)}

    def _verb_healthz(self, conn, obj, blob) -> dict:
        e = self.engine
        out = {
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self.started_unix, 3),
            "draining": self.draining,
            "load": self.sched.load,
            "pool_pressure": round(e.pool_pressure, 4),
            "under_pressure": e.under_pressure,
            "preemptions": e.preemptions_total,
            "swap_in_resumes": e.swap_in_resumes,
            # P/D disaggregation: phase role + the two numbers a
            # handoff stall shows up in (backlog on the prefill side,
            # ladder occupancy on the decode side).
            "role": self.role,
            "prefill_backlog": len(self.sched._waiting),
            "ladder_occupancy": self._ladder_occupancy(),
            "pd_handoffs": self.sched.stats.pd_handoffs,
            "pd_adoptions": e.adoptions_in,
            "pd_adopt_fallbacks": e.adopt_fallbacks,
            # Byzantine transport: corrupt KV blobs this worker
            # rejected at adopt/import time (never adopted silently).
            "kv_integrity_rejections": e.kv_integrity_rejections,
            # Fleet KV fabric: settled prefix pages this worker has
            # published to the router's pool.
            "fabric_published_pages": e.fabric_published_pages,
        }
        # Rolling SLO view (quantiles + breaches; windows stay in the
        # stats snapshot — healthz is the human-sized surface).
        if e.telemetry.slo is not None:
            out["slo"] = e.telemetry.slo.snapshot(include_window=False)
        if e.host_pool is not None:
            out["host_cache"] = {
                "capacity_pages": e.host_pool.capacity,
                "pages_used": e.host_pool.used,
                "offloaded": e.host_pool.offloaded_total,
                "restored": e.host_pool.restored_total,
                "imported": e.host_pool.imported_total,
                "evicted": e.host_pool.evicted_total,
                "swap_in_resumes": e.swap_in_resumes,
                "swap_out_s_total": round(
                    e.host_pool.swap_out_s_total, 6),
                "swap_in_s_total": round(
                    e.host_pool.swap_in_s_total, 6),
            }
        return out

    def _verb_recent(self, conn, obj, blob) -> dict:
        return {"recent": self.sched.recent_snapshot(
            int(obj.get("n", 50)))}

    def _verb_trace(self, conn, obj, blob) -> dict:
        """Pull-based span access (README "Observability"): one trace's
        spans by id, or the recent finished-trace ring — the router's
        fallback when its own assembly missed event frames (e.g. it
        restarted mid-request)."""
        # NB: the trace id rides under "trace" — "id" is the RPC
        # correlation id on every frame.
        rec = self.engine.telemetry.recorder
        tid = obj.get("trace")
        if tid:
            return {"spans": rec.get_trace(str(tid)) or []}
        return {"traces": rec.recent_traces(int(obj.get("n", 64))),
                "maintenance": rec.maintenance_spans()}

    def _verb_profile(self, conn, obj, blob) -> dict:
        """On-demand jax.profiler capture (README "Observability"):
        trace this worker's device+host activity for ``seconds`` and
        return the trace directory (TensorBoard / Perfetto-loadable).
        Serving continues while the profiler runs — that is the point:
        the capture shows the live fleet's dispatch stream. Runs on a
        slow-verb thread; the path is always under the operator's
        profile_dir, never client-chosen."""
        from tpu_inference import telemetry
        return telemetry.capture_jax_profile(
            self.cfg.server.profile_dir, self.replica,
            float(obj.get("seconds", 3.0)))

    def _verb_chaos(self, conn, obj, blob) -> dict:
        e = self.engine
        rate = obj.get("step_failure_rate")
        wedge = obj.get("step_wedge_s")
        pressure = obj.get("page_pressure")
        if rate is not None:
            e.chaos_step_failure_rate = float(rate)
        if wedge is not None:
            e.chaos_step_wedge_s = float(wedge)
        if pressure is not None:
            e.request_page_pressure(int(pressure))
        rpc = obj.get("rpc")
        if rpc is not None:
            # Transport-level chaos (README "Failure model"): rebuild
            # the worker-side shim; the router forwards the same knobs
            # it applied to its own side.
            self.chaos_rpc = self._build_chaos_rpc(rpc)
        t = e._pressure_target
        return {"step_failure_rate": e.chaos_step_failure_rate,
                "step_wedge_s": e.chaos_step_wedge_s,
                "page_pressure": (e.chaos_page_pressure if t is None
                                  else t),
                "rpc": (self.chaos_rpc.policy.snapshot()
                        if self.chaos_rpc is not None else None)}

    def _verb_embed(self, conn, obj, blob) -> dict:
        vecs = self.engine.embed_many([list(b) for b in obj["batch"]])
        return {"embeddings": vecs.tolist()}

    def _verb_import_kv(self, conn, obj, blob) -> dict:
        """Adopt a sibling replica's drain export into the host tier.
        Replies only after the engine loop APPLIED the import, so the
        router's subsequent resubmit is guaranteed to see the pages.

        Three payload shapes: a concatenated blob (relay plane), a list
        of per-page arena descriptors (``descs`` — fabric warmboot and
        fabric pulls on the shm plane), or one multi-page descriptor
        (``kv_desc`` — drain migrate on the shm plane). Descriptor
        reads that fail integrity come back in ``rejected_digests`` so
        the router evicts the poisoned pool entries."""
        from tpu_inference.engine import kv_cache as kvc
        descs = obj.get("descs")
        if descs is not None:
            return self._import_kv_descs(obj.get("digests") or (), descs)
        digests = [bytes.fromhex(d) for d in obj.get("digests") or ()]
        if not blob and obj.get("kv_desc") is not None:
            blob, rejected = self._arena_blob(obj["kv_desc"], "migrate")
            if not blob:
                return {"offered": 0, "applied": False, "adopted": 0,
                        "rejected": "arena slab unreadable"
                        if not rejected else "arena slab corrupt"}
        try:
            pages = kvc.deserialize_host_pages(blob) if blob else []
        except KVIntegrityError as e:
            # Reject-and-count: a corrupt drain export must never land
            # in the host tier; the router's resubmission falls back to
            # recompute-resume (byte-identical under greedy).
            self.engine.kv_integrity_rejections += 1
            return {"offered": 0, "applied": False, "adopted": 0,
                    "rejected": str(e)}
        n = min(len(digests), len(pages))
        before = self.engine.migrate_in_pages
        done = self.engine.request_import_host(
            list(zip(digests[:n], pages[:n])))
        self.sched.kick()
        applied = done.wait(timeout=10.0)
        return {"offered": n, "applied": bool(applied),
                "adopted": self.engine.migrate_in_pages - before}

    def _import_kv_descs(self, hex_digests, descs) -> dict:
        """Descriptor-list import (shm plane): read each per-page slab
        from the arena, deserialize its single-page blob, and offer the
        survivors to the host tier. Integrity failures (slab crc, page
        digest) are counted AND reported back by digest so the router
        drops the unusable pool entries; stale slabs are simply skipped
        (the pull falls back to recompute warmth)."""
        from tpu_inference.engine import kv_cache as kvc
        offers, rejected_hex = [], []
        for hexd, desc in zip(hex_digests, descs):
            pblob, rejected = self._arena_blob(desc, "fabric_pull")
            if not pblob:
                if rejected:
                    rejected_hex.append(hexd)
                continue
            try:
                pgs = kvc.deserialize_host_pages(pblob)
            except KVIntegrityError:
                self.engine.kv_integrity_rejections += 1
                rejected_hex.append(hexd)
                continue
            except Exception:  # noqa: BLE001 — skip, recompute covers it
                continue
            if pgs:
                offers.append((bytes.fromhex(hexd), pgs[0]))
        if not offers:
            return {"offered": 0, "applied": False, "adopted": 0,
                    "rejected_digests": rejected_hex}
        before = self.engine.migrate_in_pages
        done = self.engine.request_import_host(offers)
        self.sched.kick()
        applied = done.wait(timeout=10.0)
        return {"offered": len(offers), "applied": bool(applied),
                "adopted": self.engine.migrate_in_pages - before,
                "rejected_digests": rejected_hex}

    def _verb_drain(self, conn, obj, blob) -> dict:
        migrate = obj.get("migrate")
        if migrate is None:
            migrate = self.cfg.server.fleet_migrate
        threading.Thread(target=self.drain, args=(bool(migrate),),
                         name="worker-drain", daemon=True).start()
        return {"draining": True}

    def _verb_debug(self, conn, obj, blob) -> dict:
        """Pool-invariant snapshot for the cross-process leak tests
        (tests/_leak.py's checks, worker-side): optionally clears the
        prefix cache first so 'fully reclaimable' is checkable. Only
        meaningful when the worker is idle."""
        e = self.engine
        cache = e.prefix_cache
        out = {"pipeline_pending": bool(e.pipeline_pending),
               "preempted_uncollected": len(e._preempted_out)}
        if cache is not None and cache.host_pool is not None:
            pool = cache.host_pool
            out["host_used_matches_entries"] = (
                pool.used == len(cache._host))
            out["host_bytes_match"] = (pool.bytes_resident == sum(
                en.nbytes for en in cache._host.values()))
            out["host_within_capacity"] = (
                0 <= pool.used <= pool.capacity)
            out["tier_overlap"] = len(set(cache._host)
                                      & set(cache._table))
        if obj.get("clear"):
            e.set_page_pressure(0)
            if cache is not None:
                cache.clear()
        alloc = e.allocator
        out.update({
            "num_free": alloc.num_free,
            "num_pages": alloc.num_pages,
            "refs_held": sum(1 for p in range(1, alloc.num_pages)
                             if alloc._refs[p] > 0),
            "evictable_count": alloc.evictable_count,
            "slots_bound": sum(s is not None for s in e.slots),
            "host_used": (cache.host_pool.used
                          if cache is not None
                          and cache.host_pool is not None else 0),
        })
        return out

    def _verb_shutdown(self, conn, obj, blob) -> dict:
        drain = bool(obj.get("drain", True))
        timeout = float(obj.get("timeout_s", 30.0))
        self.draining = True
        self.sched.stop(drain=drain, timeout=timeout)
        self._shutdown.set()
        return {"stopped": True}

    # ------------------------------------------------------------ drain

    def drain(self, migrate: bool) -> None:
        """Graceful wind-down (SIGTERM / drain RPC): freeze the
        scheduler, settle in-flight device work (delivering its tokens),
        export every live request — KV pages included when migration is
        on — as ``migrate`` events, then broadcast ``drained`` (with the
        final stats + metrics dump, the router's restart carry) and
        exit."""
        if self.draining:
            return
        self.draining = True
        from tpu_inference import telemetry
        from tpu_inference.engine import kv_cache as kvc
        t0 = time.monotonic()
        budget = max(1.0, self.cfg.server.drain_timeout_s)
        engine, sched = self.engine, self.sched
        telemetry.log_event("worker_drain", level="warning",
                            replica=self.replica, migrate=migrate,
                            load=sched.load)
        if engine.telemetry.flight is not None:
            # Last full capture before state is torn down (the atexit
            # hook won't run — drain ends in os._exit).
            engine.telemetry.flight.capture("sigterm", min_interval_s=0.0)
        sched.stop(drain=False, timeout=budget)
        try:
            if engine.pipeline_pending:
                sched._deliver(engine.drain_pipeline())
        except Exception:  # noqa: BLE001 — a dying dispatch mustn't block exit
            engine.abort_pipeline()
        engine.take_preempted()
        with sched._lock:
            pendings = (list(sched._callbacks.values())
                        + list(sched._waiting))
        migrated = 0
        for pending in pendings:
            seq = pending.seq
            if seq.done:
                continue
            tid = seq.trace_id or str(seq.request_id)
            digests, host_pages = [], []
            t_exp = time.perf_counter()
            if (migrate and seq.pages
                    and time.monotonic() - t0 < budget):
                try:
                    digests, host_pages = engine.export_sequence_kv(seq)
                except Exception:  # noqa: BLE001
                    digests, host_pages = [], []
            if host_pages:
                engine.telemetry.recorder.add(
                    "drain_export", tid, t_exp, time.perf_counter(),
                    pages=len(host_pages))
            ev = {"ev": "migrate", "rid": seq.request_id,
                  "n_generated": len(seq.generated),
                  "digests": [d.hex() for d in digests],
                  # In-flight spans so far (chunks, swaps, the export):
                  # the request continues on another worker, so its
                  # trace must not die with this process.
                  "trace": tid,
                  "spans": engine.telemetry.recorder.export_open(tid)}
            blob = (kvc.serialize_host_pages(host_pages)
                    if host_pages else b"")
            if blob and self._arena is not None:
                # Zero-copy migrate: the export outlives this process
                # in the arena (the segment is router-owned); only the
                # descriptor rides the event. Region full → relay blob.
                from tpu_inference.server import shm_arena
                try:
                    ev["kv_desc"] = self._arena.publish(blob)
                    blob = b""
                except shm_arena.ArenaFull:
                    pass
            target = self._req_conn.get(seq.request_id)
            if target is not None and target.alive:
                target.send(ev, blob, verb="migrate")
                migrated += 1
        self._broadcast({
            "ev": "drained", "replica": self.replica,
            "migrated_requests": migrated,
            "stats": sched.stats.snapshot(engine),
            "metrics": telemetry.dump_registry(
                engine.telemetry.registry),
        }, verb="drained")
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            c.flush(timeout=max(1.0, budget - (time.monotonic() - t0)))
        self._drained_evt.set()
        self._shutdown.set()
        # The accept loop may sit in a 250 ms timeout; exiting here is
        # the point of a drain — everything worth saving already left.
        os._exit(0)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="tpu_inference engine-worker process (one dp "
                    "replica behind the fleet router; README 'Process "
                    "fleet'). Reads a JSON config envelope from stdin.")
    ap.add_argument("--socket", required=True,
                    help="unix socket path to serve the RPC on")
    ap.add_argument("--replica", type=int, default=0)
    ap.add_argument("--config", default=None,
                    help="config envelope path (default: stdin)")
    args = ap.parse_args()

    if args.config:
        with open(args.config) as f:
            envelope = json.load(f)
    else:
        envelope = json.load(sys.stdin)

    # Platform override BEFORE any computation: this image's
    # sitecustomize points a fresh interpreter at the TPU tunnel, so the
    # router ships its own resolved backend and the worker pins it via
    # jax.config (the conftest/__main__ pattern — env vars are too late).
    import jax

    platform = envelope.get("platform")
    if platform:
        jax.config.update("jax_platforms", platform)
        if platform == "cpu":
            from tpu_inference.compat import set_cpu_device_count
            set_cpu_device_count(max(1, int(envelope.get("cpu_devices",
                                                         1))))

    from tpu_inference.config import framework_config_from_dict

    cfg = framework_config_from_dict(envelope["config"])
    role = envelope.get("role")
    if role:
        # Per-worker phase role (README "P/D disaggregation"): the
        # router resolves ServerConfig.worker_roles and ships THIS
        # worker's entry, folded into the engine config so warmup and
        # the handoff hook specialize.
        import dataclasses

        cfg.engine = dataclasses.replace(cfg.engine, role=role)
    nice = int(envelope.get("nice") or 0)
    if nice and hasattr(os, "nice"):
        # Shared-CPU hosts (README "P/D disaggregation"): the prefill
        # tier self-deprioritizes so decode workers keep their cadence
        # under prefill bursts — on per-chip deployments the isolation
        # is physical and this is a no-op. A refused increment (e.g. a
        # negative value without CAP_SYS_NICE) must NOT crash the
        # worker into a restart loop — priority is an optimization,
        # not a correctness requirement.
        try:
            os.nice(nice)
        except OSError as e:
            print(f"[worker {args.replica}] os.nice({nice}) refused: "
                  f"{e}; serving at current priority", file=sys.stderr)
    worker = EngineWorker(cfg, replica=args.replica,
                          socket_path=args.socket,
                          warmup=bool(envelope.get("warmup", True)))
    # Zero-copy KV plane (README "KV data plane"): the router ships
    # this worker's arena region spec plus the fabric pool's current
    # free-page watermark; both are absent on the relay plane.
    worker.attach_arena(envelope.get("shm"))
    ff = envelope.get("fabric_free")
    if ff is not None:
        worker._fabric_free = int(ff)

    def _sigterm(signum, frame):
        # Signal-handler context: just flag; the drain thread does the
        # blocking work (device sync + socket writes).
        threading.Thread(target=worker.drain,
                         args=(worker.cfg.server.fleet_migrate,),
                         name="worker-drain", daemon=True).start()

    signal.signal(signal.SIGTERM, _sigterm)
    worker.serve()


if __name__ == "__main__":
    main()
