"""Zero-copy KV data plane: the shared-memory page arena.

Every KV movement the fleet performs — P/D handoff, drain migration,
fabric publish/pull, warm boot — used to serialize pages into a blob
and relay it THROUGH the router over the JSON-framed RPC, paying 4+
full copies per transfer. The arena cuts that to one copy: the owning
worker writes the serialized blob into a shared-memory slab once, and
frames carry a compact descriptor ``{seg, rg, off, len, crc, gen, ep}``
instead of the payload. The adopting worker reads the slab directly.

Layout
------
One ``multiprocessing.shared_memory`` segment, created and owned by the
ROUTER (workers attach read/write but never create or unlink), split
into fixed equal regions — one per worker replica. Single-writer
discipline makes the allocator trivial and portable: only region
``rg``'s worker allocates or frees slabs inside region ``rg``; the
router writes nothing but the per-region epoch word.

* Region header: one big-endian u32 EPOCH word at the region base.
  The router bumps it when the region's worker is respawned or
  quarantined — every descriptor minted by the dead incarnation then
  fails closed (``ArenaStale``), which is how in-flight slabs of a
  kill -9'd worker are reclaimed without any cooperation from it.
* Slab: 8-byte header ``[u32 gen][u32 len]`` followed by the payload,
  16-byte aligned extents. ``gen`` is a per-incarnation monotonic
  nonzero counter; ``free()`` zeroes the gen word so a stale
  descriptor read fails closed instead of returning recycled bytes.

Integrity
---------
A read validates epoch word -> slab gen/len -> payload crc32c (the
PR-15 checksum, carried in the descriptor), copies the payload out,
then RE-validates epoch+gen — a slab freed and recycled mid-copy is
detected, never silently adopted. Failures are typed: ``ArenaStale``
(epoch/gen moved — a reclaim or free raced the read; fall back to
recompute/miss) vs ``ArenaCorrupt`` (length/crc mismatch — count it as
an integrity rejection like any corrupt KV blob).

Lifecycle
---------
The ROUTER is the consumer-side authority: it tracks outstanding slabs
in a ``SlabDirectory``, releases them when the pooled/handoff entry is
dropped, and batches the frees back to the owning worker on the
periodic stats RPC (the worker applies them to its allocator). When a
worker dies, the router reclaims the region at respawn/quarantine time
— count the still-registered slabs, drop them, bump the epoch.
"""

from __future__ import annotations

import struct
import sys
import threading
from typing import Dict, List, Optional, Tuple

from tpu_inference.server.transport import crc32c


def effective_kv_plane(server_cfg) -> str:
    """Resolve --kv-plane against reality (the README "KV data plane"
    decision table): shm only helps — and only works — when workers are
    separate OS processes on a host with POSIX shared memory. Anything
    else silently rides the relay plane; the knob is a request, not a
    promise."""
    if getattr(server_cfg, "kv_plane", "relay") != "shm":
        return "relay"
    if getattr(server_cfg, "fleet", "in-process") != "subprocess":
        return "relay"
    if not sys.platform.startswith("linux"):
        return "relay"
    return "shm"

_EPOCH = struct.Struct(">I")
_SLAB = struct.Struct(">II")          # gen, payload length
_ALIGN = 16
# First allocatable byte of a region: the epoch word, padded to one
# alignment unit so slab extents never straddle it.
_REGION_HDR = _ALIGN


class ArenaError(Exception):
    """Base for arena read/alloc failures; carries a short reason."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


class ArenaStale(ArenaError):
    """Epoch or generation moved under the descriptor (free, recycle,
    or a supervisor reclaim) — not corruption; fall back to the relay
    or recompute path."""


class ArenaCorrupt(ArenaError):
    """Length or crc32c mismatch — treat exactly like a corrupt KV
    blob: reject, count, never adopt."""


class ArenaFull(ArenaError):
    """No free extent fits the payload; caller falls back to the
    through-router relay path."""

    def __init__(self, detail: str = ""):
        super().__init__("full", detail)


# Segments THIS process created (ArenaSegment) or already detached:
# attach() must unregister a cross-process mapping from the resource
# tracker exactly once — and never strip the owner's own registration
# (same-process attach happens in tests and the in-process fallback).
_OWNED: set = set()
_DETACHED: set = set()


def attach(name: str):
    """Attach an existing segment WITHOUT adopting ownership: Python
    3.10's SharedMemory registers every mapping with the
    resource_tracker, whose cleanup would unlink the router-owned
    segment when this (worker) process exits — unregister right away."""
    from multiprocessing import shared_memory
    shm = shared_memory.SharedMemory(name=name)
    if name not in _OWNED and name not in _DETACHED:
        _DETACHED.add(name)
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:  # noqa: BLE001 — tracker internals; best effort
            pass
    return shm


def _validate_header(buf, desc, region_bytes: int) -> None:
    off, length = int(desc["off"]), int(desc["len"])
    rg = int(desc["rg"])
    base = rg * region_bytes
    if not (base + _REGION_HDR <= off - _SLAB.size
            and off + length <= base + region_bytes
            and off + length <= len(buf)):
        raise ArenaCorrupt("bounds", f"off={off} len={length} rg={rg}")
    (epoch,) = _EPOCH.unpack_from(buf, base)
    if epoch != int(desc["ep"]):
        raise ArenaStale("epoch", f"region {rg}: {epoch} != {desc['ep']}")
    gen, slab_len = _SLAB.unpack_from(buf, off - _SLAB.size)
    if gen != int(desc["gen"]):
        raise ArenaStale("gen", f"slab@{off}: {gen} != {desc['gen']}")
    if slab_len != length:
        raise ArenaCorrupt("len", f"slab@{off}: {slab_len} != {length}")


def read_slab(buf, desc: dict, region_bytes: int) -> bytes:
    """Validate + copy a slab payload out of the segment. The
    post-copy re-validation closes the torn-read window: the owner may
    free (gen -> 0) or the supervisor reclaim (epoch bump) the slab
    while the copy is in flight — the recycled bytes must never be
    returned as if they were the descriptor's payload."""
    _validate_header(buf, desc, region_bytes)
    off, length = int(desc["off"]), int(desc["len"])
    payload = bytes(buf[off:off + length])
    if crc32c(payload) != int(desc["crc"]):
        raise ArenaCorrupt("crc", f"slab@{off}")
    _validate_header(buf, desc, region_bytes)
    return payload


class RegionWriter:
    """Owner-side slab allocator for ONE region (single writer: the
    worker process assigned to it). First-fit free list with adjacent-
    extent coalescing; per-slab accounting so a leak is visible as
    ``slabs_used`` that never returns to zero."""

    def __init__(self, buf, region: int, region_bytes: int, epoch: int,
                 seg: str):
        self._buf = buf
        self.region = int(region)
        self.region_bytes = int(region_bytes)
        self.epoch = int(epoch)
        self.seg = seg
        base = self.region * self.region_bytes
        self._free: List[Tuple[int, int]] = [
            (base + _REGION_HDR, self.region_bytes - _REGION_HDR)]
        # payload offset -> (gen, extent offset, extent length)
        self._slabs: Dict[int, Tuple[int, int, int]] = {}
        self._gen = 0
        self._lock = threading.Lock()
        self.alloc_failures = 0

    @property
    def slabs_used(self) -> int:
        return len(self._slabs)

    @property
    def bytes_used(self) -> int:
        return sum(ext_len for _, _, ext_len in self._slabs.values())

    def alloc(self, payload: bytes) -> dict:
        """Write one slab; returns the wire descriptor. Raises
        ArenaFull when no extent fits (caller relays instead)."""
        return self.alloc_parts((payload,))

    def alloc_parts(self, parts) -> dict:
        """Write one slab from a sequence of buffers (the serialized
        blob's constituent parts, kv_cache.serialize_host_pages_parts).
        Gather-writing straight into the slab skips the ``b"".join``
        the relay frame needs — the payload is copied exactly once, and
        the descriptor crc is chained across the parts on the way in."""
        length = sum(len(p) for p in parts)
        need = _SLAB.size + length
        need += (-need) % _ALIGN
        with self._lock:
            for i, (off, size) in enumerate(self._free):
                if size >= need:
                    if size == need:
                        self._free.pop(i)
                    else:
                        self._free[i] = (off + need, size - need)
                    self._gen = (self._gen % 0xFFFFFFFE) + 1
                    gen = self._gen
                    _SLAB.pack_into(self._buf, off, gen, length)
                    pay_off = off + _SLAB.size
                    at, crc = pay_off, 0
                    for p in parts:
                        self._buf[at:at + len(p)] = p
                        crc = crc32c(p, crc)
                        at += len(p)
                    self._slabs[pay_off] = (gen, off, need)
                    return {"seg": self.seg, "rg": self.region,
                            "off": pay_off, "len": length,
                            "crc": crc, "gen": gen,
                            "ep": self.epoch}
            self.alloc_failures += 1
            raise ArenaFull(f"{length}B, region {self.region}")

    def free(self, pay_off: int) -> bool:
        """Release a slab by payload offset (idempotent — the router
        may double-free across a reconnect resync). Zeroes the gen
        word first so concurrent readers fail closed."""
        with self._lock:
            slab = self._slabs.pop(int(pay_off), None)
            if slab is None:
                return False
            _, ext_off, ext_len = slab
            _SLAB.pack_into(self._buf, ext_off, 0, 0)
            self._free.append((ext_off, ext_len))
            self._free.sort()
            merged: List[Tuple[int, int]] = []
            for off, size in self._free:
                if merged and merged[-1][0] + merged[-1][1] == off:
                    merged[-1] = (merged[-1][0], merged[-1][1] + size)
                else:
                    merged.append((off, size))
            self._free = merged
            return True


class WorkerArena:
    """Worker-side facade: attach the router's segment once, write
    into THIS worker's region, read any region's slabs. Counts the
    zero-copy plane's traffic for the kv_plane_shm metric family."""

    def __init__(self, spec: dict):
        self.seg = spec["seg"]
        self.region = int(spec["region"])
        self.region_bytes = int(spec["region_bytes"])
        self.shm = attach(self.seg)
        self.writer = RegionWriter(self.shm.buf, self.region,
                                   self.region_bytes, int(spec["epoch"]),
                                   self.seg)
        self.puts = 0
        self.gets = 0
        self.put_bytes = 0
        self.get_bytes = 0

    def publish(self, payload: bytes) -> dict:
        return self.publish_parts((payload,))

    def publish_parts(self, parts) -> dict:
        desc = self.writer.alloc_parts(parts)
        self.puts += 1
        self.put_bytes += desc["len"]
        return desc

    def read(self, desc: dict) -> bytes:
        if desc.get("seg") != self.seg:
            raise ArenaStale("seg", f"{desc.get('seg')} != {self.seg}")
        payload = read_slab(self.shm.buf, desc, self.region_bytes)
        self.gets += 1
        self.get_bytes += len(payload)
        return payload

    def free(self, pay_off: int) -> bool:
        return self.writer.free(pay_off)

    def close(self) -> None:
        try:
            self.shm.close()
        except (OSError, BufferError):
            pass


class ArenaSegment:
    """Router-side owner: creates the segment, assigns regions, bumps
    epochs at reclaim, and unlinks at teardown. The router never
    allocates slabs — it only reads descriptors' geometry and writes
    epoch words."""

    def __init__(self, total_bytes: int, regions: int):
        from multiprocessing import shared_memory
        regions = max(1, int(regions))
        region_bytes = max(_REGION_HDR + _ALIGN,
                           (int(total_bytes) // regions) & ~(_ALIGN - 1))
        self.region_bytes = region_bytes
        self.regions = regions
        self.shm = shared_memory.SharedMemory(
            create=True, size=region_bytes * regions)
        self.name = self.shm.name
        _OWNED.add(self.name)
        for rg in range(regions):
            _EPOCH.pack_into(self.shm.buf, rg * region_bytes, 1)
        self._closed = False

    def region_spec(self, rg: int) -> Optional[dict]:
        """Boot-envelope entry for one worker, or None when the
        replica index is past the region count (autoscaled workers
        beyond the boot-time fleet fall back to the relay plane)."""
        if not (0 <= rg < self.regions) or self._closed:
            return None
        return {"seg": self.name, "region": rg,
                "region_bytes": self.region_bytes,
                "epoch": self.epoch(rg)}

    def epoch(self, rg: int) -> int:
        (ep,) = _EPOCH.unpack_from(self.shm.buf, rg * self.region_bytes)
        return ep

    def bump_epoch(self, rg: int) -> int:
        """Invalidate every outstanding descriptor of region ``rg``
        (dead-incarnation reclaim). Returns the new epoch the fresh
        incarnation will mint descriptors under."""
        ep = (self.epoch(rg) % 0xFFFFFFFE) + 1
        _EPOCH.pack_into(self.shm.buf, rg * self.region_bytes, ep)
        return ep

    def read(self, desc: dict) -> bytes:
        return read_slab(self.shm.buf, desc, self.region_bytes)

    def close(self, unlink: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.shm.close()
        except (OSError, BufferError):
            pass
        if unlink:
            try:
                self.shm.unlink()
            except (OSError, FileNotFoundError):
                pass
        _OWNED.discard(self.name)
        _DETACHED.discard(self.name)


class SlabDirectory:
    """Router-side ledger of outstanding slabs: registered when a
    descriptor arrives (fabric put, handoff, migrate), released when
    its last consumer drops it, drained as per-region free batches for
    the periodic stats RPC, and reclaimed wholesale — with a count —
    when the owning incarnation dies."""

    def __init__(self):
        self._lock = threading.Lock()
        self._live: Dict[Tuple[int, int], dict] = {}
        self._pending: Dict[int, List[int]] = {}
        self.reclaims = 0
        self.released = 0

    @property
    def slabs_live(self) -> int:
        with self._lock:
            return len(self._live)

    @property
    def slabs_tracked(self) -> int:
        """Live + released-but-not-yet-freed (the owner applies frees
        on its next stats tick)."""
        with self._lock:
            return len(self._live) + sum(
                len(v) for v in self._pending.values())

    def register(self, desc: dict) -> None:
        with self._lock:
            self._live[(int(desc["rg"]), int(desc["off"]))] = desc

    def release(self, desc: dict) -> None:
        """Idempotent: only a tracked slab moves to the pending-free
        batch (a double release or a release after reclaim is a
        no-op)."""
        key = (int(desc["rg"]), int(desc["off"]))
        with self._lock:
            if self._live.pop(key, None) is None:
                return
            self._pending.setdefault(key[0], []).append(key[1])
            self.released += 1

    def drain_free(self, rg: int) -> List[int]:
        with self._lock:
            return self._pending.pop(int(rg), [])

    def requeue_free(self, rg: int, offs: List[int]) -> None:
        """Put a drained batch back (the stats RPC that would have
        carried it failed; retry next tick)."""
        if not offs:
            return
        with self._lock:
            self._pending.setdefault(int(rg), []).extend(offs)

    def reclaim(self, rg: int) -> int:
        """Drop everything the dead incarnation owned. The epoch bump
        (ArenaSegment.bump_epoch) makes the dropped descriptors fail
        closed; this just settles the books and reports how many
        slabs the supervisor took back."""
        rg = int(rg)
        with self._lock:
            dead = [k for k in self._live if k[0] == rg]
            for k in dead:
                del self._live[k]
            n = len(dead) + len(self._pending.pop(rg, []))
            self.reclaims += n
            return n
