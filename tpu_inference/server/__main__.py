"""CLI entry point: ``python -m tpu_inference.server --model tiny-llama``.

The reference has no CLI (argparse commented out; reference:
traffic_generator/main.py:4). This is the serve() entry SURVEY.md §3.5
plans for.
"""

from __future__ import annotations

import argparse

from aiohttp import web

from tpu_inference.config import PRESETS


def main() -> None:
    p = argparse.ArgumentParser(description="TPU-native LLM inference server "
                                            "(Ollama-protocol endpoint)")
    p.add_argument("--model", default="tiny-llama",
                   help=f"preset ({', '.join(sorted(PRESETS))}), a HF "
                        "checkpoint dir (config.json read for the "
                        "architecture), or 'auto' with --checkpoint")
    p.add_argument("--tokenizer", default="byte",
                   help="'byte', a local HF tokenizer dir, or 'auto' "
                        "(= the checkpoint dir's tokenizer when present)")
    p.add_argument("--checkpoint", default=None,
                   help="HF safetensors directory (random init if omitted)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=11434)
    from tpu_inference.engine.autosize import int_or_auto

    p.add_argument("--max-batch-size", type=int_or_auto, default=8,
                   help="decode slots in the batched graph, or 'auto': "
                        "size from the chip's HBM after weights "
                        "(engine/autosize.py)")
    p.add_argument("--decode-ladder", default="auto",
                   help="compiled decode-graph batch ladder: 'auto' "
                        "(doubling rungs 8/16/32/... up to max-batch-"
                        "size — with --max-batch-size auto this is the "
                        "HBM-derived ladder), 'off' (one graph at "
                        "max-batch-size, legacy), or explicit comma "
                        "rungs e.g. '8,16,32'. The engine dispatches "
                        "at the smallest rung covering the occupied "
                        "lanes and steps between rungs as occupancy "
                        "changes (README 'Batch ladder')")
    p.add_argument("--ladder-admit-headroom-pages", type=int, default=0,
                   help="batch-ladder admission guard: growing the "
                        "batch past the base rung must leave this many "
                        "reclaimable KV pages spare, so more lanes "
                        "never drain the pool to the preemption "
                        "watermark or churn the hot cache set; 0 = off")
    p.add_argument("--num-pages", type=int_or_auto, default=512,
                   help="KV pool pages, or 'auto': fill the HBM left "
                        "after weights + activation headroom")
    p.add_argument("--target-ctx", type=int, default=0,
                   help="with auto sizing: expected typical context "
                        "tokens per sequence (0 = half the per-sequence "
                        "max); batch = KV tokens / this, capped")
    p.add_argument("--batch-cap", type=int, default=32,
                   help="upper bound for --max-batch-size auto")
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-pages-per-seq", type=int, default=64,
                   help="max context = page-size * this")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel degree (devices in the mesh)")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel degree: sharded-sequence "
                        "prefill over this many devices (long prompts)")
    p.add_argument("--sp-attn", default="ring",
                   choices=("ring", "ulysses"),
                   help="sequence-parallel algorithm: 'ring' (ppermute "
                        "K/V rotation, O((S/n)^2) memory) or 'ulysses' "
                        "(two all-to-alls, balanced causal load; needs "
                        "head counts divisible by tp*sp)")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel replicas: each gets its own tp*sp "
                        "submesh, KV pool and scheduler; requests route "
                        "to the least-loaded replica")
    p.add_argument("--fleet", default="in-process",
                   choices=("in-process", "subprocess"),
                   help="dp fleet backend (README 'Process fleet'): "
                        "'in-process' runs every replica as a thread of "
                        "this server (one process, one GIL, one failure "
                        "domain); 'subprocess' runs a router plus one "
                        "engine-worker OS process per replica over a "
                        "local JSON RPC — worker faults are isolated, "
                        "workers restart with backoff, and graceful "
                        "drains migrate KV pages instead of recomputing")
    p.add_argument("--worker-restart-max", type=int, default=3,
                   help="subprocess fleet: restarts allowed per worker "
                        "(doubling backoff) before it stays down and "
                        "the fleet serves degraded on the survivors")
    p.add_argument("--drain-timeout-s", type=float, default=10.0,
                   help="subprocess fleet: budget a SIGTERM'd worker "
                        "gets to settle dispatches and export KV pages "
                        "before exiting")
    p.add_argument("--no-fleet-migrate", action="store_true",
                   help="subprocess fleet: disable drain-time KV page "
                        "migration (resubmissions re-prefill from "
                        "scratch — the benchmark comparison arm)")
    p.add_argument("--autoscale", action="store_true",
                   help="subprocess fleet: SLO-driven autoscaler "
                        "(README 'Elastic fleet') — spawn a worker when "
                        "pooled p95 TTFT/TPOT breaches --slo-ttft-ms/"
                        "--slo-tpot-ms for a sustained window, drain-"
                        "and-migrate the coldest replica away when "
                        "occupancy stays under the low watermark")
    p.add_argument("--autoscale-min", type=int, default=1,
                   help="autoscaler floor on live replicas")
    p.add_argument("--autoscale-max", type=int, default=0,
                   help="autoscaler ceiling on live replicas "
                        "(0 = dp + 2)")
    p.add_argument("--autoscale-breach-window-s", type=float, default=3.0,
                   help="seconds of continuous p95-over-target before a "
                        "scale-up")
    p.add_argument("--autoscale-cooldown-s", type=float, default=10.0,
                   help="minimum seconds between scale decisions "
                        "(anti-flap hysteresis)")
    p.add_argument("--autoscale-low-watermark", type=float, default=0.25,
                   help="scale down when pooled ladder occupancy stays "
                        "under this (0..1) for the idle window")
    p.add_argument("--autoscale-idle-window-s", type=float, default=5.0,
                   help="seconds of continuous low occupancy before a "
                        "scale-down")
    p.add_argument("--default-class", default="interactive",
                   choices=("interactive", "batch", "background"),
                   help="priority class for requests without an "
                        "X-Priority header (README 'Elastic fleet'): "
                        "interactive lanes preempt batch/background "
                        "ones at the admission watermark instead of "
                        "shedding 429")
    p.add_argument("--class-queue-depth", type=int, default=0,
                   help="per-class deferral queue depth: over the "
                        "admission cap, batch/background requests park "
                        "here (drained as load drops) instead of "
                        "shedding; 0 = legacy single global cap")
    p.add_argument("--role", default="mixed",
                   choices=("prefill", "decode", "mixed"),
                   help="uniform worker phase role (README 'P/D "
                        "disaggregation'): 'prefill' workers serve "
                        "prompt prefills and hand each settled prefill "
                        "(KV pages + stream state) off to a decode "
                        "worker — no re-prefill, byte-identical under "
                        "greedy; 'decode' workers adopt handoffs and "
                        "decode at high occupancy with zero prefill "
                        "interference; 'mixed' (default) runs both "
                        "phases on every worker, unchanged from "
                        "pre-P/D behavior. Needs --fleet subprocess "
                        "when not 'mixed'")
    p.add_argument("--roles", default=None,
                   help="per-worker phase roles, comma-separated, one "
                        "per dp replica (e.g. 'prefill,decode,decode') "
                        "— overrides --role; needs --fleet subprocess")
    p.add_argument("--pd-ratio", default=None,
                   help="size the prefill:decode worker split over dp: "
                        "'P:D' (e.g. '1:3') or 'auto' (split by each "
                        "phase's chip-seconds share from the expected "
                        "prompt/decode token mix — engine/autosize.py "
                        "pd_worker_roles); overrides --role, mutually "
                        "exclusive with --roles; needs --fleet "
                        "subprocess and dp >= 2")
    p.add_argument("--pd-prompt-rate", type=float, default=None,
                   help="with --pd-ratio auto: observed prompt tokens/s "
                        "offered to the fleet (default: the BurstGPT-"
                        "shaped 512-token-prompt mix)")
    p.add_argument("--pd-decode-rate", type=float, default=None,
                   help="with --pd-ratio auto: observed decode tokens/s "
                        "(default: 128-token replies)")
    p.add_argument("--pd-prefill-nice", type=int, default=0,
                   help="os.nice() increment for prefill-role worker "
                        "processes (shared-CPU hosts: keeps decode "
                        "cadence flat under prefill bursts; no-op on "
                        "per-chip deployments or at 0)")
    p.add_argument("--attn-backend", default="auto",
                   choices=("auto", "dense", "pallas"),
                   help="decode attention: Pallas paged kernel (TPU) or "
                        "dense gather; auto = pallas on TPU")
    p.add_argument("--quant", default="none",
                   choices=("none", "int8", "int4"),
                   help="weight quantization: int8 stores matmul weights "
                        "as int8 + per-channel scales (int4: 4-bit + "
                        "group-128 scales, quartering), halving the HBM "
                        "weight traffic that bounds decode throughput")
    p.add_argument("--kv-quant", default="none",
                   choices=("none", "int8", "int4"),
                   help="KV-cache quantization: int8 codes + per-token-"
                        "head scales — halves KV HBM traffic and doubles "
                        "the context a same-sized pool holds; int4 "
                        "nibble-packs (quarter traffic, lossier — int8 "
                        "is the accuracy-safe tier)")
    p.add_argument("--spec-mode", default="auto",
                   choices=("auto", "off", "draft", "ngram"),
                   help="speculative decoding proposal source: 'ngram' "
                        "= draft-free self-drafting (prompt lookup "
                        "against each sequence's own history; no draft "
                        "model, no extra HBM; composes with the decode "
                        "ladder, host KV tier and repeat_penalty); "
                        "'draft' = a separate draft model "
                        "(--draft-model); 'auto' = draft when "
                        "--draft-model is given, else off")
    p.add_argument("--draft-model", default=None,
                   help="enable draft-model speculative decoding with "
                        "this draft preset or HF checkpoint dir")
    p.add_argument("--draft-checkpoint", default=None,
                   help="HF safetensors dir for the draft model (required "
                        "when --checkpoint is set)")
    p.add_argument("--num-speculative-tokens", type=int, default=4,
                   help="speculation depth γ: proposed tokens verified "
                        "per round (each round emits 1..γ+1 tokens from "
                        "one target forward); [1, 16] when spec is on")
    p.add_argument("--ngram-window", type=int, default=3,
                   help="ngram spec: longest suffix n-gram matched "
                        "against the sequence's history ([1, 8]; "
                        "matching tries window..1, most recent match "
                        "wins)")
    p.add_argument("--decode-pipeline-depth", type=int, default=1,
                   help=">1 keeps that many fused-decode dispatches in "
                        "flight (hides dispatch latency; adds (depth-1)*K "
                        "steps of streaming latency)")
    p.add_argument("--chunked-prefill-size", type=int, default=0,
                   help="split multi-chunk prompts into chunks of this "
                        "many tokens (0 = the largest prefill bucket); "
                        "smaller chunks interleave/fuse with decode at "
                        "a finer grain")
    p.add_argument("--hybrid-prefill", action="store_true",
                   help="fuse each chunk of a multi-chunk prompt's "
                        "prefill into the decode dispatch (Sarathi-style "
                        "piggybacking): running lanes keep producing "
                        "tokens instead of stalling a chunk wall per "
                        "chunk; greedy outputs stay byte-identical")
    p.add_argument("--step-token-budget", type=int, default=0,
                   help="with --hybrid-prefill: per-fused-step token "
                        "budget — chunk tokens are capped at budget minus "
                        "the granted decode tokens (floor: page-size), "
                        "bounding the prefill compute added to any one "
                        "decode dispatch; 0 = uncapped")
    p.add_argument("--platform", default="auto",
                   choices=("auto", "cpu", "tpu"),
                   help="jax platform: 'cpu' forces the CPU backend "
                        "(with --cpu-devices virtual devices) before any "
                        "computation — serve without TPU hardware or "
                        "when the TPU tunnel is down; 'auto' uses the "
                        "environment default")
    p.add_argument("--cpu-devices", type=int, default=0,
                   help="with --platform cpu: number of virtual CPU "
                        "devices (0 = max(1, dp*tp*sp), enough for the "
                        "requested mesh)")
    p.add_argument("--step-watchdog-s", type=float, default=0.0,
                   help="quarantine a replica whose prefill/decode "
                        "dispatch stays in flight this long (the wedged-"
                        "TPU failure mode); 0 = off. Use with --no-warmup "
                        "cautiously: the first dispatch includes XLA "
                        "compile")
    p.add_argument("--quarantine-after", type=int, default=3,
                   help="consecutive step failures before a replica is "
                        "quarantined (first failure marks it degraded)")
    p.add_argument("--quarantine-cooldown-s", type=float, default=30.0,
                   help="quarantined replicas re-enter (probation) after "
                        "this long; one clean step re-promotes, one "
                        "failure re-quarantines")
    p.add_argument("--failover-retries", type=int, default=1,
                   help="resubmit a request failed/stranded by a sick "
                        "replica (before any token streamed) to a "
                        "healthy one at most this many times")
    p.add_argument("--routing", default="prefix_affinity",
                   choices=("prefix_affinity", "least_loaded"),
                   help="dp replica routing: 'prefix_affinity' scores "
                        "replicas by expected re-prefill pages (prompt "
                        "pages minus a prefix-cache peek) blended with "
                        "load/pressure so returning conversations land "
                        "on the replica holding their KV pages; "
                        "'least_loaded' is the legacy load-only policy")
    p.add_argument("--route-hit-weight", type=float, default=1.0,
                   help="prefix-affinity: pages of prefill work one "
                        "peeked cache-hit page is worth in the routing "
                        "score (1.0 = at cost; larger lets warmth "
                        "outbid queue depth and preemption pressure)")
    p.add_argument("--route-host-hit-weight", type=float, default=0.5,
                   help="prefix-affinity: pages of prefill work one "
                        "HOST-tier hit page is worth (three "
                        "temperatures: HBM-warm > host-warm > cold — a "
                        "host page saves the compute but still pays a "
                        "host->device swap-in; 0 ignores host warmth)")
    p.add_argument("--host-cache-pages", type=int_or_auto, default="auto",
                   help="host-RAM KV tier capacity in pages: evicted "
                        "prefix-cache pages demote to host memory and "
                        "swap back in on reuse instead of re-prefilling "
                        "(README 'Tiered KV cache'); 0 = off, 'auto' "
                        "(default) = size from available RAM "
                        "(/proc/meminfo MemAvailable; capacity is a "
                        "cap — RAM is consumed only as pages demote)")
    p.add_argument("--fabric-cache-pages", type=int, default=0,
                   help="fleet KV fabric: router-side shared pool "
                        "capacity in pages (README 'KV fabric'); "
                        "settled prefix pages published by any replica "
                        "warm prefills on EVERY replica, and autoscaled "
                        "workers boot warm from the pool; 0 = off")
    p.add_argument("--fabric-publish-min-pages", type=int, default=1,
                   help="fleet KV fabric: publish a prefix to the pool "
                        "only once at least this many settled pages are "
                        "available (filters short one-off prompts)")
    p.add_argument("--fabric-warmboot-pages", type=int, default=64,
                   help="fleet KV fabric: push up to this many MRU pool "
                        "pages into a newly spawned worker BEFORE it "
                        "becomes routable (warm boot for autoscale "
                        "scale-ups, restarts, and rollouts); 0 = off")
    p.add_argument("--kv-plane", default="relay",
                   choices=("relay", "shm"),
                   help="KV data plane (README 'KV data plane'): how KV "
                        "payloads (fabric publishes, P/D handoffs, drain "
                        "migrations) move between processes. 'relay' = "
                        "blobs ride the RPC sockets through the router "
                        "(default, works everywhere); 'shm' = payloads "
                        "go into a shared-memory page arena and only "
                        "descriptors cross the sockets (zero-copy; "
                        "needs --fleet subprocess on Linux, silently "
                        "falls back to relay otherwise)")
    p.add_argument("--shm-arena-bytes", type=int,
                   default=256 * 1024 * 1024,
                   help="--kv-plane shm: total shared-memory arena size "
                        "in bytes, split into one single-writer region "
                        "per worker (default 256 MiB)")
    p.add_argument("--route-fabric-hit-weight", type=float, default=0.25,
                   help="prefix-affinity: pages of prefill work one "
                        "fabric-pool hit page is worth (fourth "
                        "temperature: HBM-warm > host-warm > "
                        "fabric-warm > cold — a fabric page saves the "
                        "compute but pays deserialize + host->device "
                        "swap-in; 0 ignores fabric warmth)")
    p.add_argument("--admission-queue-depth", type=int, default=0,
                   help="shed load (429 + Retry-After) when every "
                        "routable replica has this many requests queued "
                        "or running; 0 = queue without bound (legacy)")
    p.add_argument("--admission", default="reserve",
                   choices=("reserve", "optimistic"),
                   help="KV admission mode: 'reserve' charges each "
                        "request prompt+max_new worst case (OOM-free, "
                        "strands pool under bursty traffic); "
                        "'optimistic' charges prompt+headroom and "
                        "preempts/recompute-resumes on exhaustion "
                        "(token-identical under greedy decoding)")
    p.add_argument("--optimistic-headroom-pages", type=int, default=2,
                   help="optimistic admission: decode-headroom pages "
                        "charged per request on top of its prompt")
    p.add_argument("--preempt-watermark-pages", type=int, default=4,
                   help="preempt the most-recently-admitted sequences "
                        "when a decode grant comes up short and "
                        "free+evictable pages fall below this")
    p.add_argument("--preempt-max-per-request", type=int, default=3,
                   help="starvation guard: after this many preemptions "
                        "a request re-admits under full worst-case "
                        "reservation (and is never preempted again)")
    p.add_argument("--chaos-page-pressure", type=int, default=0,
                   help="fault injection: hold this many KV pages out "
                        "of the pool at boot (deterministic exhaustion "
                        "testing; adjustable via POST /debug/chaos)")
    p.add_argument("--chaos-failure-rate", type=float, default=0.0,
                   help="HTTP fault injection: 503 this fraction of "
                        "generate/chat/embed requests (harness testing)")
    p.add_argument("--chaos-delay-s", type=float, default=0.0,
                   help="HTTP fault injection: delay requests uniformly "
                        "up to this many seconds")
    p.add_argument("--chaos-step-failure-rate", type=float, default=0.0,
                   help="engine fault injection: each prefill/decode "
                        "dispatch raises with this probability "
                        "(exercises quarantine + failover end to end)")
    p.add_argument("--chaos-step-wedge-s", type=float, default=0.0,
                   help="engine fault injection: each dispatch sleeps "
                        "this long first (exercises the step watchdog)")
    p.add_argument("--chaos-rpc-seed", type=int, default=0,
                   help="transport fault injection: deterministic seed "
                        "for the frame-level fault schedule (same seed "
                        "=> same faults at the same frame indices)")
    p.add_argument("--chaos-rpc-corrupt-rate", type=float, default=0.0,
                   help="transport fault injection: flip one byte in "
                        "this fraction of RPC frames (CRC rejects them; "
                        "exercises reconnect + resync)")
    p.add_argument("--chaos-rpc-drop-rate", type=float, default=0.0,
                   help="transport fault injection: reset the "
                        "connection instead of sending this fraction "
                        "of frames")
    p.add_argument("--chaos-rpc-delay-rate", type=float, default=0.0,
                   help="transport fault injection: delay this "
                        "fraction of frames by --chaos-rpc-delay-s")
    p.add_argument("--chaos-rpc-delay-s", type=float, default=0.02,
                   help="transport fault injection: per-delayed-frame "
                        "sleep (seconds)")
    p.add_argument("--chaos-rpc-truncate-rate", type=float, default=0.0,
                   help="transport fault injection: torn write — send "
                        "a prefix of the frame, then reset")
    p.add_argument("--chaos-rpc-wedge-after", type=int, default=0,
                   help="transport fault injection: after this many "
                        "matching frames, the connection silently "
                        "swallows ALL traffic until the deadline "
                        "watchdog recycles it (0 = off; one-shot)")
    p.add_argument("--chaos-rpc-wedge-replica", type=int, default=0,
                   help="replica whose router connection arms the "
                        "wedge (with --chaos-rpc-wedge-after)")
    p.add_argument("--chaos-rpc-verbs", default="",
                   help="comma-separated RPC verbs the transport chaos "
                        "applies to ('' = every verb)")
    p.add_argument("--chaos-rpc-direction", default="both",
                   choices=("send", "recv", "both"),
                   help="which direction transport chaos applies to: "
                        "send = router->worker frames, recv = "
                        "worker->router frames")
    p.add_argument("--rpc-deadline-fast-s", type=float, default=10.0,
                   help="deadline for control-plane RPCs (cancel, "
                        "chaos, healthz, ...); timeouts emit "
                        "structured rpc_timeout events and three "
                        "consecutive ones recycle the connection")
    p.add_argument("--rpc-deadline-slow-s", type=float, default=60.0,
                   help="deadline for data-plane RPCs that move KV "
                        "bytes or block on admission (submit, "
                        "import-kv, drain)")
    p.add_argument("--poison-max-workers", type=int, default=3,
                   help="quarantine a request as poison (terminal 500) "
                        "once its attempts have crashed or wedged this "
                        "many DISTINCT workers (0 disables)")
    p.add_argument("--slo-ttft-ms", type=float, default=0.0,
                   help="rolling SLO target for time-to-first-token "
                        "(ms): requests past it count into "
                        "tpu_inf_slo_breaches_total{slo=\"ttft\"}; the "
                        "windowed p50/p95 gauges export regardless. "
                        "0 = no target")
    p.add_argument("--slo-tpot-ms", type=float, default=0.0,
                   help="rolling SLO target for time-per-output-token "
                        "(ms): breaches count into "
                        "tpu_inf_slo_breaches_total{slo=\"tpot\"}; "
                        "0 = no target")
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--step-ledger-depth", type=int, default=256,
                   help="per-replica step-ledger ring depth (per-"
                        "dispatch records behind GET /debug/steps and "
                        "the flight recorder; floor 8)")
    p.add_argument("--blackbox-dir", default="/tmp/tpu-inf-blackbox",
                   help="crash flight-recorder root (per-replica "
                        "capture dirs survive kill -9; '' disables). "
                        "Operator-chosen — clients never name capture "
                        "paths")
    p.add_argument("--blackbox-retain", type=int, default=8,
                   help="flight-recorder retention cap: newest N "
                        "trigger captures kept per replica")
    p.add_argument("--debug", action="store_true",
                   help="expose the unauthenticated /debug/* endpoints "
                        "(request timelines, profiler control)")
    p.add_argument("--check-numerics", action="store_true",
                   help="verify params are finite + run a checkify'd "
                        "forward before serving (catches corrupt "
                        "checkpoints)")
    p.add_argument("--debug-nans", action="store_true",
                   help="enable jax_debug_nans: any NaN-producing op "
                        "re-runs un-jitted and raises at the source")
    args = p.parse_args()

    if args.platform != "auto":
        # Must land before jax initializes a backend: env vars are read
        # at (sitecustomize-time) import in this image, so jax.config is
        # the only working override (same pattern as tests/conftest.py
        # and __graft_entry__.dryrun_multichip).
        import jax

        jax.config.update("jax_platforms", args.platform)
        if args.platform == "cpu":
            from tpu_inference.compat import set_cpu_device_count

            n = args.cpu_devices or max(1, args.dp * args.tp * args.sp)
            set_cpu_device_count(n)

    if args.debug_nans:
        import jax

        jax.config.update("jax_debug_nans", True)

    from tpu_inference.config import validate_spec_config

    spec_mode = args.spec_mode
    if spec_mode == "auto":
        spec_mode = "draft" if args.draft_model else "off"
    if spec_mode == "draft" and not args.draft_model:
        p.error("--spec-mode draft requires --draft-model")
    if spec_mode == "off" and args.draft_model:
        p.error("--spec-mode off conflicts with --draft-model "
                "(drop one)")
    if spec_mode != "off":
        try:
            validate_spec_config(spec_mode, args.num_speculative_tokens,
                                 args.ngram_window,
                                 has_draft_model=bool(args.draft_model))
        except ValueError as e:
            p.error(str(e))
    if args.fleet == "subprocess" and args.draft_model:
        p.error("--fleet subprocess does not support --draft-model "
                "(workers boot their own params; use --spec-mode ngram "
                "or the in-process fleet)")
    if args.autoscale and args.fleet != "subprocess":
        p.error("--autoscale needs --fleet subprocess (scaling spawns "
                "and drains worker processes)")
    if args.autoscale and not (args.slo_ttft_ms or args.slo_tpot_ms):
        p.error("--autoscale needs an SLO target to scale on: set "
                "--slo-ttft-ms and/or --slo-tpot-ms")

    # P/D disaggregation (README "P/D disaggregation"): resolve the
    # per-worker role tuple from --roles > --pd-ratio > --role before
    # any model loads, so a bad split is a usage error in milliseconds.
    if args.roles and args.pd_ratio:
        p.error("--roles and --pd-ratio both name the worker split; "
                "pick one")
    from tpu_inference.config import resolve_worker_roles

    worker_roles: tuple = ()
    try:
        if args.roles:
            worker_roles = resolve_worker_roles(
                args.dp, tuple(r.strip() for r in args.roles.split(",")))
        elif args.pd_ratio:
            from tpu_inference.engine.autosize import pd_worker_roles

            worker_roles = pd_worker_roles(
                args.dp, args.pd_ratio,
                prompt_token_rate=args.pd_prompt_rate,
                decode_token_rate=args.pd_decode_rate)
        elif args.role != "mixed":
            worker_roles = resolve_worker_roles(
                args.dp, (), default_role=args.role)
    except ValueError as e:
        p.error(str(e))
    if any(r != "mixed" for r in worker_roles):
        if args.fleet != "subprocess":
            p.error("--role/--roles/--pd-ratio need --fleet subprocess "
                    "(the live KV handoff moves pages between worker "
                    "processes)")
        import sys

        print(f"[pd] worker roles: {list(worker_roles)}",
              file=sys.stderr)

    from tpu_inference.engine.autosize import resolve_sizing_args

    max_batch_size, num_pages = resolve_sizing_args(args)

    from tpu_inference.engine.autosize import parse_decode_ladder

    try:
        decode_ladder = parse_decode_ladder(args.decode_ladder,
                                            max_batch_size)
    except ValueError as e:
        p.error(str(e))
    if len(decode_ladder) > 1:
        import sys

        print(f"[autosize] decode ladder: {list(decode_ladder)} "
              f"(graph per rung, top = max_batch_size)", file=sys.stderr)

    host_cache_pages = args.host_cache_pages
    if host_cache_pages == "auto":
        from tpu_inference.engine.autosize import (
            auto_host_cache_pages, resolve_model_config)

        # Every dp replica builds its OWN host pool from this one
        # EngineConfig — divide the machine budget so the fleet's tiers
        # together stay inside available RAM.
        host_cache_pages = auto_host_cache_pages(
            resolve_model_config(args.model, args.checkpoint),
            kv_quant=args.kv_quant,
            page_size=args.page_size) // max(1, args.dp)
        import sys

        print(f"[autosize] host KV tier: {host_cache_pages} pages/replica "
              f"(from /proc/meminfo MemAvailable, dp={args.dp})",
              file=sys.stderr)

    from tpu_inference.server.http import build_server

    server = build_server(model=args.model, tokenizer=args.tokenizer,
                          checkpoint=args.checkpoint,
                          warmup=not args.no_warmup, tp=args.tp, sp=args.sp,
                          dp=args.dp,
                          draft_model=args.draft_model,
                          draft_checkpoint=args.draft_checkpoint,
                          enable_debug=args.debug,
                          server_overrides=dict(
                              routing=args.routing,
                              route_hit_weight=args.route_hit_weight,
                              route_host_hit_weight=(
                                  args.route_host_hit_weight),
                              fabric_cache_pages=args.fabric_cache_pages,
                              fabric_publish_min_pages=(
                                  args.fabric_publish_min_pages),
                              fabric_warmboot_pages=(
                                  args.fabric_warmboot_pages),
                              route_fabric_hit_weight=(
                                  args.route_fabric_hit_weight),
                              fleet=args.fleet,
                              kv_plane=args.kv_plane,
                              shm_arena_bytes=args.shm_arena_bytes,
                              worker_roles=worker_roles,
                              pd_prefill_nice=args.pd_prefill_nice,
                              worker_restart_max=args.worker_restart_max,
                              drain_timeout_s=args.drain_timeout_s,
                              fleet_migrate=not args.no_fleet_migrate,
                              autoscale=args.autoscale,
                              autoscale_min_replicas=args.autoscale_min,
                              autoscale_max_replicas=args.autoscale_max,
                              autoscale_breach_window_s=(
                                  args.autoscale_breach_window_s),
                              autoscale_cooldown_s=args.autoscale_cooldown_s,
                              autoscale_low_watermark=(
                                  args.autoscale_low_watermark),
                              autoscale_idle_window_s=(
                                  args.autoscale_idle_window_s),
                              default_class=args.default_class,
                              class_queue_depth=args.class_queue_depth,
                              step_watchdog_s=args.step_watchdog_s,
                              quarantine_after_failures=args.quarantine_after,
                              quarantine_cooldown_s=args.quarantine_cooldown_s,
                              failover_max_retries=args.failover_retries,
                              admission_queue_depth=args.admission_queue_depth,
                              chaos_failure_rate=args.chaos_failure_rate,
                              chaos_delay_s=args.chaos_delay_s,
                              chaos_rpc_seed=args.chaos_rpc_seed,
                              chaos_rpc_corrupt_rate=(
                                  args.chaos_rpc_corrupt_rate),
                              chaos_rpc_drop_rate=args.chaos_rpc_drop_rate,
                              chaos_rpc_delay_rate=(
                                  args.chaos_rpc_delay_rate),
                              chaos_rpc_delay_s=args.chaos_rpc_delay_s,
                              chaos_rpc_truncate_rate=(
                                  args.chaos_rpc_truncate_rate),
                              chaos_rpc_wedge_after=(
                                  args.chaos_rpc_wedge_after),
                              chaos_rpc_wedge_replica=(
                                  args.chaos_rpc_wedge_replica),
                              chaos_rpc_verbs=tuple(
                                  v for v in
                                  args.chaos_rpc_verbs.split(",") if v),
                              chaos_rpc_direction=args.chaos_rpc_direction,
                              rpc_deadline_fast_s=args.rpc_deadline_fast_s,
                              rpc_deadline_slow_s=args.rpc_deadline_slow_s,
                              poison_max_workers=args.poison_max_workers,
                              blackbox_dir=args.blackbox_dir,
                              blackbox_retain=args.blackbox_retain),
                          step_ledger_depth=args.step_ledger_depth,
                          chaos_step_failure_rate=args.chaos_step_failure_rate,
                          chaos_step_wedge_s=args.chaos_step_wedge_s,
                          chaos_page_pressure=args.chaos_page_pressure,
                          admission=args.admission,
                          optimistic_headroom_pages=(
                              args.optimistic_headroom_pages),
                          preempt_watermark_pages=args.preempt_watermark_pages,
                          preempt_max_per_request=args.preempt_max_per_request,
                          attn_backend=args.attn_backend,
                          sp_attn=args.sp_attn,
                          quant=args.quant, kv_quant=args.kv_quant,
                          max_batch_size=max_batch_size,
                          decode_ladder=decode_ladder,
                          ladder_admit_headroom_pages=(
                              args.ladder_admit_headroom_pages),
                          host_cache_pages=host_cache_pages,
                          slo_ttft_ms=args.slo_ttft_ms,
                          slo_tpot_ms=args.slo_tpot_ms,
                          num_pages=num_pages, page_size=args.page_size,
                          max_pages_per_seq=args.max_pages_per_seq,
                          decode_pipeline_depth=args.decode_pipeline_depth,
                          chunked_prefill_size=args.chunked_prefill_size,
                          hybrid_prefill=args.hybrid_prefill,
                          step_token_budget=args.step_token_budget,
                          spec_mode=("ngram" if spec_mode == "ngram"
                                     else "draft"),
                          ngram_window=args.ngram_window,
                          num_speculative_tokens=(
                              args.num_speculative_tokens
                              if spec_mode != "off" else 0))
    if args.check_numerics:
        if args.fleet == "subprocess":
            p.error("--check-numerics needs the in-process fleet "
                    "(workers own their params)")
        for eng in server.group.engines:
            eng.check_numerics()
        print("numerics check passed: params finite, forward NaN-free")
    app = server.make_app()
    web.run_app(app, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
