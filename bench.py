"""Benchmark: batched decode throughput through the serving engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: tokens/s of continuous-batching decode (batch=8) on a 1B-class
Llama-shape model (TinyLlama-1.1B dims) with the paged KV cache — the
engine's steady-state serving path. Baseline: the only decode-rate number
recorded anywhere in the reference, Ollama serving `mistral` on the
reference author's host at ~93 tok/s single-stream (BASELINE.md,
reference notebooks/aiohttp_tracing.ipynb cell e01c6727 output).

On non-TPU platforms (driver smoke runs) the model drops to test scale so
the script stays fast; `vs_baseline` is only meaningful on TPU.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_inference.config import EngineConfig, ModelConfig, tiny_llama
from tpu_inference.engine.engine import InferenceEngine, Sequence

BASELINE_TOK_S = 93.0  # BASELINE.md: reference-side Ollama decode rate


def bench_cfg(platform: str) -> ModelConfig:
    if platform != "tpu":
        return tiny_llama()
    return ModelConfig(
        name="llama-1b-bench", family="llama", vocab_size=32000, d_model=2048,
        n_layers=22, n_heads=32, n_kv_heads=4, d_ff=5632, max_seq_len=2048,
        rope_theta=10000.0, dtype=jnp.bfloat16,
    )


def main() -> None:
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    cfg = bench_cfg(platform)
    batch = 8
    prompt_len = 120
    k = 8                                    # fused decode steps per dispatch
    timed_calls = 32 if on_tpu else 2
    ramp_calls = 2
    budget = (timed_calls + ramp_calls + 1) * k
    ecfg = EngineConfig(page_size=16, num_pages=512, max_pages_per_seq=32,
                        max_batch_size=batch, prefill_buckets=(128,),
                        decode_steps_per_call=k, max_new_tokens=budget)
    print(f"[bench] platform={platform} model={cfg.name}", file=sys.stderr)
    engine = InferenceEngine(cfg, ecfg)
    t = engine.warmup()
    print(f"[bench] warmup (XLA compile) {t:.1f}s", file=sys.stderr)

    rng = np.random.default_rng(0)
    for i in range(batch):
        seq = Sequence(request_id=i,
                       prompt_tokens=rng.integers(
                           1, cfg.vocab_size, prompt_len).tolist(),
                       max_new_tokens=budget)
        engine.prefill(seq)

    # Timed steady-state decode: full batch, k fused steps per dispatch.
    for _ in range(ramp_calls):              # un-timed ramp
        engine.decode_steps()
    jax.block_until_ready(engine.kv.k)
    t0 = time.perf_counter()
    produced = 0
    for _ in range(timed_calls):
        produced += sum(len(t) for t in engine.decode_steps().values())
    jax.block_until_ready(engine.kv.k)
    dt = time.perf_counter() - t0

    tok_s = produced / dt
    print(json.dumps({
        "metric": "decode_tok_s_llama1b_bs8_paged",
        "value": round(tok_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(tok_s / BASELINE_TOK_S, 3),
    }))


if __name__ == "__main__":
    main()
