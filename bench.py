"""Benchmark: batched decode throughput through the serving engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Headline metric: aggregate tokens/s of continuous-batching decode (batch=8)
on a 1B-class Llama-shape model (TinyLlama-1.1B dims) with the paged KV
cache and the **Pallas paged-attention kernel** — the engine's steady-state
serving path on TPU. The dense gather backend is timed too and reported as
``dense_tok_s`` so the kernel's delta is visible (ADVICE.md r2: name the
backend in the metric).

Baseline: the only decode-rate number recorded anywhere in the reference,
Ollama serving `mistral` at ~93 tok/s **single-stream** (BASELINE.md,
reference notebooks/aiohttp_tracing.ipynb cell e01c6727 output).
``vs_baseline`` compares like-for-like per-stream rate against it;
the aggregate ratio is reported separately as ``vs_baseline_aggregate``.

Extras: ``mfu`` and ``hbm_util`` situate the number against chip peaks
(v5e: 394 bf16 TFLOP/s, 819 GB/s HBM) — decode at small batch is HBM-bound,
so ``hbm_util`` is the honest utilization figure.

On non-TPU platforms (driver smoke runs) the model drops to test scale so
the script stays fast; ratios are only meaningful on TPU. Transient TPU
runtime failures (tunnel dial) are retried with backoff before giving up
with a parseable {"error": ...} line on stdout and rc=1.
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time
import traceback

BASELINE_TOK_S = 93.0  # BASELINE.md: reference-side Ollama single-stream rate


def _r(x, nd=2):
    return round(x, nd) if x is not None else None


def _ratio(a, b, nd=3):
    return round(a / b, nd) if a is not None and b else None

# Per-chip peaks for utilization reporting (bf16 FLOP/s, HBM bytes/s)
# and HBM capacity (bytes) for fits-on-chip gating.
CHIP_PEAKS = {
    "TPU v5 lite": (394e12, 819e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),
}
CHIP_HBM_BYTES = {
    "TPU v5 lite": 16e9,
    "TPU v4": 32e9,
    "TPU v5p": 95e9,
    "TPU v6 lite": 32e9,
}


def bench_cfg(platform: str):
    import jax.numpy as jnp
    from tpu_inference.config import ModelConfig, tiny_llama

    if platform != "tpu":
        return tiny_llama()
    if os.environ.get("BENCH_MODEL") == "8b":
        # Llama-3-8B dims. bf16 weights (16 GB) don't fit one v5e chip,
        # so this lane is int8-only (run_backend skips the bf16 lanes
        # when the bf16 model exceeds HBM); opt-in via BENCH_MODEL=8b.
        return ModelConfig(
            name="llama-8b-bench", family="llama", vocab_size=128256,
            d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            d_ff=14336, max_seq_len=2048, rope_theta=500000.0,
            dtype=jnp.bfloat16,
        )
    return ModelConfig(
        name="llama-1b-bench", family="llama", vocab_size=32000, d_model=2048,
        n_layers=22, n_heads=32, n_kv_heads=4, d_ff=5632, max_seq_len=2048,
        rope_theta=10000.0, dtype=jnp.bfloat16,
    )


def run_backend(backend: str, cfg, on_tpu: bool, quant: str = "none"):
    """Time steady-state batched decode for one attention backend.

    Returns (sync tok/s, chained tok/s, model param count, weight bytes
    actually resident (int8 shrinks this), mean context length, first 8
    greedy tokens of lane 0 for cross-backend equality).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_inference.config import EngineConfig
    from tpu_inference.engine.engine import InferenceEngine, Sequence

    batch = 8
    prompt_len = 120
    k = 8                                    # fused decode steps per dispatch
    timed_calls = 32 if on_tpu else 2
    ramp_calls = 2
    budget = (timed_calls + ramp_calls + 1) * k
    ecfg = EngineConfig(page_size=16, num_pages=512, max_pages_per_seq=32,
                        max_batch_size=batch, prefill_buckets=(128,),
                        decode_steps_per_call=k, max_new_tokens=budget,
                        attn_backend=backend, quant=quant)
    engine = InferenceEngine(cfg, ecfg)
    t = engine.warmup()
    print(f"[bench] {backend}/{quant}: warmup (XLA compile) {t:.1f}s",
          file=sys.stderr)

    rng = np.random.default_rng(0)
    for i in range(batch):
        seq = Sequence(request_id=i,
                       prompt_tokens=rng.integers(
                           1, cfg.vocab_size, prompt_len).tolist(),
                       max_new_tokens=budget)
        engine.prefill(seq)

    # Timed steady-state decode, both serving modes:
    # sync = one host round trip per K-step call (streaming loop);
    # chained = dispatch-ahead, device-chained carry tokens, one sync.
    for _ in range(ramp_calls):              # un-timed ramp
        engine.decode_steps()
    jax.block_until_ready(engine.kv.k)
    t0 = time.perf_counter()
    produced = 0
    for _ in range(timed_calls // 2):
        produced += sum(len(t) for t in engine.decode_steps().values())
    jax.block_until_ready(engine.kv.k)
    sync_tok_s = produced / (time.perf_counter() - t0)

    t0 = time.perf_counter()
    out = engine.decode_steps_chained(timed_calls // 2)
    produced_c = sum(len(t) for t in out.values())
    chained_tok_s = produced_c / (time.perf_counter() - t0)

    mean_ctx = float(np.mean([s.ctx_len for s in engine.slots
                              if s is not None]))
    head = list(engine.slots[0].generated[:8])
    n_params = engine.n_params
    weight_bytes = int(sum(x.size * x.dtype.itemsize
                           for x in jax.tree.leaves(engine.params)))
    # Free HBM before the next backend's engine materializes.
    del engine
    gc.collect()
    return sync_tok_s, chained_tok_s, n_params, weight_bytes, mean_ctx, head


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    cfg = bench_cfg(platform)
    print(f"[bench] platform={platform} model={cfg.name}", file=sys.stderr)

    # bf16 lanes only when the bf16 weights actually fit the chip
    # (BENCH_MODEL=8b is int8-only on a 16 GB v5e).
    d, f, L, V = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab_size
    kv_w = cfg.n_kv_heads * cfg.head_dim
    est_params = (V * d * (1 if cfg.tie_embeddings else 2)
                  + L * (2 * d * d + 2 * d * kv_w + 3 * d * f))
    hbm = CHIP_HBM_BYTES.get(jax.devices()[0].device_kind, 16e9)
    # ~0.9 usable after runtime reservations; bf16 lanes need weights
    # plus KV pool + activations headroom.
    bf16_fits = (not on_tpu) or 2 * est_params < 0.85 * hbm
    if bf16_fits:
        dense_tok_s, dense_chained, _, _, _, dense_head = run_backend(
            "dense", cfg, on_tpu)
        (pallas_tok_s, pallas_chained, n_params, weight_bytes, mean_ctx,
         pallas_head) = run_backend("pallas", cfg, on_tpu)
        if dense_head != pallas_head:
            # Greedy sampling: any drift is a correctness signal, not noise.
            print(f"[bench] WARNING: backend token mismatch "
                  f"dense={dense_head} pallas={pallas_head}", file=sys.stderr)
    else:
        print(f"[bench] {cfg.name}: bf16 (~{2 * est_params / 1e9:.0f} GB) "
              "exceeds HBM; int8 lane only", file=sys.stderr)
        dense_tok_s = dense_chained = pallas_tok_s = pallas_chained = None
        dense_head = pallas_head = None
    # Weight-only int8 (models/quant.py): halves the HBM weight read that
    # bounds decode. Tokens legitimately differ from bf16 (quantization),
    # so no equality check — test_quant.py pins the error envelope.
    (int8_tok_s, int8_chained, n_params_q, int8_weight_bytes, mean_ctx_q,
     _) = run_backend("pallas", cfg, on_tpu, quant="int8")
    if not bf16_fits:
        n_params, mean_ctx = n_params_q, mean_ctx_q
        weight_bytes = 2 * n_params

    batch = 8
    flops_per_token = 2 * n_params
    kv_bytes_per_token = (2 * 2 * cfg.n_layers * mean_ctx
                          * cfg.n_kv_heads * cfg.head_dim)  # K+V, bf16
    peak_flops, peak_bw = CHIP_PEAKS.get(
        jax.devices()[0].device_kind, (394e12, 819e9))

    def util(tok_s, wbytes):
        steps_per_s = tok_s / batch
        bw = steps_per_s * (wbytes + batch * kv_bytes_per_token)
        return (round(tok_s * flops_per_token / peak_flops, 4),
                round(bw / peak_bw, 4))

    best_bf16 = max(pallas_tok_s, pallas_chained) if bf16_fits else 0.0
    best_int8 = max(int8_tok_s, int8_chained)
    best = max(best_bf16, best_int8)
    wbytes = int8_weight_bytes if best_int8 >= best_bf16 else weight_bytes
    quant_tag = "int8" if best_int8 >= best_bf16 else "bf16"
    chained_best = max([c for c in (pallas_chained, int8_chained)
                        if c is not None])
    sync_best = max([c for c in (pallas_tok_s, int8_tok_s)
                     if c is not None])
    mode = "dispatch-ahead" if chained_best >= sync_best else "sync"
    mfu, hbm_util = util(best, wbytes)
    mfu_bf16, hbm_util_bf16 = (util(best_bf16, weight_bytes)
                               if bf16_fits else (None, None))
    print(json.dumps({
        # Name stays stable across rounds (BENCH_r{N}.json diffs by key);
        # the winning lane is reported in best_lane.
        "metric": "decode_tok_s_llama1b_bs8_pallas",
        "best_lane": quant_tag,
        "value": round(best, 2),
        "unit": f"tokens/s (aggregate, batch=8, {mode})",
        # Like-for-like: per-stream rate vs the reference's single-stream 93.
        "vs_baseline": round(best / batch / BASELINE_TOK_S, 3),
        "vs_baseline_aggregate": round(best / BASELINE_TOK_S, 3),
        "per_stream_tok_s": round(best / batch, 2),
        "model": cfg.name,
        "sync_tok_s": _r(pallas_tok_s),
        "chained_tok_s": _r(pallas_chained),
        "dense_tok_s": _r(dense_tok_s),
        "dense_chained_tok_s": _r(dense_chained),
        "int8_tok_s": round(int8_tok_s, 2),
        "int8_chained_tok_s": round(int8_chained, 2),
        # Mode-matched kernel comparisons (sync/sync and chained/chained).
        "pallas_speedup_vs_dense_sync": _ratio(pallas_tok_s, dense_tok_s),
        "pallas_speedup_vs_dense_chained": _ratio(pallas_chained,
                                                  dense_chained),
        "int8_speedup_vs_bf16": _ratio(best_int8, best_bf16 or None),
        "mfu": mfu,
        "hbm_util": hbm_util,
        "bf16_tok_s": _r(best_bf16) if bf16_fits else None,
        "bf16_mfu": mfu_bf16,
        "bf16_hbm_util": hbm_util_bf16,
        "weight_bytes_bf16": weight_bytes,
        "weight_bytes_int8": int8_weight_bytes,
        "mean_ctx": round(mean_ctx, 1),
        "chip": jax.devices()[0].device_kind,
        "platform": platform,
        "backends_token_equal": (dense_head == pallas_head
                                 if bf16_fits else None),
    }))


def _supervise() -> None:
    """Watchdog: run the measurement in a CHILD process with a hard
    timeout + retries. The TPU tunnel's failure mode is a HANG (a dead
    relay blocks ``import jax`` inside the axon plugin registration), so
    an in-process try/except can never fire — only killing the process
    works."""
    import subprocess

    attempts = 3
    for i in range(attempts):
        try:
            rc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                timeout=1200).returncode
        except subprocess.TimeoutExpired:
            rc = -1
            print(f"[bench] attempt {i + 1} timed out (hung TPU tunnel?)",
                  file=sys.stderr)
        if rc == 0:
            return
        if i + 1 == attempts:
            print(json.dumps({"metric": "decode_tok_s_llama1b_bs8_pallas",
                              "value": None, "unit": "tokens/s",
                              "vs_baseline": None,
                              "error": f"all {attempts} attempts failed "
                                       f"(last rc={rc})"}))
            sys.exit(1)
        wait = 20 * (i + 1)
        print(f"[bench] attempt {i + 1} failed (rc={rc}); retrying in "
              f"{wait}s", file=sys.stderr)
        time.sleep(wait)


if __name__ == "__main__":
    if "--child" in sys.argv:
        try:
            main()
        except Exception:  # noqa: BLE001 — parent retries
            traceback.print_exc()
            sys.exit(2)
    else:
        _supervise()
